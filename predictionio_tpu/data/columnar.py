"""Columnar event batches: the bulk-write wire format (ISSUE 7).

One JSON object of parallel arrays instead of n event objects:

    {"event":            "rate" | [...n],
     "entityType":       "user" | [...n],
     "entityId":         [...n],                     (required, the anchor)
     "targetEntityType": str | [...n] | null,
     "targetEntityId":   [...n] | null,
     "properties":       [{...} ...n] | null,
     "eventTime":        iso8601 | [...n] | null,    (null = server now)
     "eventId":          [...n] | null}              (null = server mints)

Scalars broadcast to every row — the usual bulk shapes ("all $set item
events", "all rate events at ingest time") serialize the constant
columns ONCE, which is most of why this parses ~5x faster than the
per-event object array of /batch/events.json. `ColumnarBatch` is the
normalized form every consumer shares: the event-server write route
validates rows against the same `EventValidation` rules as the object
routes (deterministic rejections stay per-record 4xxs), backends get
pre-validated columns, and `Events.insert_columnar`'s default
materializes `Event` objects for backends without a columnar fast
path.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from predictionio_tpu.data.event import (Event, EventValidation,
                                         format_event_time,
                                         parse_event_time, utcnow)

#: columns that may be a scalar (broadcast) or a per-row list
_BROADCAST = ("event", "entity_type", "target_entity_type", "event_time")

_WIRE_KEYS = {
    "event": "event",
    "entityType": "entity_type",
    "entityId": "entity_id",
    "targetEntityType": "target_entity_type",
    "targetEntityId": "target_entity_id",
    "properties": "properties",
    "eventTime": "event_time",
    "eventId": "event_id",
}


class ColumnarBatch:
    """Normalized parallel-array event batch. Columns are either a
    per-row list of length ``n``, a scalar broadcast to every row, or
    ``None`` (column absent). ``entity_id`` is always a list — it
    anchors ``n``."""

    __slots__ = ("n", "event", "entity_type", "entity_id",
                 "target_entity_type", "target_entity_id", "properties",
                 "event_time", "event_id", "minted")

    def __init__(self, n, event, entity_type, entity_id,
                 target_entity_type=None, target_entity_id=None,
                 properties=None, event_time=None, event_id=None,
                 minted=False):
        self.n = n
        self.event = event
        self.entity_type = entity_type
        self.entity_id = entity_id
        self.target_entity_type = target_entity_type
        self.target_entity_id = target_entity_id
        self.properties = properties
        self.event_time = event_time
        self.event_id = event_id
        #: True when event_id was minted BY US (server pre-mint for
        #: spill replay): ids are fresh distinct lowercase hex, so
        #: backends may keep their minted-id fast paths (no escaping,
        #: no dedup pass, no overwrite probing). Never set for ids
        #: that arrived over the wire.
        self.minted = minted

    # -- row access ---------------------------------------------------------
    def cell(self, name: str, i: int):
        col = getattr(self, name)
        if col is None or isinstance(col, str):
            return col            # absent or broadcast scalar
        return col[i]

    def row_event(self, i: int, default_time=None) -> Event:
        """Materialize row ``i`` as an ``Event`` (the slow/fallback
        path: base-class ``insert_columnar`` and the spill WAL)."""
        from predictionio_tpu.data.datamap import DataMap
        t = self.cell("event_time", i)
        props = None if self.properties is None else self.properties[i]
        kwargs = {}
        if self.event_id is not None:
            kwargs["event_id"] = self.event_id[i]
        return Event(
            event=self.cell("event", i),
            entity_type=self.cell("entity_type", i),
            entity_id=self.entity_id[i],
            target_entity_type=self.cell("target_entity_type", i) or None,
            target_entity_id=(None if self.target_entity_id is None
                              else self.target_entity_id[i] or None),
            properties=DataMap(props or {}),
            event_time=(parse_event_time(t) if t
                        else (default_time or utcnow())),
            **kwargs)

    def to_events(self) -> List[Event]:
        now = utcnow()
        return [self.row_event(i, default_time=now)
                for i in range(self.n)]

    def to_wire(self) -> dict:
        """The JSON wire body for this batch (the remote events DAO
        forwards ``insert_columnar`` as one POST)."""
        d = {}
        for wire, attr in _WIRE_KEYS.items():
            v = getattr(self, attr)
            if v is not None:
                d[wire] = v
        return d

    def slice_rows(self, lo: int, hi: int) -> "ColumnarBatch":
        """Rows [lo, hi) as a new batch — C-level list slices, so the
        nativelog pipelined bulk writer can sub-batch cheaply."""

        def cut(col):
            if col is None or isinstance(col, str):
                return col
            return col[lo:hi]

        return ColumnarBatch(
            hi - lo, cut(self.event), cut(self.entity_type),
            self.entity_id[lo:hi], cut(self.target_entity_type),
            cut(self.target_entity_id), cut(self.properties),
            cut(self.event_time), cut(self.event_id), minted=self.minted)

    def select(self, keep: Sequence[int]) -> "ColumnarBatch":
        """A new batch holding only the ``keep`` rows (the write route
        compacts away per-record rejections before the bulk insert)."""

        def pick(col):
            if col is None or isinstance(col, str):
                return col
            return [col[i] for i in keep]

        return ColumnarBatch(
            len(keep), pick(self.event), pick(self.entity_type),
            pick(self.entity_id), pick(self.target_entity_type),
            pick(self.target_entity_id), pick(self.properties),
            pick(self.event_time), pick(self.event_id),
            minted=self.minted)


def events_to_wire(events: Sequence[Event]) -> dict:
    """The columnar wire body for a list of ``Event`` objects — the
    client's ``bulk_create`` and the spill replayer's batch drain.
    Name/type columns that turn out constant collapse to broadcast
    scalars (all-absent target columns drop entirely), recovering the
    one-copy wire size the format exists for. Ids are included when
    every event carries one (pre-assigned for replay idempotency);
    otherwise the server mints."""

    def collapse(col, required):
        vals = set(col)
        if len(vals) == 1:
            v = col[0]
            return v if (v or required) else None
        return col

    d = {
        "event": collapse([e.event for e in events], True),
        "entityType": collapse([e.entity_type for e in events], True),
        "entityId": [e.entity_id for e in events],
        "targetEntityType": collapse(
            [e.target_entity_type for e in events], False),
        "targetEntityId": [e.target_entity_id or "" for e in events],
        "properties": [e.properties.fields if e.properties else {}
                       for e in events],
        "eventTime": [format_event_time(e.event_time) for e in events],
    }
    if d["targetEntityType"] is None:
        del d["targetEntityType"], d["targetEntityId"]
    ids = [e.event_id for e in events]
    if all(ids):
        d["eventId"] = ids
    return d


def _as_column(value, n: int, key: str, broadcast: bool):
    """A wire value as a normalized column: list (checked to length n),
    scalar (broadcast allowed), or None."""
    if value is None:
        return None
    if isinstance(value, list):
        if len(value) != n:
            raise ValueError(
                f"column {key} has {len(value)} rows; entityId has {n}")
        return value
    if broadcast:
        return value
    raise ValueError(f"column {key} must be an array")


def normalize_columnar(d: dict) -> ColumnarBatch:
    """Parse + shape-check one columnar wire body. Raises ValueError on
    a malformed TABLE (wrong shapes, missing required columns) — those
    reject the whole request; per-ROW problems are left to
    ``validate_rows`` so they can 4xx individually."""
    if not isinstance(d, dict):
        raise ValueError("columnar body must be a JSON object")
    unknown = set(d) - set(_WIRE_KEYS) - {"returnIds"}
    if unknown:
        raise ValueError(
            f"unknown columnar key(s): {', '.join(sorted(unknown))}")
    ids = d.get("entityId")
    if not isinstance(ids, list):
        raise ValueError("entityId must be an array (it anchors the "
                         "batch length)")
    n = len(ids)
    if d.get("event") is None:
        raise ValueError("field event is required")
    if d.get("entityType") is None:
        raise ValueError("field entityType is required")
    cols = {}
    for wire, attr in _WIRE_KEYS.items():
        if attr == "entity_id":
            continue
        cols[attr] = _as_column(d.get(wire), n, wire,
                                broadcast=attr in _BROADCAST)
    # ids arrive as strings on every path; numbers coerce like the
    # object route's Event.from_dict (entityId str(...) coercion)
    ids = [x if isinstance(x, str) else str(x) for x in ids]
    tids = cols["target_entity_id"]
    if tids is not None:
        cols["target_entity_id"] = [
            x if isinstance(x, str) or x is None else str(x)
            for x in tids]
    eids = cols["event_id"]
    if eids is not None:
        # same str coercion as the id columns: a numeric cell would
        # otherwise reach nativelog's ASCII encoder as an int —
        # TypeError → 500 — while sqlite would silently store the int
        cols["event_id"] = [
            x if isinstance(x, str) or x is None else str(x)
            for x in eids]
    return ColumnarBatch(n, cols["event"], cols["entity_type"], ids,
                         cols["target_entity_type"],
                         cols["target_entity_id"], cols["properties"],
                         cols["event_time"], cols["event_id"])


def validate_rows(b: ColumnarBatch,
                  allowed_events=None) -> Tuple[Optional[list], list]:
    """Apply the object routes' ``EventValidation`` rules per row.

    Returns ``(keep, failures)``: ``keep`` is None when every row
    passed (the hot path — no index list is materialized), else the
    row indexes to insert; ``failures`` is ``[(index, status, message)]``
    for the per-record 4xxs. Broadcast columns are validated ONCE —
    a scalar "event": "rate" costs one reserved-name check for the
    whole batch, not n."""
    ev = EventValidation

    def name_err(name) -> Optional[Tuple[int, str]]:
        if not name:
            return 400, "event must not be empty."
        if allowed_events and name not in allowed_events:
            return 403, f"{name} events are not allowed"
        if ev.is_reserved_prefix(name) and not ev.is_special_event(name):
            return 400, f"{name} is not a supported reserved event name."
        return None

    def etype_err(t) -> Optional[Tuple[int, str]]:
        if not t:
            return 400, "entityType must not be empty string."
        if ev.is_reserved_prefix(t) and not ev.is_builtin_entity_type(t):
            return (400, f"The entityType {t} is not allowed. "
                         "'pio_' is a reserved name prefix.")
        return None

    def ttype_err(t) -> Optional[Tuple[int, str]]:
        if t and ev.is_reserved_prefix(t) \
                and not ev.is_builtin_entity_type(t):
            return (400, f"The targetEntityType {t} is not allowed. "
                         "'pio_' is a reserved name prefix.")
        return None

    # broadcast-column checks run once; a bad scalar fails the whole
    # batch deterministically (every row would fail identically)
    for col, check in ((b.event, name_err), (b.entity_type, etype_err),
                       (b.target_entity_type, ttype_err)):
        if isinstance(col, str):
            err = check(col)
            if err is not None:
                if err[0] == 403:
                    raise PermissionError(err[1])
                raise ValueError(err[1])
    # eventTime cells must parse HERE, per row: a malformed timestamp
    # that only surfaced at insert time would 400 the whole request
    # after the pipelined nativelog path already committed earlier
    # chunks — a retry then duplicates them under fresh minted ids
    et = b.event_time
    bad_times: Optional[set] = None
    if isinstance(et, str):
        try:
            parse_event_time(et)
        except ValueError:
            raise ValueError(f"eventTime {et!r} is not an ISO-8601 "
                             "timestamp")
    elif et is not None:
        bad = set()
        for i, x in enumerate(et):
            if x:
                try:
                    parse_event_time(x)
                except ValueError:
                    bad.add(i)
        bad_times = bad or None

    scalar_event = isinstance(b.event, str)
    scalar_special = scalar_event and ev.is_special_event(b.event)
    scalar_etype = isinstance(b.entity_type, str)
    scalar_ttype = isinstance(b.target_entity_type, str) \
        or b.target_entity_type is None
    tids = b.target_entity_id
    # -- whole-column happy path: with broadcast name/type columns the
    # only per-row hazards are empty ids, broken target pairing, and
    # bad property cells — each disproved by one C-speed pass (all(),
    # set(map(type,...)), one set.union over every props dict).
    # Anything suspicious falls through to the per-row loop, which
    # produces the exact row indexes for the 4xxs.
    if scalar_event and scalar_etype and scalar_ttype \
            and bad_times is None:
        if tids is None:
            pair_ok = b.target_entity_type is None
        else:
            pair_ok = (b.target_entity_type is not None
                       and not scalar_special and all(tids))
        if pair_ok and all(b.entity_id):
            props = b.properties
            if props is None:
                if b.event != "$unset":
                    return None, []
            else:
                tps = set(map(type, props))
                keys = None
                if tps == {dict}:
                    keys = set().union(*props)
                elif tps <= {dict, type(None)}:
                    keys = set().union(*(p for p in props if p))
                if keys is not None and not any(
                        ev.is_reserved_prefix(k)
                        and k not in ev.BUILTIN_PROPERTIES
                        for k in keys):
                    if b.event != "$unset" or all(props):
                        return None, []
    failures: list = []
    keep: Optional[list] = None

    def fail(i, status, msg):
        nonlocal keep
        if keep is None:
            keep = list(range(i))
        failures.append((i, status, msg))

    ttype_scalar_set = isinstance(b.target_entity_type, str)
    for i in range(b.n):
        err = None
        if not scalar_event:
            err = name_err(b.event[i])
        if err is None and not scalar_etype:
            err = etype_err(b.entity_type[i])
        if err is None and not b.entity_id[i]:
            err = 400, "entityId must not be empty string."
        if err is None:
            tid = tids[i] if tids is not None else None
            ttype = b.target_entity_type if ttype_scalar_set else (
                b.target_entity_type[i] if b.target_entity_type else None)
            if not scalar_ttype:
                err = ttype_err(ttype)
            if err is None and bool(tid) != bool(ttype):
                err = (400, "targetEntityType and targetEntityId must "
                            "be specified together.")
            if err is None and tid:
                special = (scalar_special if scalar_event
                           else ev.is_special_event(b.event[i]))
                if special:
                    name = b.event if scalar_event else b.event[i]
                    err = (400, f"Reserved event {name} cannot have "
                                "targetEntity")
        if err is None and b.properties is not None:
            props = b.properties[i]
            if props is not None and not isinstance(props, dict):
                err = 400, "field properties must be a JSON object"
            elif props:
                for k in props:
                    if ev.is_reserved_prefix(k) \
                            and k not in ev.BUILTIN_PROPERTIES:
                        err = (400, f"The property {k} is not allowed. "
                                    "'pio_' is a reserved name prefix.")
                        break
        if err is None and bad_times is not None and i in bad_times:
            err = (400, f"eventTime {et[i]!r} is not an ISO-8601 "
                        "timestamp")
        if err is None:
            name = b.event if scalar_event else b.event[i]
            if name == "$unset" and not (b.properties is not None
                                         and b.properties[i]):
                err = 400, "properties cannot be empty for $unset event"
        if err is not None:
            fail(i, *err)
        elif keep is not None:
            keep.append(i)
    return keep, failures
