"""MailChimp form webhook connector.

Rebuilds the reference connector (reference:
data/src/main/scala/io/prediction/data/webhooks/mailchimp/
MailChimpConnector.scala): subscribe/unsubscribe/profile/upemail/cleaned/
campaign form payloads -> events. MailChimp timestamps are
"yyyy-MM-dd HH:mm:ss" in UTC.
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict

from predictionio_tpu.data.event import UTC, format_event_time
from predictionio_tpu.data.webhooks.base import (ConnectorException,
                                                 FormConnector)


def _parse_time(s: str) -> str:
    t = _dt.datetime.strptime(s, "%Y-%m-%d %H:%M:%S").replace(tzinfo=UTC)
    return format_event_time(t)


class MailChimpConnector(FormConnector):
    def to_event_dict(self, form: Dict[str, str]) -> dict:
        typ = form.get("type")
        if typ is None:
            raise ConnectorException(
                "The field 'type' is required for MailChimp data.")
        handlers = {
            "subscribe": self._subscribe,
            "unsubscribe": self._unsubscribe,
            "profile": self._profile,
            "upemail": self._upemail,
            "cleaned": self._cleaned,
            "campaign": self._campaign,
        }
        if typ not in handlers:
            raise ConnectorException(
                f"Cannot convert unknown MailChimp data type {typ} to "
                "event JSON")
        return handlers[typ](form)

    @staticmethod
    def _req(form, key):
        if key not in form:
            raise ConnectorException(f"missing field {key}")
        return form[key]

    @classmethod
    def _merges(cls, form) -> dict:
        merges = {
            "EMAIL": cls._req(form, "data[merges][EMAIL]"),
            "FNAME": cls._req(form, "data[merges][FNAME]"),
            "LNAME": cls._req(form, "data[merges][LNAME]"),
        }
        if "data[merges][INTERESTS]" in form:
            merges["INTERESTS"] = form["data[merges][INTERESTS]"]
        return merges

    def _subscribe(self, form):
        return {
            "event": "subscribe", "entityType": "user",
            "entityId": self._req(form, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": self._req(form, "data[list_id]"),
            "eventTime": _parse_time(self._req(form, "fired_at")),
            "properties": {
                "email": self._req(form, "data[email]"),
                "email_type": self._req(form, "data[email_type]"),
                "merges": self._merges(form),
                "ip_opt": self._req(form, "data[ip_opt]"),
                "ip_signup": self._req(form, "data[ip_signup]"),
            }}

    def _unsubscribe(self, form):
        return {
            "event": "unsubscribe", "entityType": "user",
            "entityId": self._req(form, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": self._req(form, "data[list_id]"),
            "eventTime": _parse_time(self._req(form, "fired_at")),
            "properties": {
                "action": self._req(form, "data[action]"),
                "reason": self._req(form, "data[reason]"),
                "email": self._req(form, "data[email]"),
                "email_type": self._req(form, "data[email_type]"),
                "merges": self._merges(form),
                "ip_opt": self._req(form, "data[ip_opt]"),
                "campaign_id": self._req(form, "data[campaign_id]"),
            }}

    def _profile(self, form):
        return {
            "event": "profile", "entityType": "user",
            "entityId": self._req(form, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": self._req(form, "data[list_id]"),
            "eventTime": _parse_time(self._req(form, "fired_at")),
            "properties": {
                "email": self._req(form, "data[email]"),
                "email_type": self._req(form, "data[email_type]"),
                "merges": self._merges(form),
                "ip_opt": self._req(form, "data[ip_opt]"),
            }}

    def _upemail(self, form):
        return {
            "event": "upemail", "entityType": "user",
            "entityId": self._req(form, "data[new_id]"),
            "targetEntityType": "list",
            "targetEntityId": self._req(form, "data[list_id]"),
            "eventTime": _parse_time(self._req(form, "fired_at")),
            "properties": {
                "new_email": self._req(form, "data[new_email]"),
                "old_email": self._req(form, "data[old_email]"),
            }}

    def _cleaned(self, form):
        return {
            "event": "cleaned", "entityType": "list",
            "entityId": self._req(form, "data[list_id]"),
            "eventTime": _parse_time(self._req(form, "fired_at")),
            "properties": {
                "campaignId": self._req(form, "data[campaign_id]"),
                "reason": self._req(form, "data[reason]"),
                "email": self._req(form, "data[email]"),
            }}

    def _campaign(self, form):
        return {
            "event": "campaign", "entityType": "campaign",
            "entityId": self._req(form, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": self._req(form, "data[list_id]"),
            "eventTime": _parse_time(self._req(form, "fired_at")),
            "properties": {
                "subject": self._req(form, "data[subject]"),
                "status": self._req(form, "data[status]"),
                "reason": self._req(form, "data[reason]"),
            }}
