"""Connector interfaces (reference: data/webhooks/{JsonConnector,
FormConnector}.scala, ConnectorUtil.scala toEvent)."""

from __future__ import annotations

import abc
from typing import Dict, Optional

from predictionio_tpu.data.event import Event


class ConnectorException(ValueError):
    pass


class JsonConnector(abc.ABC):
    @abc.abstractmethod
    def to_event_dict(self, data: dict) -> dict:
        """Third-party JSON -> event JSON dict."""

    def to_event(self, data: dict) -> Event:
        return Event.from_dict(self.to_event_dict(data))


class FormConnector(abc.ABC):
    @abc.abstractmethod
    def to_event_dict(self, form: Dict[str, str]) -> dict:
        """Form fields -> event JSON dict."""

    def to_event(self, form: Dict[str, str]) -> Event:
        return Event.from_dict(self.to_event_dict(form))


class ConnectorRegistry:
    def __init__(self):
        self._json: Dict[str, JsonConnector] = {}
        self._form: Dict[str, FormConnector] = {}

    def register_json(self, name: str, connector: JsonConnector):
        self._json[name] = connector

    def register_form(self, name: str, connector: FormConnector):
        self._form[name] = connector

    def get_json(self, name: str) -> Optional[JsonConnector]:
        return self._json.get(name)

    def get_form(self, name: str) -> Optional[FormConnector]:
        return self._form.get(name)
