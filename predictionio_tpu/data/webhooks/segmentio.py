"""segment.io JSON webhook connector.

Rebuilds the reference connector (reference:
data/src/main/scala/io/prediction/data/webhooks/segmentio/
SegmentIOConnector.scala): maps identify/track/alias/page/screen/group
payloads to events keyed by userId (falling back to anonymousId), carrying
type-specific properties plus optional `context`.
"""

from __future__ import annotations

from predictionio_tpu.data.webhooks.base import (ConnectorException,
                                                 JsonConnector)


class SegmentIOConnector(JsonConnector):
    SUPPORTED = ("identify", "track", "alias", "page", "screen", "group")

    def to_event_dict(self, data: dict) -> dict:
        typ = data.get("type")
        if typ is None:
            raise ConnectorException(
                f"Cannot extract Common field from {data}.")
        if typ not in self.SUPPORTED:
            raise ConnectorException(
                f"Cannot convert unknown type {typ} to event JSON.")
        user_id = data.get("userId") or data.get("anonymousId")
        if not user_id:
            raise ConnectorException(
                "there was no `userId` or `anonymousId` in the common "
                "fields.")
        props = self._event_properties(typ, data)
        if "context" in data and data["context"] is not None:
            props = {"context": data["context"], **props}
        out = {
            "event": typ,
            "entityType": "user",
            "entityId": user_id,
            "properties": props,
        }
        if data.get("timestamp"):
            out["eventTime"] = data["timestamp"]
        return out

    @staticmethod
    def _event_properties(typ: str, data: dict) -> dict:
        def req(key):
            if key not in data:
                raise ConnectorException(
                    f"Cannot convert {data} to event JSON. missing {key}")
            return data[key]

        if typ == "identify":
            req("userId")
            return {"traits": data.get("traits")}
        if typ == "track":
            return {"properties": data.get("properties"),
                    "event": req("event")}
        if typ == "alias":
            return {"previousId": req("previousId")}
        if typ == "page":
            return {"name": req("name"),
                    "properties": data.get("properties")}
        if typ == "screen":
            return {"name": req("name"),
                    "properties": data.get("properties")}
        if typ == "group":
            return {"groupId": req("groupId"),
                    "traits": data.get("traits")}
        raise ConnectorException(f"unhandled type {typ}")
