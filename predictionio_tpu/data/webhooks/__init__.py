"""Webhook connectors: third-party payloads -> events.

Rebuilds the reference's webhook framework (reference:
data/src/main/scala/io/prediction/data/webhooks/{JsonConnector,FormConnector,
ConnectorUtil}.scala and the registry api/WebhooksConnectors.scala:34 —
segment.io as the JSON connector, MailChimp as the form connector).
"""

from predictionio_tpu.data.webhooks.base import (ConnectorException,
                                                 ConnectorRegistry,
                                                 FormConnector,
                                                 JsonConnector)


def default_connectors() -> ConnectorRegistry:
    from predictionio_tpu.data.webhooks.segmentio import SegmentIOConnector
    from predictionio_tpu.data.webhooks.mailchimp import MailChimpConnector
    reg = ConnectorRegistry()
    reg.register_json("segmentio", SegmentIOConnector())
    reg.register_form("mailchimp", MailChimpConnector())
    return reg


__all__ = ["ConnectorException", "ConnectorRegistry", "FormConnector",
           "JsonConnector", "default_connectors"]
