"""The Event Server: REST event collection into the event store.

Rebuilds the reference's Spray event server
(reference: data/src/main/scala/io/prediction/data/api/EventServer.scala:112-460):
  GET  /                       -> {"status": "alive"}
  POST /events.json            -> 201 {"eventId": id}
  GET  /events/<id>.json       -> event JSON | 404
  DELETE /events/<id>.json     -> {"message": "Found"} | 404
  GET  /events.json            -> query events (filters as query params)
  POST /batch/events.json      -> per-event status array (max 50)
  GET  /stats.json             -> bookkeeping counters (opt-in --stats)
  POST /webhooks/<name>.json   -> JSON webhook connectors
  POST /webhooks/<name>        -> form webhook connectors

Auth (EventServer.scala:81-107): accessKey query param or HTTP Basic
username; an AccessKey row with a non-empty `events` list whitelists which
event names it may write. `channel` param scopes to a named channel.
"""

from __future__ import annotations

import base64
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

from predictionio_tpu.data.api.stats import Stats
from predictionio_tpu.data.event import (Event, EventValidation,
                                         parse_event_time)
from predictionio_tpu.data.storage.base import ABSENT
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.obs import (FLIGHT, MetricsRegistry, SLOEngine,
                                  TRACER, default_event_specs, fleet,
                                  flight_response, get_incidents,
                                  get_registry, health_response,
                                  ingress_trace_kwargs, traces_response)
from predictionio_tpu.utils.http import HttpServer, Request, Response, Router

logger = logging.getLogger(__name__)

#: default /batch/events.json cap — the reference EventServer.scala
#: limit, kept as the default for wire compat (--max-batch raises it)
MAX_BATCH_SIZE = 50


@dataclass
class EventServerConfig:
    ip: str = "0.0.0.0"
    port: int = 7070
    stats: bool = False
    # /batch/events.json cap (`pio eventserver --max-batch`). The
    # columnar bulk-write route has its own, much larger bound below —
    # one parallel-array body amortizes parsing, so the Scala-era 50
    # would defeat its purpose.
    max_batch: int = MAX_BATCH_SIZE
    max_columnar_rows: int = 1_000_000
    # durable ingest spill (ISSUE 3): when the event-store write fails
    # or its circuit breaker is open, accepted events append to a local
    # WAL and ACK 201 {"spilled": true}; a background replayer drains
    # the WAL into the primary on recovery (order-preserving, id-deduped)
    spill: bool = True
    spill_dir: Optional[str] = None   # default <PIO_FS_BASEDIR>/ingest_spill
    # event-store breaker: consecutive write failures before failing
    # fast (straight to the WAL), and the open->half-open probe delay
    breaker_failures: int = 5
    breaker_reset_s: float = 5.0


class _IngestBatcher:
    """Admission micro-batcher for single-event ingest (ISSUE 7
    tentpole, L1 half). Under concurrent load each request thread pays
    a GIL round trip at every blocking point of its own storage write
    — measured as the residual concurrent-8 < serial inversion after
    the storage convoy itself was fixed. Here request threads instead
    enqueue their validated event and block at most once: a LEADER —
    the arrival that completes the group, or the earliest follower
    whose formation wait expires — drains everything queued into one
    resilient ``insert_batch`` per (app, channel) on its own request
    thread: one storage round trip, one group commit, one batched
    wakeup, and no relay thread at all (the leader writes its own
    response with zero handoffs) — the event-server port of the
    nativelog group committer's leader/follower design, and the same
    shape as the serving plane's MicroBatcher.

    Serial traffic never pays the relay: ``submit`` runs the insert
    inline whenever no other ingest is in flight, so an idle server's
    single-event latency is byte-identical to the direct path. The
    durability contract is unchanged — the ack still happens only
    after the group's flush (or its WAL spill)."""

    class _Slot:
        __slots__ = ("done", "result", "error")

        def __init__(self):
            self.done = threading.Event()
            self.result = None
            self.error: Optional[BaseException] = None

    def __init__(self, server: "EventServer"):
        self.server = server
        self._cv = threading.Condition(threading.Lock())
        self._queue: list = []
        # ingest REQUESTS currently being handled (enter() at route
        # entry, exit() at response). The solo/batched decision keys on
        # this, not on threads inside submit(): request threads are
        # GIL-staggered, so at any instant usually at most one is
        # between parse and insert — a submit-scoped count reads
        # "solo" under heavy concurrency and defeats the batcher.
        self._ingress = 0
        # group-formation budget: how long a FOLLOWER waits for a
        # group-completing arrival to lead it before claiming
        # leadership itself. Its own knob, NOT tied to the storage
        # fsync cadence. With leadership usually triggered by the
        # arrival that completes the group, this is a straggler bound,
        # not the formation mechanism — an interleaved A/B sweep on a
        # 2-core box: conc8/serial 0.93-0.99 at 0 ms, 1.06-1.26 at
        # 1 ms, ~1.0 at 2-8 ms (long waits idle the server between
        # group completion and commit). Serial traffic never enters
        # the batcher at all.
        try:
            ms = float(os.environ.get("PIO_INGEST_ADMISSION_WAIT_MS",
                                      "1"))
        except (TypeError, ValueError):
            ms = 1.0
        self._wait_s = min(max(ms / 1000.0, 0.0), 0.020)
        self._h_group = server.metrics.histogram(
            "pio_ingest_admission_group_size",
            "Events per admission-batcher dispatch (1 = inline path)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128))

    def enter(self):
        with self._cv:
            self._ingress += 1

    def exit(self):
        with self._cv:
            self._ingress -= 1

    def submit(self, event, app_id, channel_id):
        """Land one event; returns ``(event_id, spilled)`` or raises
        the insert's error (deterministic rejections keep their 4xx)."""
        batch = None
        with self._cv:
            solo = self._ingress <= 1 and not self._queue
            if not solo:
                slot = self._Slot()
                self._queue.append((event, app_id, channel_id, slot))
                if len(self._queue) >= self._ingress:
                    # this arrival completes the group: lead it.
                    # (``_ingress`` overcounts requests already
                    # writing their response, so under sustained load
                    # leadership usually falls to the timed-out
                    # follower below instead.)
                    batch, self._queue = self._queue, []
        if solo:
            self._h_group.observe(1)
            return self.server._resilient_insert(event, app_id,
                                                 channel_id)
        if batch is None and not slot.done.wait(self._wait_s):
            # formation budget expired with no leader landing us: claim
            # whatever queued (our own slot included, unless a leader
            # grabbed it between the wait and the lock — then the queue
            # holds only later stragglers, which ride with us anyway)
            with self._cv:
                if not slot.done.is_set() and self._queue:
                    batch, self._queue = self._queue, []
        if batch is not None:
            self._dispatch(batch)
        slot.done.wait()
        if slot.error is not None:
            raise slot.error
        return slot.result

    def _dispatch(self, batch):
        """Leader: land every queued submission in one resilient
        ``insert_batch`` per (app, channel) and wake the followers.
        Runs on the leading request's own thread — there is no relay
        thread, so the leader's response costs zero handoffs."""
        groups: dict = {}
        for ev, app, chan, slot in batch:
            groups.setdefault((app, chan), []).append((ev, slot))
        for (app, chan), items in groups.items():
            self._h_group.observe(len(items))
            try:
                res = self.server._resilient_insert_batch(
                    [ev for ev, _ in items], app, chan)
                for (_, slot), r in zip(items, res):
                    slot.result = r
            except BaseException as e:   # waiters must never hang
                for _, slot in items:
                    slot.error = e
            for _, slot in items:
                slot.done.set()


class EventServer:
    def __init__(self, config: EventServerConfig = EventServerConfig(),
                 access_keys=None, channels=None, events=None,
                 webhook_connectors=None, plugin_context=None):
        self.config = config
        self._access_keys = access_keys
        self._channels = channels
        self._events = events
        self.stats = Stats()
        if webhook_connectors is None:
            from predictionio_tpu.data.webhooks import default_connectors
            webhook_connectors = default_connectors()
        self.webhook_connectors = webhook_connectors
        if plugin_context is None:
            from predictionio_tpu.data.api.plugins import \
                EventServerPluginContext
            plugin_context = EventServerPluginContext.load_from_env()
        self.plugin_context = plugin_context
        # short-TTL access-key cache: the auth lookup otherwise hits the
        # metadata store on EVERY request (profiled at ~5% of the single-
        # event ingest loop; the reference pays the same per-request DAO
        # round trip — EventServer.scala:81-107). Revocation/creation
        # takes effect within the TTL; PIO_ACCESSKEY_CACHE_S=0 disables.
        try:
            self.auth_cache_ttl_s = float(
                os.environ.get("PIO_ACCESSKEY_CACHE_S", "3.0"))
        except ValueError:
            logger.warning(
                "PIO_ACCESSKEY_CACHE_S=%r is not a number; using the "
                "3.0s default",
                os.environ.get("PIO_ACCESSKEY_CACHE_S"))
            self.auth_cache_ttl_s = 3.0
        self._auth_cache: dict = {}
        # ISSUE 2: this server's metrics registry (chained onto the
        # process-wide one). The window counters keep Stats as their
        # single source of truth and are sampled via func collectors;
        # the event-write latency distribution is a native histogram.
        from predictionio_tpu.obs import jaxmon
        jaxmon.install()
        self.metrics = MetricsRegistry(parent=get_registry())
        self._h_write = self.metrics.histogram(
            "pio_event_write_seconds",
            "Event-store write latency per accepted event")
        self._window_pin = None
        # ISSUE 3 resilience: breaker over the event-store write path +
        # lazy spill WAL (created on first spill, or adopted at start()
        # when a prior process left an undrained one)
        from predictionio_tpu.resilience import CircuitBreaker
        self.breaker = CircuitBreaker(
            "event_store", failure_threshold=config.breaker_failures,
            reset_timeout_s=config.breaker_reset_s)
        self._wal = None
        self._replayer = None
        self._wal_lock = threading.Lock()
        self.spilled_count = 0
        self.metrics.counter_func(
            "pio_ingest_spilled_total",
            "Accepted events diverted to the spill WAL",
            lambda: self.spilled_count)
        self.metrics.gauge_func(
            "pio_ingest_spill_pending_bytes",
            "Un-replayed bytes in the spill WAL",
            lambda: (self._wal.pending_bytes() if self._wal else 0))
        # diagnostics plane (ISSUE 6): flight-recorder metric context
        # from this server's families, burn-rate SLOs at /health.json,
        # and WAL/quarantine state frozen into incident bundles
        FLIGHT.add_source(self.metrics)
        self.slo = SLOEngine(default_event_specs(),
                             registries=[self.metrics])
        get_incidents().register_provider("ingest_wal",
                                          self._incident_state)
        # ISSUE 7: admission micro-batcher for concurrent single-event
        # ingest (inline when traffic is serial)
        self._batcher = _IngestBatcher(self)
        # fleet member record id (ISSUE 13), set by start()'s on_bound
        self._fleet_id: Optional[str] = None
        self._register_metrics()
        self.router = self._build_router()
        self.server: Optional[HttpServer] = None

    def _register_metrics(self):
        m = self.metrics

        def window(field):
            return self._window_snapshot()["currentWindow"][field]

        m.gauge_func(
            "pio_event_window_start_seconds",
            "Start of the current counter window (unix time)",
            lambda: self._window_snapshot()["startTime"])
        m.gauge_func(
            "pio_event_window_events",
            "Events accepted in the current window, by event name",
            lambda: [({"event": k}, v) for k, v in
                     sorted(window("byEvent").items())] or [(None, 0)])
        m.gauge_func(
            "pio_event_window_statuses",
            "Responses in the current window, by HTTP status",
            lambda: [({"status": k}, v) for k, v in
                     sorted(window("byStatus").items())] or [(None, 0)])

    def _window_snapshot(self) -> dict:
        """One Stats snapshot shared by the three window collectors
        within a single /metrics render: _metrics pins it for the
        render's duration so an hourly rotation landing mid-scrape
        can't pair the fresh window's start time with the old window's
        counts. Outside a render (direct collect()), falls through to
        a live read."""
        pinned = getattr(self, "_window_pin", None)
        return pinned if pinned is not None else self.stats.to_dict(None)

    # DAOs resolved lazily so env/registry changes are respected
    @property
    def access_keys(self):
        return self._access_keys or Storage.get_meta_data_access_keys()

    @property
    def channels(self):
        return self._channels or Storage.get_meta_data_channels()

    @property
    def events(self):
        return self._events or Storage.get_events()

    # -- auth (EventServer.scala:81-107) -----------------------------------
    def _authenticate(self, req: Request):
        key = req.params.get("accessKey")
        if not key:
            auth = req.headers.get("Authorization", "")
            if auth.startswith("Basic "):
                try:
                    decoded = base64.b64decode(auth[6:]).decode("utf-8")
                    key = decoded.split(":", 1)[0]
                except Exception:
                    key = None
        if not key:
            raise AuthError(401, "Missing accessKey.")
        access_key = self._cached_access_key(key)
        if access_key is None:
            raise AuthError(401, "Invalid accessKey.")
        channel_id = None
        channel_name = req.params.get("channel")
        if channel_name:
            match = [c for c in self.channels.get_by_app_id(access_key.appid)
                     if c.name == channel_name]
            if not match:
                raise AuthError(400, "Invalid channel.")
            channel_id = match[0].id
        return access_key, channel_id

    def _cached_access_key(self, key: str):
        """DAO lookup behind a TTL cache (misses cached too, so invalid
        keys can't hammer the metadata store). Dict ops are GIL-atomic;
        a racing refresh only costs a duplicate lookup."""
        ttl = self.auth_cache_ttl_s
        if ttl <= 0:
            return self.access_keys.get(key)
        now = time.monotonic()
        hit = self._auth_cache.get(key)
        if hit is not None and now - hit[1] < ttl:
            return hit[0]
        access_key = self.access_keys.get(key)
        if len(self._auth_cache) >= 1024:
            # bound growth from junk keys: FIFO-evict one (dict keeps
            # insertion order) — clearing everything would let a scanner
            # evict hot valid keys and reinstate the per-request DAO hit
            try:
                self._auth_cache.pop(next(iter(self._auth_cache)))
            except (StopIteration, KeyError):   # concurrent shrink
                pass
        self._auth_cache[key] = (access_key, now)
        return access_key

    # -- handlers -----------------------------------------------------------
    def _status(self, req: Request) -> Response:
        return Response(200, {"status": "alive"})

    def _check_event_allowed(self, access_key, event_name: str):
        if access_key.events and event_name not in access_key.events:
            raise AuthError(
                403, f"{event_name} events are not allowed")

    def _create_event(self, req: Request) -> Response:
        # the in-flight count drives the admission batcher's
        # solo-vs-batched decision and its group-formation wait
        self._batcher.enter()
        try:
            return self._create_event_inner(req)
        finally:
            self._batcher.exit()

    def _create_event_inner(self, req: Request) -> Response:
        # ingress mints the trace — unless the caller already carries
        # one (ISSUE 13): an inbound X-PIO-Trace-Id (the engine
        # server's feedback loop, a spill replay re-POST, any traced
        # upstream) is ADOPTED, so the event's ingest spans land under
        # the cross-process trace id instead of a fresh disconnected
        # one. The storage write lands here, and the scheduler's tail
        # read later links the fold tick that absorbs this event back
        # to this trace (end-to-end causality on /traces.json). The
        # response carries the trace id for log correlation.
        with TRACER.trace("event_ingest",
                          **ingress_trace_kwargs(req.headers)) as tr:
            access_key, channel_id = self._authenticate(req)
            d = req.json()
            if not isinstance(d, dict):
                raise ValueError("request body must be a JSON object")
            event = Event.from_dict(d)
            tr.root.attrs["event"] = event.event
            self._check_event_allowed(access_key, event.event)
            EventValidation.validate(event)
            # inputblocker plugins may veto (EventServer.scala:239)
            self.plugin_context.check_input(
                {"appId": access_key.appid, "channelId": channel_id,
                 "event": d})
            event_id, spilled = self._insert_traced(
                event, access_key.appid, channel_id)
            if self.config.stats:
                self.stats.update(access_key.appid, event.event,
                                  event.entity_type, 201)
            body = {"eventId": event_id, "traceId": tr.trace_id}
            if spilled:
                body["spilled"] = True
            return Response(201, body)

    def _insert_traced(self, event, app_id, channel_id):
        """Storage write under a span + the write-latency histogram,
        registering event_id -> trace_id for fold-tick linking.
        Returns ``(event_id, spilled)``."""
        with TRACER.span("storage_write") as sp:
            t0 = time.perf_counter()
            event_id, spilled = self._batcher.submit(event, app_id,
                                                     channel_id)
            self._h_write.observe(time.perf_counter() - t0)
            if sp is not None:
                sp.attrs["eventId"] = event_id
                if spilled:
                    sp.attrs["spilled"] = True
        TRACER.register_event(event_id, TRACER.current_trace_id())
        return event_id, spilled

    # -- resilient write path (ISSUE 3) -------------------------------------
    def _get_wal(self):
        """The spill WAL + its replayer, created on first need (the path
        depends on PIO_FS_BASEDIR, and idle servers should not touch
        disk)."""
        with self._wal_lock:
            if self._wal is None:
                from predictionio_tpu.resilience import (SpillReplayer,
                                                         SpillWAL)
                path = self._spill_path()
                os.makedirs(os.path.dirname(path), exist_ok=True)
                self._wal = SpillWAL(path)
                self._replayer = SpillReplayer(
                    self._wal, self.events, app_breaker=self.breaker,
                    registry=self.metrics)
                self._replayer.start()
            return self._wal

    def _spill_path(self) -> str:
        if self.config.spill_dir:
            d = self.config.spill_dir
        else:
            from predictionio_tpu.data.storage.registry import base_dir
            d = os.path.join(base_dir(), "ingest_spill")
        return os.path.join(d, "events.wal")

    #: error classes the spill path treats as TRANSIENT (an outage the
    #: replay will outlast). Anything else — validation errors, SQL
    #: constraint rejections — is deterministic: spilling it would ACK
    #: an event the store will never accept and wedge the replayer
    #: head-of-line, so those propagate to the client instead.
    from predictionio_tpu.resilience import \
        TRANSIENT_ERRORS as TRANSIENT_WRITE_ERRORS

    def _resilient_insert(self, event, app_id, channel_id):
        """Primary write behind the event-store breaker; on a transient
        failure or an open circuit the event lands in the durable WAL
        and is still ACKed (the never-lose-an-accepted-event contract).
        Returns ``(event_id, spilled)``."""
        from predictionio_tpu.data.event import new_event_id
        from predictionio_tpu.resilience import CircuitOpenError
        if not self.config.spill:
            return self.events.insert(event, app_id, channel_id), False
        # pre-assign the id: if a transient failure strikes AFTER the
        # backend actually committed (timeout on the ack), the spill
        # carries the SAME id, so the replayer's get-check dedups the
        # committed copy instead of inserting a second event under a
        # fresh id (the eventserver_client._with_id retry pattern)
        if not event.event_id:
            # minted=True: our fresh hex cannot name an existing event,
            # so the backend skips its overwrite-by-id probes
            event = event.with_id(new_event_id(), minted=True)
        try:
            self.breaker.allow()
        except CircuitOpenError:
            return self._spill(event, app_id, channel_id), True
        try:
            eid = self.events.insert(event, app_id, channel_id)
        except self.TRANSIENT_WRITE_ERRORS as e:
            self.breaker.record_failure()
            logger.warning("event-store write failed (%s); spilling", e)
            return self._spill(event, app_id, channel_id), True
        except Exception:
            # a deterministic rejection (validation, constraint): the
            # store ANSWERED, so it is reachable — that's a breaker
            # success (and releases a half-open probe slot); the client
            # gets the honest error instead of a false ACK
            self.breaker.record_success()
            raise
        self.breaker.record_success()
        return eid, False

    def _incident_state(self) -> dict:
        """Ingest durability state frozen into incident bundles: WAL
        pending/quarantine counts + breaker state (obs/incidents.py)."""
        out = {"breaker": self.breaker.state,
               "spilledCount": self.spilled_count}
        wal = self._wal
        if wal is not None:
            try:
                out["pendingRecords"] = wal.pending_count()
                out["pendingBytes"] = wal.pending_bytes()
                # sidecar line count only — a scan_wal() here would
                # frame-walk + CRC the whole WAL on the disk the
                # incident is about, mid-outage
                from predictionio_tpu.resilience.spill import \
                    count_quarantined
                out["quarantined"] = count_quarantined(wal.path)
            except Exception as e:
                out["walError"] = str(e)
        return out

    def _resilient_insert_batch(self, events, app_id, channel_id):
        """Batched ``_resilient_insert`` (the admission batcher's
        dispatch): ids pre-assigned for replay idempotency, ONE breaker
        decision and one ``insert_batch`` for the group; a transient
        failure or an open circuit spills the whole group to the WAL
        under one fsync and still acks every event. Returns
        ``[(event_id, spilled), ...]`` in input order."""
        from predictionio_tpu.data.event import new_event_id
        from predictionio_tpu.resilience import CircuitOpenError
        if not self.config.spill:
            ids = self.events.insert_batch(events, app_id, channel_id)
            return [(eid, False) for eid in ids]
        events = [e if e.event_id
                  else e.with_id(new_event_id(), minted=True)
                  for e in events]
        try:
            self.breaker.allow()
        except CircuitOpenError:
            return [(eid, True) for eid in
                    self._spill_many(events, app_id, channel_id)]
        try:
            ids = self.events.insert_batch(events, app_id, channel_id)
        except self.TRANSIENT_WRITE_ERRORS as e:
            self.breaker.record_failure()
            logger.warning("event-store batch write failed (%s); "
                           "spilling %d events", e, len(events))
            return [(eid, True) for eid in
                    self._spill_many(events, app_id, channel_id)]
        except Exception:
            # deterministic rejection: the store answered (breaker
            # success); the callers get the honest error, not an ACK
            self.breaker.record_success()
            raise
        self.breaker.record_success()
        return [(eid, False) for eid in ids]

    def _spill_many(self, events, app_id, channel_id) -> list:
        with TRACER.span("spill_append"):
            eids = self._get_wal().append_many(events, app_id,
                                               channel_id)
        self.spilled_count += len(eids)
        FLIGHT.record("spill", coalesce_s=1.0, rows=len(eids),
                      pending=self._wal.pending_count()
                      if self._wal else None)
        return eids

    def _spill(self, event, app_id, channel_id) -> str:
        with TRACER.span("spill_append"):
            eid = self._get_wal().append(event, app_id, channel_id)
        self.spilled_count += 1
        # lifecycle record (ISSUE 6): coalesced — a 2k ev/s outage is
        # one spill record per second (+ suppressed count), not a ring
        # flood that evicts the breaker/replay narrative an incident
        # bundle needs
        FLIGHT.record("spill", coalesce_s=1.0, eventId=eid,
                      pending=self._wal.pending_count()
                      if self._wal else None)
        return eid

    def _batch_create(self, req: Request) -> Response:
        access_key, channel_id = self._authenticate(req)
        items = req.json()
        if not isinstance(items, list):
            raise ValueError("request body must be a JSON array")
        if len(items) > self.config.max_batch:
            # 413 with the honest limit (ISSUE 7): the caller learns
            # exactly what to re-chunk to — and that the columnar route
            # exists for genuinely bulk loads
            return Response(413, {
                "message": f"Batch request must have less than or equal "
                           f"to {self.config.max_batch} events",
                "maxBatch": self.config.max_batch,
                "received": len(items),
                "hint": "POST /events/columnar.json accepts "
                        f"{self.config.max_columnar_rows} rows per "
                        "request as parallel arrays"})
        results = []
        with TRACER.trace("event_batch", events=len(items),
                          **ingress_trace_kwargs(req.headers)):
            for d in items:
                try:
                    event = Event.from_dict(d)
                    self._check_event_allowed(access_key, event.event)
                    EventValidation.validate(event)
                    event_id, spilled = self._insert_traced(
                        event, access_key.appid, channel_id)
                    item = {"status": 201, "eventId": event_id}
                    if spilled:
                        item["spilled"] = True
                    results.append(item)
                    if self.config.stats:
                        self.stats.update(access_key.appid, event.event,
                                          event.entity_type, 201)
                except AuthError as e:
                    results.append({"status": e.status,
                                    "message": e.message})
                except Exception as e:
                    results.append({"status": 400, "message": str(e)})
        return Response(200, results)

    def _get_event(self, req: Request) -> Response:
        access_key, channel_id = self._authenticate(req)
        event_id = req.path_args[0]
        event = self.events.get(event_id, access_key.appid, channel_id)
        if event is None:
            return Response(404, {"message": "Not Found"})
        return Response(200, event.to_dict())

    def _delete_event(self, req: Request) -> Response:
        access_key, channel_id = self._authenticate(req)
        event_id = req.path_args[0]
        ok = self.events.delete(event_id, access_key.appid, channel_id)
        if ok:
            return Response(200, {"message": "Found"})
        return Response(404, {"message": "Not Found"})

    @staticmethod
    def _parse_find_filters(p) -> dict:
        """The query-param filter surface shared by /events.json and
        /events/columnar.json — one parser so the two routes cannot
        silently diverge."""
        def time_of(key):
            return parse_event_time(p[key]) if key in p else None

        def tgt(key):
            if key not in p:
                return None
            return ABSENT if p[key] == "" else p[key]

        return dict(
            start_time=time_of("startTime"),
            until_time=time_of("untilTime"),
            entity_type=p.get("entityType"), entity_id=p.get("entityId"),
            event_names=(p["event"].split(",") if "event" in p else None),
            target_entity_type=tgt("targetEntityType"),
            target_entity_id=tgt("targetEntityId"))

    def _find_events(self, req: Request) -> Response:
        access_key, channel_id = self._authenticate(req)
        p = req.params
        limit = int(p.get("limit", 20))
        reversed_order = p.get("reversed") == "true"
        if reversed_order and not (p.get("entityType") and
                                   p.get("entityId")):
            return Response(400, {
                "message": "the parameter reversed can only be used with "
                           "both entityType and entityId specified."})
        events = list(self.events.find(
            app_id=access_key.appid, channel_id=channel_id,
            limit=limit, reversed_order=reversed_order,
            **self._parse_find_filters(p)))
        if not events:
            return Response(404, {"message": "Not Found"})
        return Response(200, [e.to_dict() for e in events])

    def _find_columnar(self, req: Request) -> Response:
        """GET /events/columnar.json — the training-ingest read as flat
        column arrays (the PEvents bulk-scan role over the network):
        {"entity_id": [...], "target_entity_id": [...], "event": [...],
        "t": [...], "prop": [...]} is ~4x leaner on the wire than
        per-event JSON objects and parses without per-event dicts.
        `propertyField` selects the numeric property column (NaN ->
        null). Filters match /events.json exactly (shared parser); the
        client pages big reads by time windows, so `limit` bounds every
        response."""
        access_key, channel_id = self._authenticate(req)
        p = req.params
        limit = int(p.get("limit", -1))
        cols = self.events.find_columnar(
            app_id=access_key.appid, channel_id=channel_id,
            property_field=p.get("propertyField"),
            limit=limit, **self._parse_find_filters(p))
        # .tolist() yields native str/int directly — no per-element
        # Python calls on the bulk path this route exists to accelerate
        out = {
            "entity_id": cols["entity_id"].tolist(),
            "target_entity_id": cols["target_entity_id"].tolist(),
            "event": cols["event"].tolist(),
            "t": cols["t"].tolist(),
        }
        if "prop" in cols:
            out["prop"] = [None if x != x else x
                           for x in cols["prop"].astype(float).tolist()]
        return Response(200, out)

    def _columnar_post(self, req: Request) -> Response:
        """POST /events/columnar.json dispatch. The body shape picks the
        mode: ``entityId`` (singular — the write anchor column) means a
        columnar bulk WRITE; anything else is the entity-filtered read
        (``entityIds``/``targetEntityIds`` lists). Auth runs before the
        body parse either way."""
        access_key, channel_id = self._authenticate(req)
        d = req.json()
        if not isinstance(d, dict):
            raise ValueError("request body must be a JSON object")
        if "entityId" in d:
            return self._columnar_create(access_key, channel_id, d,
                                         req)
        return self._columnar_by_entities(access_key, channel_id, d)

    def _columnar_create(self, access_key, channel_id, d,
                         req: Request) -> Response:
        """Columnar bulk write (ISSUE 7 tentpole b): parallel arrays in
        one body -> one normalize pass, one whole-column validation
        pass, one ``insert_columnar`` DAO call. Deterministic per-ROW
        problems come back as per-record 4xx entries in ``failures``
        (the good rows still land — /batch semantics); malformed TABLES
        (wrong shapes, bad broadcast scalar) reject the whole request.
        ``returnIds: true`` echoes the minted ids (the response is
        otherwise O(1) — 100k-row acks should not cost a 3 MB body)."""
        from predictionio_tpu.data.columnar import (normalize_columnar,
                                                    validate_rows)
        with TRACER.trace("event_ingest_columnar",
                          **ingress_trace_kwargs(req.headers)) as tr:
            try:
                batch = normalize_columnar(d)
            except ValueError as e:
                return Response(400, {"message": str(e)})
            tr.root.attrs["rows"] = batch.n
            if batch.n > self.config.max_columnar_rows:
                return Response(413, {
                    "message": "columnar request must have less than or "
                               f"equal to {self.config.max_columnar_rows}"
                               " rows",
                    "maxRows": self.config.max_columnar_rows,
                    "received": batch.n})
            try:
                keep, failures = validate_rows(
                    batch, allowed_events=access_key.events or None)
            except PermissionError as e:
                return Response(403, {"message": str(e)})
            except ValueError as e:
                return Response(400, {"message": str(e)})
            # inputblocker plugins see each event only when some are
            # actually registered — the bulk path must not materialize
            # n dicts for the (default) empty plugin set
            from predictionio_tpu.data.api.plugins import INPUT_BLOCKER
            if self.plugin_context.plugins[INPUT_BLOCKER]:
                kept = keep if keep is not None else range(batch.n)
                vetoed = set()
                for i in kept:
                    try:
                        self.plugin_context.check_input(
                            {"appId": access_key.appid,
                             "channelId": channel_id,
                             "event": batch.row_event(i).to_dict()})
                    except Exception as e:
                        failures.append((i, 400, str(e)))
                        vetoed.add(i)
                if vetoed:
                    keep = [i for i in kept if i not in vetoed]
            ins = batch if keep is None else batch.select(keep)
            ids: list = []
            spilled = False
            if ins.n:
                with TRACER.span("storage_write") as sp:
                    t0 = time.perf_counter()
                    ids, spilled = self._resilient_insert_columnar(
                        ins, access_key.appid, channel_id)
                    self._h_write.observe(time.perf_counter() - t0)
                    if sp is not None:
                        sp.attrs["rows"] = ins.n
            if self.config.stats:
                self._stats_columnar(access_key.appid, ins,
                                     len(failures))
            body: dict = {"eventsCreated": len(ids),
                          "traceId": tr.trace_id}
            if spilled:
                body["spilled"] = True
            if d.get("returnIds"):
                body["eventIds"] = ids
            if failures:
                body["failures"] = [
                    {"index": i, "status": s, "message": m}
                    for i, s, m in sorted(failures)]
                return Response(200, body)
            return Response(201, body)

    def _stats_columnar(self, app_id, ins, n_failed: int):
        """Window counters for a columnar batch: broadcast name/type
        count in ONE bulk update; per-row columns group first."""
        from collections import Counter
        if ins.n:
            if isinstance(ins.event, str) and isinstance(ins.entity_type,
                                                         str):
                self.stats.update(app_id, ins.event, ins.entity_type,
                                  201, n=ins.n)
            else:
                groups = Counter(
                    (ins.cell("event", i), ins.cell("entity_type", i))
                    for i in range(ins.n))
                for (ev_name, etype), k in groups.items():
                    self.stats.update(app_id, ev_name, etype, 201, n=k)
        if n_failed:
            self.stats.update(app_id, "(invalid)", "(invalid)", 400,
                              n=n_failed)

    def _resilient_insert_columnar(self, batch, app_id, channel_id):
        """The bulk analog of _resilient_insert: ids pre-assigned before
        the first attempt (a commit-then-timeout replays as a dedup),
        transient failure or an open breaker spills the WHOLE batch to
        the WAL under one fsync and still acks. Returns (ids, spilled)."""
        from predictionio_tpu.resilience import CircuitOpenError
        if not self.config.spill:
            return self.events.insert_columnar(batch, app_id,
                                               channel_id), False
        if batch.event_id is None:
            from predictionio_tpu.data.event import new_event_ids
            batch.event_id = new_event_ids(batch.n)
            batch.minted = True     # our fresh hex: backends keep their
            #                         minted-id fast paths (columnar.py)
        try:
            self.breaker.allow()
        except CircuitOpenError:
            return self._spill_columnar(batch, app_id, channel_id), True
        try:
            ids = self.events.insert_columnar(batch, app_id, channel_id)
        except self.TRANSIENT_WRITE_ERRORS as e:
            self.breaker.record_failure()
            logger.warning(
                "columnar event-store write failed (%s); spilling %d "
                "events", e, batch.n)
            return self._spill_columnar(batch, app_id, channel_id), True
        except Exception:
            self.breaker.record_success()
            raise
        self.breaker.record_success()
        return ids, False

    def _spill_columnar(self, batch, app_id, channel_id) -> list:
        return self._spill_many(batch.to_events(), app_id, channel_id)

    def _columnar_by_entities(self, access_key, channel_id,
                              d) -> Response:
        """The entity-filtered columnar read (the fold tick's O(touched)
        ingest over the network). The touched id lists ride in the JSON
        body (query strings cap out around a few thousand ids); scalar
        filters match /events.json semantics. The response is the same
        flat column shape as the GET route."""

        def time_of(key):
            return parse_event_time(d[key]) if d.get(key) else None

        target_type = d.get("targetEntityType")
        if target_type == "":
            target_type = ABSENT
        limit = d.get("limit")
        cols = self.events.find_columnar_by_entities(
            app_id=access_key.appid, channel_id=channel_id,
            entity_ids=[str(x) for x in d.get("entityIds") or ()],
            target_entity_ids=[str(x)
                               for x in d.get("targetEntityIds") or ()],
            property_field=d.get("propertyField"),
            start_time=time_of("startTime"),
            until_time=time_of("untilTime"),
            entity_type=d.get("entityType"),
            target_entity_type=target_type,
            event_names=d.get("events"),
            limit=int(limit) if limit is not None else None)
        out = {
            "entity_id": cols["entity_id"].tolist(),
            "target_entity_id": cols["target_entity_id"].tolist(),
            "event": cols["event"].tolist(),
            "t": cols["t"].tolist(),
        }
        if "prop" in cols:
            out["prop"] = [None if x != x else x
                           for x in cols["prop"].astype(float).tolist()]
        return Response(200, out)

    def _get_stats(self, req: Request) -> Response:
        access_key, _ = self._authenticate(req)
        if not self.config.stats:
            return Response(404, {
                "message": "To see stats, launch Event Server with "
                           "--stats argument."})
        return Response(200, self.stats.to_dict(access_key.appid))

    def _metrics(self, req: Request) -> Response:
        """Prometheus text exposition, rendered solely by the shared
        metrics registry (ISSUE 2). Unauthenticated — scrapers don't
        carry access keys — and therefore AGGREGATE only (event counts
        across all apps, no per-app split; the keyed /stats.json keeps
        the per-app view). 404 unless --stats, like /stats.json."""
        if not self.config.stats:
            return Response(404, {
                "message": "To expose metrics, launch Event Server with "
                           "--stats argument."})
        from predictionio_tpu.utils.prometheus import (
            CONTENT_TYPE, OPENMETRICS_CONTENT_TYPE, wants_exemplars)
        om = wants_exemplars(req)
        self._window_pin = self.stats.to_dict(None)
        try:
            body = self.metrics.render(exemplars=om)
        finally:
            self._window_pin = None
        return Response(200, body,
                        content_type=OPENMETRICS_CONTENT_TYPE if om
                        else CONTENT_TYPE)

    def _traces(self, req: Request) -> Response:
        """GET /traces.json — recent span trees from the process-wide
        tracer (?n=, ?kind=, ?sort=slowest). Gated like /metrics:
        unauthenticated, and ingest traces carry per-event detail
        (event ids/names, write timings), so a server launched without
        --stats exposes nothing."""
        if not self.config.stats:
            return Response(404, {
                "message": "To expose traces, launch Event Server with "
                           "--stats argument."})
        return Response(200, traces_response(req.params))

    def _flight(self, req: Request) -> Response:
        """GET /flight.json — lifecycle wide events (?n=, ?kind=,
        ?trace_id=). Gated like /traces.json: spill records carry
        event ids, so a server launched without --stats exposes
        nothing."""
        if not self.config.stats:
            return Response(404, {
                "message": "To expose flight records, launch Event "
                           "Server with --stats argument."})
        return Response(200, flight_response(req.params))

    def _health(self, req: Request) -> Response:
        """GET /health.json — SLO verdicts (ingest write p99, ingest
        rate, spill budget). Ungated: aggregate liveness only, no
        per-app detail."""
        return Response(200, health_response(self.slo, extra={
            "breaker": self.breaker.state}))

    def _profile(self, req: Request) -> Response:
        """``/profile.json`` (ISSUE 11 satellite) — the same profiling
        surface the engine server mounts (obs/profiler.py): jax trace
        start/stop toggle + the sampling profiler's report. Gated like
        /metrics: stacks name storage paths and internals, so a server
        launched without --stats exposes nothing."""
        if not self.config.stats:
            return Response(404, {
                "message": "To expose profiling, launch Event Server "
                           "with --stats argument."})
        from predictionio_tpu.obs import profiler
        status, body = profiler.profile_response_from_request(req)
        return Response(status, body)

    # -- fleet federation (ISSUE 13) ----------------------------------------
    def _fleet_status(self, req: Request) -> Response:
        """GET /fleet/status.json — member registry with liveness.
        Ungated: aggregate process liveness only, like /health.json."""
        return Response(200, fleet.fleet_status_response(req.params))

    def _fleet_health(self, req: Request) -> Response:
        """GET /fleet/health.json — worst-of SLO rollup across live
        members. Ungated, like /health.json."""
        return Response(200, fleet.fleet_health_response(req.params))

    def _fleet_metrics(self, req: Request) -> Response:
        """GET /fleet/metrics — every live member's scrape merged with
        {role,pid} labels. Gated like /metrics (the merge contains this
        server's own families)."""
        if not self.config.stats:
            return Response(404, {
                "message": "To federate metrics, launch Event Server "
                           "with --stats argument."})
        from predictionio_tpu.utils.prometheus import CONTENT_TYPE
        return Response(200, fleet.fleet_metrics_response(req.params),
                        content_type=CONTENT_TYPE)

    def _fleet_traces(self, req: Request) -> Response:
        """GET /fleet/traces.json?trace_id= — the trace stitched
        fleet-wide. Gated like /traces.json."""
        if not self.config.stats:
            return Response(404, {
                "message": "To expose traces, launch Event Server with "
                           "--stats argument."})
        return Response(200, fleet.fleet_traces_response(req.params))

    def _incidents_list(self, req: Request) -> Response:
        """GET /incidents.json — bundle index (ISSUE 13 satellite: `pio
        incidents list --url` against a member that does not share the
        operator's filesystem). Gated like /flight.json."""
        if not self.config.stats:
            return Response(404, {
                "message": "To expose incidents, launch Event Server "
                           "with --stats argument."})
        from predictionio_tpu.obs.incidents import incidents_response
        return Response(200, incidents_response(req.params))

    def _incident_show(self, req: Request) -> Response:
        if not self.config.stats:
            return Response(404, {
                "message": "To expose incidents, launch Event Server "
                           "with --stats argument."})
        from predictionio_tpu.obs.incidents import incident_response
        status, body = incident_response(req.path_args[0])
        return Response(status, body)

    def _webhook_json(self, req: Request) -> Response:
        access_key, channel_id = self._authenticate(req)
        name = req.path_args[0]
        connector = self.webhook_connectors.get_json(name)
        if connector is None:
            return Response(404, {"message": f"webhook {name} not supported"})
        event = connector.to_event(req.json() or {})
        EventValidation.validate(event)
        event_id, spilled = self._resilient_insert(
            event, access_key.appid, channel_id)
        body = {"eventId": event_id}
        if spilled:
            body["spilled"] = True
        return Response(201, body)

    def _webhook_form(self, req: Request) -> Response:
        access_key, channel_id = self._authenticate(req)
        name = req.path_args[0]
        connector = self.webhook_connectors.get_form(name)
        if connector is None:
            return Response(404, {"message": f"webhook {name} not supported"})
        event = connector.to_event(req.form())
        EventValidation.validate(event)
        event_id, spilled = self._resilient_insert(
            event, access_key.appid, channel_id)
        body = {"eventId": event_id}
        if spilled:
            body["spilled"] = True
        return Response(201, body)

    def _webhook_get(self, req: Request) -> Response:
        self._authenticate(req)
        name = req.path_args[0]
        if (self.webhook_connectors.get_json(name) or
                self.webhook_connectors.get_form(name)):
            return Response(200, {"message": "Ok"})
        return Response(404, {"message": f"webhook {name} not supported"})

    def _build_router(self) -> Router:
        r = Router()

        def guarded(handler):
            def wrapped(req: Request) -> Response:
                try:
                    return handler(req)
                except AuthError as e:
                    return Response(e.status, {"message": e.message})
            return wrapped

        r.add("GET", "/", self._status)
        r.add("GET", "/plugins.json",
              lambda req: Response(200, self.plugin_context.to_dict()))
        r.add("POST", "/events.json", guarded(self._create_event))
        r.add("GET", "/events.json", guarded(self._find_events))
        r.add("POST", "/batch/events.json", guarded(self._batch_create))
        # columnar must precede the <id> route ("columnar" is not an id)
        r.add("GET", "/events/columnar.json", guarded(self._find_columnar))
        r.add("POST", "/events/columnar.json",
              guarded(self._columnar_post))
        r.add("GET", "/events/<id>.json", guarded(self._get_event))
        r.add("DELETE", "/events/<id>.json", guarded(self._delete_event))
        r.add("GET", "/stats.json", guarded(self._get_stats))
        r.add("GET", "/metrics", self._metrics)
        r.add("GET", "/traces.json", self._traces)
        r.add("GET", "/flight.json", self._flight)
        r.add("GET", "/health.json", self._health)
        r.add("GET", "/fleet/status.json", self._fleet_status)
        r.add("GET", "/fleet/health.json", self._fleet_health)
        r.add("GET", "/fleet/metrics", self._fleet_metrics)
        r.add("GET", "/fleet/traces.json", self._fleet_traces)
        r.add("GET", "/incidents.json", self._incidents_list)
        r.add("GET", "/incidents/<id>.json", self._incident_show)
        r.add("POST", "/profile.json", self._profile)
        r.add("GET", "/profile.json", self._profile)
        r.add("POST", "/webhooks/<name>.json", guarded(self._webhook_json))
        r.add("GET", "/webhooks/<name>.json", guarded(self._webhook_get))
        r.add("POST", "/webhooks/<name>", guarded(self._webhook_form))
        return r

    # -- lifecycle ----------------------------------------------------------
    def start(self, background: bool = True) -> "EventServer":
        # adopt a WAL a prior process left undrained: the replay
        # contract survives restarts (events spilled before a crash
        # still reach the primary store)
        if self.config.spill and os.path.exists(self._spill_path()) \
                and os.path.getsize(self._spill_path()) > 0:
            self._get_wal()
        # always-on sampling profiler (ISSUE 11; PIO_PROFILER=off)
        from predictionio_tpu.obs import profiler
        profiler.ensure_started()
        srv = HttpServer(self.router, self.config.ip, self.config.port)
        self.server = srv

        def _bound(s):
            # runs post-bind / pre-serve: the only window where a
            # FOREGROUND server can publish its resolved port. Fleet
            # member record (ISSUE 13): real liveness for federation,
            # flight GC and incident capture.
            self.config.port = s.port
            self._fleet_id = fleet.register_member(
                "event_server", port=s.port, host=self.config.ip,
                stats=self.config.stats)
            logger.info("Event Server started on %s:%d",
                        self.config.ip, s.port)

        srv.on_bound = _bound
        srv.start(background=background)
        return self

    def stop(self):
        fleet.deregister_member(getattr(self, "_fleet_id", None))
        self._fleet_id = None
        if self.server:
            self.server.stop()
            self.server = None
        with self._wal_lock:
            replayer, self._replayer = self._replayer, None
            wal, self._wal = self._wal, None
        if replayer is not None:
            replayer.stop()
        if wal is not None:
            # the WAL file itself persists (durable by design); only
            # the handle closes. A final opportunistic drain narrows
            # the restart-replay window without blocking shutdown —
            # which is why it only runs with the breaker CLOSED: with
            # the store down the drain can only sleep through retry
            # backoffs and fail anyway (the restart replay covers it)
            try:
                if wal.pending_bytes() and self.breaker.state == "closed":
                    replayer.drain(max_records=1000)
            except Exception:
                logger.debug("final spill drain failed", exc_info=True)
            wal.close()


class AuthError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message
