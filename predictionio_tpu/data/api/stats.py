"""Event-server bookkeeping counters.

Rebuilds the reference's ``Stats`` / ``StatsActor``
(reference: data/src/main/scala/io/prediction/data/api/Stats.scala:40-79,
StatsActor.scala:28-33): per-app counters of (event, entityType, status)
kept for the current and previous window, served on ``/stats.json``.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict, Optional, Tuple


class Stats:
    WINDOW_SEC = 3600.0  # reference rotates hourly

    def __init__(self):
        self._lock = threading.Lock()
        self._window_start = time.time()
        self._current: Dict[Tuple, int] = defaultdict(int)
        self._previous: Dict[Tuple, int] = defaultdict(int)

    def _maybe_rotate(self):
        now = time.time()
        elapsed = now - self._window_start
        if elapsed >= 2 * self.WINDOW_SEC:
            # idle gap spanning more than one full window: the stale
            # current window is not "previous" — at least one empty
            # window sat between it and now, so reporting it would
            # claim hour-old traffic as last-hour traffic (ISSUE 2
            # satellite). Both windows start empty.
            self._previous = defaultdict(int)
            self._current = defaultdict(int)
            self._window_start = now
        elif elapsed >= self.WINDOW_SEC:
            self._previous = self._current
            self._current = defaultdict(int)
            self._window_start = now

    def update(self, app_id: int, event_name: str, entity_type: str,
               status: int, n: int = 1):
        """``n`` lets the columnar bulk-write route count a whole batch
        of identical (event, entityType) rows in one lock acquisition."""
        with self._lock:
            self._maybe_rotate()
            self._current[(app_id, event_name, entity_type, status)] += n

    def _render(self, counters: Dict[Tuple, int], app_id: Optional[int]):
        by_event: Dict[str, int] = defaultdict(int)
        by_entity: Dict[str, int] = defaultdict(int)
        by_status: Dict[str, int] = defaultdict(int)
        total = 0
        for (aid, ev, et, st), n in counters.items():
            if app_id is not None and aid != app_id:
                continue
            by_event[ev] += n
            by_entity[et] += n
            by_status[str(st)] += n
            total += n
        return {"count": total, "byEvent": dict(by_event),
                "byEntityType": dict(by_entity), "byStatus": dict(by_status)}

    def to_dict(self, app_id: Optional[int] = None) -> dict:
        with self._lock:
            self._maybe_rotate()
            return {
                "startTime": self._window_start,
                "currentWindow": self._render(self._current, app_id),
                "previousWindow": self._render(self._previous, app_id),
            }
