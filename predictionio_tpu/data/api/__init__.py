"""Event-collection REST API (L1)."""

from predictionio_tpu.data.api.event_server import EventServer, EventServerConfig

__all__ = ["EventServer", "EventServerConfig"]
