"""Event-server plugin interface.

Rebuilds the reference's ``EventServerPlugin``
(reference: data/src/main/scala/io/prediction/data/api/EventServerPlugin.scala:18-32
and api/PluginsActor.scala): inputblocker plugins validate/veto incoming
events, inputsniffer plugins observe them; both are discovered from
PIO_EVENT_SERVER_PLUGINS (dotted class names) or registered explicitly.
"""

from __future__ import annotations

import abc
import importlib
import logging
import os
from typing import Dict, List

logger = logging.getLogger(__name__)

INPUT_BLOCKER = "inputblocker"
INPUT_SNIFFER = "inputsniffer"


class EventServerPlugin(abc.ABC):
    plugin_name: str = "plugin"
    plugin_description: str = ""
    input_type: str = INPUT_SNIFFER

    def start(self, context: "EventServerPluginContext") -> None:
        pass

    @abc.abstractmethod
    def process(self, event_info: dict,
                context: "EventServerPluginContext") -> None:
        """inputblocker: raise ValueError to reject the event;
        inputsniffer: observe only."""

    def handle_rest(self, app_id: int, channel_id, arguments: List[str]):
        return {"message": "The plugin does not support REST."}


class EventServerPluginContext:
    def __init__(self):
        self.plugins: Dict[str, Dict[str, EventServerPlugin]] = {
            INPUT_BLOCKER: {}, INPUT_SNIFFER: {}}

    def register(self, plugin: EventServerPlugin):
        self.plugins[plugin.input_type][plugin.plugin_name] = plugin

    @staticmethod
    def load_from_env() -> "EventServerPluginContext":
        ctx = EventServerPluginContext()
        spec = os.environ.get("PIO_EVENT_SERVER_PLUGINS", "")
        for dotted in filter(None, (s.strip() for s in spec.split(","))):
            try:
                module_name, _, attr = dotted.rpartition(".")
                cls = getattr(importlib.import_module(module_name), attr)
                ctx.register(cls())
            except Exception as e:
                logger.error("Cannot load plugin %s: %s", dotted, e)
        return ctx

    def check_input(self, event_info: dict) -> None:
        """Run inputblockers (may raise) then inputsniffers."""
        for plugin in self.plugins[INPUT_BLOCKER].values():
            plugin.process(event_info, self)
        for plugin in self.plugins[INPUT_SNIFFER].values():
            try:
                plugin.process(event_info, self)
            except Exception as e:
                logger.error("inputsniffer %s failed: %s",
                             plugin.plugin_name, e)

    def to_dict(self) -> dict:
        return {
            "plugins": {
                kind: {name: {"name": p.plugin_name,
                              "description": p.plugin_description,
                              "class": type(p).__name__}
                       for name, p in plugins.items()}
                for kind, plugins in self.plugins.items()}}
