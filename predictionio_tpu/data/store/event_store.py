"""App-name-keyed event access for engine components.

Rebuilds the reference's ``PEventStore`` / ``LEventStore``
(reference: data/src/main/scala/io/prediction/data/store/PEventStore.scala:30-116,
LEventStore.scala:30-142, Common.scala appNameToId): engines refer to apps by
*name* (+ optional channel name); the store resolves ids through the metadata
DAOs and forwards to the configured Events backend.

The P/L split collapses here: one synchronous API serves both the bulk
training reads (PEvents role — feed ``parallel.dataset`` ingest) and the
serve-time point lookups with a deadline (LEvents role; the ecommerce
template's 200 ms business-rule reads)."""

from __future__ import annotations

import datetime as _dt
import threading
import time
from typing import Dict, Iterator, Optional, Sequence

from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.registry import Storage


class EventStore:
    def __init__(self, apps=None, channels=None, events=None):
        self._apps = apps
        self._channels = channels
        self._events = events

    @property
    def apps(self):
        return self._apps or Storage.get_meta_data_apps()

    @property
    def channels(self):
        return self._channels or Storage.get_meta_data_channels()

    @property
    def events(self):
        return self._events or Storage.get_events()

    def resolve(self, app_name: str,
                channel_name: Optional[str] = None) -> tuple:
        """app/channel name -> ids (store/Common.scala appNameToId)."""
        app = self.apps.get_by_name(app_name)
        if app is None:
            raise ValueError(
                f"Invalid app name {app_name!r}: app does not exist.")
        channel_id = None
        if channel_name is not None:
            match = [c for c in self.channels.get_by_app_id(app.id)
                     if c.name == channel_name]
            if not match:
                raise ValueError(
                    f"Invalid channel name {channel_name!r} for app "
                    f"{app_name!r}.")
            channel_id = match[0].id
        return app.id, channel_id

    # -- bulk reads (PEventStore.find, PEventStore.scala:54) ---------------
    def find(self, app_name: str, channel_name: Optional[str] = None,
             start_time: Optional[_dt.datetime] = None,
             until_time: Optional[_dt.datetime] = None,
             entity_type: Optional[str] = None,
             entity_id: Optional[str] = None,
             event_names: Optional[Sequence[str]] = None,
             target_entity_type=None, target_entity_id=None,
             limit: Optional[int] = None,
             reversed_order: bool = False) -> Iterator[Event]:
        app_id, channel_id = self.resolve(app_name, channel_name)
        return self.events.find(
            app_id=app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            entity_id=entity_id, event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id, limit=limit,
            reversed_order=reversed_order)

    def find_columnar(self, app_name: str,
                      channel_name: Optional[str] = None,
                      property_field: Optional[str] = None,
                      **filters) -> Dict[str, "object"]:
        """Columnar bulk read (see Events.find_columnar): flat numpy arrays
        for vectorized training ingest — the PEvents-scan-to-RDD role
        (PEvents.scala:77) without per-event Python objects."""
        app_id, channel_id = self.resolve(app_name, channel_name)
        return self.events.find_columnar(
            app_id=app_id, channel_id=channel_id,
            property_field=property_field, **filters)

    def find_columnar_chunked(self, app_name: str,
                              channel_name: Optional[str] = None,
                              property_field: Optional[str] = None,
                              chunk_rows: Optional[int] = None,
                              **filters) -> Iterator[Dict[str, "object"]]:
        """Streaming columnar bulk read (see
        Events.find_columnar_chunked): a generator of chunk-sized column
        dicts whose concatenation is byte-identical to ``find_columnar``
        — the bulk data plane's cursor into the store (dataplane reader
        threads drain it so read/decode/upload overlap)."""
        app_id, channel_id = self.resolve(app_name, channel_name)
        return self.events.find_columnar_chunked(
            app_id=app_id, channel_id=channel_id,
            property_field=property_field, chunk_rows=chunk_rows,
            **filters)

    def find_columnar_by_entities(self, app_name: str,
                                  channel_name: Optional[str] = None,
                                  entity_ids=None, target_entity_ids=None,
                                  property_field: Optional[str] = None,
                                  **filters) -> Dict[str, "object"]:
        """Entity-set-filtered columnar read (see
        Events.find_columnar_by_entities): the fold tick's O(touched)
        ingest — rows whose subject is a touched entity OR whose target
        is a touched target, with each backend's real pushdown behind
        it."""
        app_id, channel_id = self.resolve(app_name, channel_name)
        return self.events.find_columnar_by_entities(
            app_id=app_id, channel_id=channel_id, entity_ids=entity_ids,
            target_entity_ids=target_entity_ids,
            property_field=property_field, **filters)

    # -- property aggregation (PEventStore.aggregateProperties) ------------
    def aggregate_properties(self, app_name: str, entity_type: str,
                             channel_name: Optional[str] = None,
                             start_time: Optional[_dt.datetime] = None,
                             until_time: Optional[_dt.datetime] = None,
                             required: Optional[Sequence[str]] = None
                             ) -> Dict[str, PropertyMap]:
        app_id, channel_id = self.resolve(app_name, channel_name)
        return self.events.aggregate_properties(
            app_id=app_id, channel_id=channel_id, entity_type=entity_type,
            start_time=start_time, until_time=until_time, required=required)

    # -- serve-time point reads (LEventStore.findByEntity) -----------------

    #: cap on concurrently outstanding deadline-guarded point reads. A
    #: timed-out read's worker thread keeps running against the slow
    #: backend (Python threads cannot be killed); the permit it holds is
    #: released only when the backend finally answers, so at most this
    #: many wedged readers can pile up — past that, new deadline reads
    #: fail fast instead of minting another stuck thread each.
    POINT_READ_MAX_INFLIGHT = 8

    _point_read_sem = threading.BoundedSemaphore(POINT_READ_MAX_INFLIGHT)

    def _timeout_counter(self):
        from predictionio_tpu.obs import get_registry
        return get_registry().counter(
            "pio_event_point_read_timeout_total",
            "Deadline-guarded event point reads that timed out (their "
            "late results are discarded; the worker permit is bounded)")

    def find_by_entity(self, app_name: str, entity_type: str, entity_id: str,
                       channel_name: Optional[str] = None,
                       event_names: Optional[Sequence[str]] = None,
                       target_entity_type=None, target_entity_id=None,
                       start_time=None, until_time=None,
                       limit: Optional[int] = None, latest: bool = True,
                       timeout_ms: Optional[int] = None) -> list:
        """Point lookup with an optional deadline (LEventStore.scala:30 — the
        reference's Duration timeout; the ecommerce template calls this with
        200 ms). Runs in a worker thread when a timeout is given so a slow
        backend cannot stall the serving path; timed-out workers are
        BOUNDED (POINT_READ_MAX_INFLIGHT permits — a wedged backend can
        strand at most that many threads, after which deadline reads
        fail fast) and counted under
        ``pio_event_point_read_timeout_total``."""
        def _query():
            return list(self.find(
                app_name=app_name, channel_name=channel_name,
                entity_type=entity_type, entity_id=entity_id,
                event_names=event_names,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id, start_time=start_time,
                until_time=until_time, limit=limit, reversed_order=latest))

        if timeout_ms is None:
            return _query()
        # the permit wait SHARES the deadline: a healthy burst past the
        # permit count queues briefly and still answers in time, while a
        # wedged backend (permits stranded by timed-out workers) makes
        # new reads fail at their own deadline instead of minting more
        # stuck threads
        t_start = time.monotonic()
        if not EventStore._point_read_sem.acquire(
                timeout=timeout_ms / 1000.0):
            self._timeout_counter().inc()
            raise TimeoutError(
                f"event lookup exceeded {timeout_ms} ms deadline: all "
                f"{self.POINT_READ_MAX_INFLIGHT} deadline-read workers "
                "are busy (backend wedged?)")
        done = threading.Event()
        result: list = []
        error: list = []

        def _run():
            try:
                result.append(_query())
            except Exception as e:  # surfaced below (if still awaited)
                error.append(e)
            finally:
                EventStore._point_read_sem.release()
                done.set()

        t = threading.Thread(target=_run, daemon=True,
                             name="pio-point-read")
        t.start()
        remaining = timeout_ms / 1000.0 - (time.monotonic() - t_start)
        if not done.wait(max(0.0, remaining)):
            # the worker keeps its permit until the backend answers;
            # its late result is dropped on the floor by design
            self._timeout_counter().inc()
            raise TimeoutError(
                f"event lookup exceeded {timeout_ms} ms deadline")
        if error:
            raise error[0]
        return result[0]


# Module-level default instances, mirroring the reference's singletons.
PEventStore = EventStore()
LEventStore = PEventStore
