"""Engine-facing event access (app-name-keyed), L2 of the layer map."""

from predictionio_tpu.data.store.event_store import (EventStore, LEventStore,
                                                     PEventStore)

__all__ = ["EventStore", "PEventStore", "LEventStore"]
