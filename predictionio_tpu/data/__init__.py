"""Data layer: event model, property maps, storage backends, event APIs."""

from predictionio_tpu.data.event import Event, EventValidation
from predictionio_tpu.data.datamap import DataMap, PropertyMap

__all__ = ["Event", "EventValidation", "DataMap", "PropertyMap"]
