"""Bidirectional id maps: string entity ids <-> dense integer indices.

Rebuilds the reference's ``BiMap``/``EntityMap``
(reference: data/src/main/scala/io/prediction/data/storage/BiMap.scala:25-165,
EntityMap.scala:27-98). This is SURVEY.md hard-part #1: every TPU kernel
indexes embedding tables by dense int32 row, so the string->index build must
be deterministic and the serve-time lookup O(1).

Design: ids are assigned by first-occurrence order over a deterministic
iteration (``string_int``) or by sorted order (``string_int_sorted``) for
cross-host determinism without coordination. Backed by plain dicts +
a numpy array for the inverse, so device-side gathers take the int index
directly and host-side lookup is one dict probe.
"""

from __future__ import annotations

from typing import Dict, Generic, Hashable, Iterable, Iterator, List, Mapping, TypeVar

import numpy as np

K = TypeVar("K", bound=Hashable)
V = TypeVar("V", bound=Hashable)


class BiMap(Generic[K, V]):
    """Immutable one-to-one map with O(1) forward and inverse lookup."""

    __slots__ = ("_fwd", "_inv")

    def __init__(self, forward: Mapping[K, V]):
        fwd = dict(forward)
        inv: Dict[V, K] = {}
        for k, v in fwd.items():
            if v in inv:
                raise ValueError(f"BiMap values must be unique; duplicate {v!r}")
            inv[v] = k
        self._fwd = fwd
        self._inv = inv

    # -- forward ------------------------------------------------------------
    def __getitem__(self, key: K) -> V:
        return self._fwd[key]

    def __contains__(self, key: K) -> bool:
        return key in self._fwd

    def __len__(self) -> int:
        return len(self._fwd)

    def __iter__(self) -> Iterator[K]:
        return iter(self._fwd)

    def get(self, key: K, default=None):
        return self._fwd.get(key, default)

    def contains(self, key: K) -> bool:
        return key in self._fwd

    def keys(self):
        return self._fwd.keys()

    def values(self):
        return self._fwd.values()

    def items(self):
        return self._fwd.items()

    def to_map(self) -> Dict[K, V]:
        return dict(self._fwd)

    # -- inverse ------------------------------------------------------------
    def inverse(self) -> "BiMap[V, K]":
        return BiMap(self._inv)

    def inverse_get(self, value: V, default=None):
        return self._inv.get(value, default)

    def take(self, keys: Iterable[K]) -> "BiMap[K, V]":
        """Sub-map restricted to ``keys`` (BiMap.scala `take`)."""
        return BiMap({k: self._fwd[k] for k in keys})

    def __eq__(self, other: object) -> bool:
        return isinstance(other, BiMap) and self._fwd == other._fwd

    def __repr__(self) -> str:
        return f"BiMap({self._fwd!r})"

    # -- constructors (BiMap.scala:102-165) ---------------------------------
    @staticmethod
    def string_int(keys: Iterable[str]) -> "BiMap[str, int]":
        """Dense 0..n-1 indices by first-occurrence order (deterministic for a
        deterministic input order; use string_int_sorted for order-free
        determinism)."""
        fwd: Dict[str, int] = {}
        for k in keys:
            if k not in fwd:
                fwd[k] = len(fwd)
        return BiMap(fwd)

    @staticmethod
    def string_int_sorted(keys: Iterable[str]) -> "BiMap[str, int]":
        """Dense indices by lexicographic order — deterministic regardless of
        input order, so every host builds the identical vocabulary."""
        uniq = sorted(set(keys))
        return BiMap({k: i for i, k in enumerate(uniq)})

    @staticmethod
    def string_long(keys: Iterable[str]) -> "BiMap[str, int]":
        return BiMap.string_int(keys)

    @staticmethod
    def string_double(keys: Iterable[str]) -> "BiMap[str, float]":
        fwd: Dict[str, float] = {}
        for k in keys:
            if k not in fwd:
                fwd[k] = float(len(fwd))
        return BiMap(fwd)


class EntityIdIxMap:
    """entityId <-> dense row index, with a numpy inverse table for vectorized
    index->id translation (EntityMap.scala:27-63)."""

    def __init__(self, id_to_ix: BiMap):
        self._bimap = id_to_ix
        n = len(id_to_ix)
        ids: List[str] = [""] * n
        for k, v in id_to_ix.items():
            ids[int(v)] = k
        self._ids = np.array(ids, dtype=object)

    @staticmethod
    def build(keys: Iterable[str], sort: bool = True) -> "EntityIdIxMap":
        bm = (BiMap.string_int_sorted(keys) if sort else BiMap.string_int(keys))
        return EntityIdIxMap(bm)

    @staticmethod
    def build_with_indices(ids: np.ndarray
                           ) -> "tuple[EntityIdIxMap, np.ndarray]":
        """Vectorized vocabulary build: one np.unique pass yields both the
        sorted-order map (same order as ``build``) and the dense index of
        every input row — the ingest-scale replacement for building the map
        and then re-translating 20M ids through a Python dict."""
        arr = np.asarray(ids)
        if arr.dtype == object:
            arr = arr.astype(str)
        uniq, inv = np.unique(arr, return_inverse=True)
        bm = BiMap({str(k): i for i, k in enumerate(uniq)})
        return EntityIdIxMap(bm), inv.astype(np.int32)

    def __getitem__(self, entity_id: str) -> int:
        return self._bimap[entity_id]

    def get(self, entity_id: str, default: int = -1) -> int:
        return self._bimap.get(entity_id, default)

    def __contains__(self, entity_id: str) -> bool:
        return entity_id in self._bimap

    def __len__(self) -> int:
        return len(self._bimap)

    def id_of(self, ix: int) -> str:
        return str(self._ids[ix])

    def ids_of(self, ixs) -> List[str]:
        return [str(x) for x in self._ids[np.asarray(ixs, dtype=np.int64)]]

    def to_indices(self, entity_ids: Iterable[str]) -> np.ndarray:
        """id->index per element via dict probes; unknown ids map to -1."""
        return np.array([self._bimap.get(e, -1) for e in entity_ids],
                        dtype=np.int32)

    def to_indices_array(self, ids: np.ndarray) -> np.ndarray:
        """Vectorized id->index for numpy id arrays (unknowns -> -1):
        binary search against a lazily-built sorted view of the key
        table. Works at full speed for grown (append-ordered, unsorted)
        maps too — the online fold-in path translates the whole corpus
        through this every tick, so a per-row dict-probe fallback would
        cost ~20M Python probes per side at ML-20M scale."""
        arr = np.asarray(ids)
        if arr.dtype == object:
            arr = arr.astype(str)
        keys = self._ids.astype(str)
        if len(keys) == 0 or arr.size == 0:
            return np.full(arr.shape, -1, dtype=np.int32)
        cache = getattr(self, "_sorted_view", None)
        if cache is None or len(cache[0]) != len(keys):
            order = np.argsort(keys)
            cache = (keys[order], order.astype(np.int32))
            self._sorted_view = cache
        sorted_keys, order = cache
        pos = np.searchsorted(sorted_keys, arr)
        pos_safe = np.clip(pos, 0, len(sorted_keys) - 1)
        hit = sorted_keys[pos_safe] == arr
        return np.where(hit, order[pos_safe], -1).astype(np.int32)

    @property
    def bimap(self) -> BiMap:
        return self._bimap

    # -- online growth (fold-in path) ---------------------------------------
    def grow(self, new_ids: Iterable[str]
             ) -> "tuple[EntityIdIxMap, np.ndarray]":
        """Append unseen ids AFTER the existing vocabulary, preserving every
        existing dense index — the invariant the online fold-in path depends
        on: factor-table row i must keep meaning the same entity across
        model versions, so grown tables are old tables plus appended rows.

        Returns (grown_map, appended_indices) where ``appended_indices`` are
        the dense indices assigned to the ids that were actually new, in
        first-occurrence order of ``new_ids``. Already-known ids are
        ignored. When nothing is new, returns (self, empty).

        Note the grown map is generally NOT in sorted order anymore;
        ``to_indices_array`` detects that and falls back to dict probes."""
        fresh: List[str] = []
        seen = set()
        for e in new_ids:
            e = str(e)
            if e not in self._bimap and e not in seen:
                seen.add(e)
                fresh.append(e)
        if not fresh:
            return self, np.empty(0, dtype=np.int32)
        base = len(self._bimap)
        fwd = dict(self._bimap.items())
        for i, e in enumerate(fresh):
            fwd[e] = base + i
        grown = EntityIdIxMap(BiMap(fwd))
        return grown, np.arange(base, base + len(fresh), dtype=np.int32)


class EntityMap(Generic[V]):
    """entityId-keyed data with dense-index access (EntityMap.scala:65-98)."""

    def __init__(self, data: Mapping[str, V], ix_map: EntityIdIxMap = None):
        self._data = dict(data)
        self._ix = ix_map or EntityIdIxMap.build(self._data.keys())

    def __getitem__(self, entity_id: str) -> V:
        return self._data[entity_id]

    def __contains__(self, entity_id: str) -> bool:
        return entity_id in self._data

    def __len__(self) -> int:
        return len(self._data)

    def get_by_index(self, ix: int) -> V:
        return self._data[self._ix.id_of(ix)]

    @property
    def ix_map(self) -> EntityIdIxMap:
        return self._ix
