"""Core train/eval drivers with instance lifecycle records.

Rebuilds the reference's ``CoreWorkflow``
(reference: core/src/main/scala/io/prediction/workflow/CoreWorkflow.scala:
runTrain :42-99 — EngineInstance INIT -> train -> Kryo models ->
Models.insert -> status COMPLETED; runEvaluation :101-160 —
EvaluationInstance lifecycle with rendered results). Pickle of host-side
pytrees replaces Kryo; the SparkContext is replaced by the ambient device
mesh (parallel.mesh.current_mesh), created lazily by kernels.
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import traceback
from typing import Optional, Sequence

from predictionio_tpu.core.engine import (Engine, EngineParams,
                                          WorkflowParams)
from predictionio_tpu.core.evaluation import Evaluation, MetricEvaluator
from predictionio_tpu.data.storage.base import (EngineInstance,
                                                EvaluationInstance, Model)
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.obs import TRACER, get_registry, jaxmon

logger = logging.getLogger(__name__)


def _now():
    return _dt.datetime.now(_dt.timezone.utc)


def _stage_hist():
    """Process-wide per-stage training timings (ISSUE 2): one labeled
    histogram instead of ad-hoc log lines, exposed on every /metrics
    through the registry parent chain."""
    return get_registry().histogram(
        "pio_train_stage_seconds",
        "Wall time of core-workflow stages, labeled by stage",
        buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0),
        labelnames=("stage",))


def _timed_stage(hist, stage: str):
    """Context manager: one span + one histogram observation."""
    import contextlib
    import time

    @contextlib.contextmanager
    def cm():
        t0 = time.perf_counter()
        with TRACER.span(stage):
            yield
        hist.labels(stage=stage).observe(time.perf_counter() - t0)
    return cm()


def run_train(engine: Engine, engine_params: EngineParams,
              engine_id: str = "default", engine_version: str = "0",
              engine_variant: str = "default",
              engine_factory: str = "",
              env: Optional[dict] = None,
              workflow_params: WorkflowParams = WorkflowParams()) -> str:
    """Train and persist; returns the EngineInstance id
    (CoreWorkflow.runTrain)."""
    instances = Storage.get_meta_data_engine_instances()
    ep_json = engine.engine_params_to_json(engine_params)
    instance = EngineInstance(
        id="", status="INIT", start_time=_now(), end_time=_now(),
        engine_id=engine_id, engine_version=engine_version,
        engine_variant=engine_variant, engine_factory=engine_factory,
        batch=workflow_params.batch, env=env or {},
        data_source_params=json.dumps(ep_json.get("datasource", {})),
        preparator_params=json.dumps(ep_json.get("preparator", {})),
        algorithms_params=json.dumps(ep_json.get("algorithms", [])),
        serving_params=json.dumps(ep_json.get("serving", {})))
    instance_id = instances.insert(instance)
    instance = instances.get(instance_id)
    hist = _stage_hist()
    jaxmon.install()
    from predictionio_tpu.obs.flight import FLIGHT
    FLIGHT.record("train_start", model_version=instance_id,
                  engine=engine_id)
    try:
        with TRACER.trace("train", instance=instance_id,
                          engine=engine_id):
            with _timed_stage(hist, "train"):
                result = engine.train(engine_params, workflow_params)
            if workflow_params.save_model:
                with _timed_stage(hist, "serialize"):
                    serializable = engine.make_serializable_models(
                        result, instance_id, engine_params)
                    blob = engine.serialize_models(serializable)
                with _timed_stage(hist, "persist"):
                    Storage.get_model_data_models().insert(
                        Model(instance_id, blob))
            instances.update(instance.with_(status="COMPLETED",
                                            end_time=_now()))
        FLIGHT.record("train_end", model_version=instance_id,
                      status="COMPLETED")
        logger.info("Training completed: engine instance %s", instance_id)
        return instance_id
    except Exception:
        logger.error("Training failed:\n%s", traceback.format_exc())
        FLIGHT.record("train_end", model_version=instance_id,
                      status="ABORTED")
        instances.update(instance.with_(status="ABORTED", end_time=_now()))
        raise


def run_evaluation(engine: Engine, evaluation: Evaluation,
                   engine_params_list: Sequence[EngineParams],
                   evaluation_class: str = "",
                   engine_params_generator_class: str = "",
                   env: Optional[dict] = None,
                   output_path: Optional[str] = None,
                   workflow_params: WorkflowParams = WorkflowParams()) -> str:
    """Evaluate a params sweep and record results; returns the
    EvaluationInstance id (CoreWorkflow.runEvaluation)."""
    dao = Storage.get_meta_data_evaluation_instances()
    instance = EvaluationInstance(
        status="INIT", start_time=_now(), end_time=_now(),
        evaluation_class=evaluation_class,
        engine_params_generator_class=engine_params_generator_class,
        batch=workflow_params.batch, env=env or {})
    instance_id = dao.insert(instance)
    instance = dao.get(instance_id)
    try:
        assert evaluation.metric is not None, "Evaluation.metric must be set"
        evaluator = MetricEvaluator(evaluation.metric,
                                    list(evaluation.metrics),
                                    output_path=output_path)
        with TRACER.trace("evaluation", instance=instance_id), \
                _timed_stage(_stage_hist(), "evaluate"):
            result = evaluator.evaluate_base(engine, engine_params_list,
                                             workflow_params)
        dao.update(instance.with_(
            status="EVALCOMPLETED", end_time=_now(),
            evaluator_results=result.one_liner(),
            evaluator_results_html=result.to_html(),
            evaluator_results_json=result.to_json(engine)))
        logger.info("Evaluation completed: %s", result.one_liner())
        return instance_id
    except Exception:
        logger.error("Evaluation failed:\n%s", traceback.format_exc())
        dao.update(instance.with_(status="ABORTED", end_time=_now()))
        raise
