"""Training/eval workflow runtime (L4)."""

from predictionio_tpu.workflow.core_workflow import (run_evaluation,
                                                     run_train)
from predictionio_tpu.workflow.create_workflow import (WorkflowConfig,
                                                       create_workflow_main)

__all__ = ["run_train", "run_evaluation", "WorkflowConfig",
           "create_workflow_main"]
