"""Dev/test harness: run an arbitrary function through the evaluation
plumbing.

Rebuilds the reference's ``FakeWorkflow``/``FakeRun``
(reference: core/src/main/scala/io/prediction/workflow/FakeWorkflow.scala:93+):
a developer can push any `fn(mesh) -> None` through the full evaluation
lifecycle (EvaluationInstance records included) without writing DASE
components — useful for smoke-testing storage + mesh wiring.
"""

from __future__ import annotations

from typing import Callable

from predictionio_tpu.core import (DataSource, Engine, EngineParams,
                                   Evaluation, FirstServing,
                                   IdentityPreparator, LAlgorithm,
                                   ZeroMetric)
from predictionio_tpu.parallel.mesh import MeshContext, current_mesh


class _FakeDataSource(DataSource):
    def read_training(self):
        return None

    def read_eval(self):
        return [(None, None, [(None, None)])]


class _FakeAlgorithm(LAlgorithm):
    fn: Callable[[MeshContext], None] = staticmethod(lambda mesh: None)

    def train(self, pd):
        return None

    def predict(self, model, query):
        type(self).fn(current_mesh())
        return None


class FakeRun(Evaluation):
    """Evaluation that just runs `fn(mesh)` once (FakeWorkflow.scala FakeRun)."""

    def __init__(self, fn: Callable[[MeshContext], None]):
        algo_cls = type("_FakeAlgo", (_FakeAlgorithm,),
                        {"fn": staticmethod(fn)})
        self.engine = Engine({"": _FakeDataSource}, {"": IdentityPreparator},
                             {"": algo_cls}, {"": FirstServing})
        self.metric = ZeroMetric()
        self.engine_params_list = [EngineParams()]


def run_fake(fn: Callable[[MeshContext], None]) -> str:
    """Run fn through the evaluation workflow; returns the
    EvaluationInstance id."""
    from predictionio_tpu.workflow.core_workflow import run_evaluation
    fake = FakeRun(fn)
    return run_evaluation(fake.engine, fake, fake.engine_params_list,
                          evaluation_class="FakeRun")
