"""Workflow entry point: engine.json variant -> train/eval run.

Rebuilds the reference's ``CreateWorkflow`` main
(reference: core/src/main/scala/io/prediction/workflow/CreateWorkflow.scala:
WorkflowConfig :40-58, main :132-266): parse the engine variant JSON,
resolve the engine factory (registry lookup replacing JVM reflection —
WorkflowUtils.scala:62), extract EngineParams, and dispatch to the train or
evaluation driver. No spark-submit: the trainer runs in-process on the
ambient mesh (SURVEY.md section 2.9 driver/executor row).
"""

from __future__ import annotations

import importlib
import json
import logging
import os
from dataclasses import dataclass
from typing import Optional

from predictionio_tpu.core.engine import WorkflowParams
from predictionio_tpu.models import get_engine_factory
from predictionio_tpu.workflow.core_workflow import (run_evaluation,
                                                     run_train)

logger = logging.getLogger(__name__)


@dataclass
class WorkflowConfig:
    """(CreateWorkflow.scala:40-58)"""
    batch: str = ""
    engine_id: str = "default"
    engine_version: str = "0"
    engine_variant: str = "engine.json"
    engine_factory: Optional[str] = None   # overrides variant's field
    evaluation_class: Optional[str] = None
    engine_params_generator_class: Optional[str] = None
    engine_params_key: Optional[str] = None
    verbosity: int = 0
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    verbose: bool = False
    json_extractor: str = "Both"  # accepted for CLI parity; JSON is native


def load_variant(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def resolve_class(dotted: str):
    module_name, _, attr = dotted.rpartition(".")
    if not module_name:
        raise ValueError(f"not a dotted class path: {dotted!r}")
    return getattr(importlib.import_module(module_name), attr)


def workflow_params_from_config(config: WorkflowConfig) -> WorkflowParams:
    return WorkflowParams(
        batch=config.batch, verbose=config.verbosity,
        skip_sanity_check=config.skip_sanity_check,
        stop_after_read=config.stop_after_read,
        stop_after_prepare=config.stop_after_prepare)


def create_workflow_main(config: WorkflowConfig) -> str:
    """Returns the created instance id (engine or evaluation)."""
    if config.evaluation_class:
        return _run_evaluation(config)
    return _run_train(config)


def _engine_and_params(config: WorkflowConfig):
    variant = load_variant(config.engine_variant)
    factory_name = config.engine_factory or variant.get("engineFactory")
    if not factory_name:
        raise ValueError(
            "engineFactory must be given in the engine variant or via "
            "--engine-factory")
    factory = get_engine_factory(factory_name)
    engine = factory.apply()
    if config.engine_params_key:
        # named programmatic params from the factory instead of the
        # variant JSON (CreateWorkflow.scala:216-220)
        engine_params = factory.engine_params(config.engine_params_key)
    else:
        engine_params = engine.json_to_engine_params(variant)
    return variant, factory_name, engine, engine_params


def _run_train(config: WorkflowConfig) -> str:
    variant, factory_name, engine, engine_params = _engine_and_params(config)
    return run_train(
        engine, engine_params,
        engine_id=variant.get("id", config.engine_id),
        engine_version=config.engine_version,
        engine_variant=config.engine_variant,
        engine_factory=factory_name,
        env={k: v for k, v in os.environ.items() if k.startswith("PIO_")},
        workflow_params=workflow_params_from_config(config))


def _run_evaluation(config: WorkflowConfig) -> str:
    evaluation_cls = resolve_class(config.evaluation_class)
    evaluation = (evaluation_cls() if isinstance(evaluation_cls, type)
                  else evaluation_cls)
    engine = evaluation.engine
    if engine is None:
        raise ValueError(
            f"{config.evaluation_class} does not define .engine")
    if config.engine_params_generator_class:
        gen_cls = resolve_class(config.engine_params_generator_class)
        generator = gen_cls() if isinstance(gen_cls, type) else gen_cls
    else:
        generator = evaluation  # Evaluation may carry its own list
    params_list = list(getattr(generator, "engine_params_list", ()))
    if not params_list:
        raise ValueError("engine_params_list is empty")
    return run_evaluation(
        engine, evaluation, params_list,
        evaluation_class=config.evaluation_class or "",
        engine_params_generator_class=(
            config.engine_params_generator_class or ""),
        env={k: v for k, v in os.environ.items() if k.startswith("PIO_")},
        workflow_params=workflow_params_from_config(config))
