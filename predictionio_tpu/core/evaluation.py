"""Evaluation & hyperparameter tuning.

Rebuilds the reference's ``Evaluation`` trait, ``EngineParamsGenerator`` and
``MetricEvaluator`` (reference: controller/Evaluation.scala:88,
controller/EngineParamsGenerator.scala:27, controller/MetricEvaluator.scala:215
and MetricEvaluatorResult :38-80): run the engine's batch_eval over a list of
EngineParams, score each with the primary metric, pick the best setting, and
render one-line / JSON / HTML reports persisted on the EvaluationInstance.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from predictionio_tpu.core.engine import Engine, EngineParams, WorkflowParams
from predictionio_tpu.core.metrics import Metric

logger = logging.getLogger(__name__)


class EngineParamsGenerator:
    """Provides the list of EngineParams to sweep
    (controller/EngineParamsGenerator.scala:27)."""

    engine_params_list: Sequence[EngineParams] = ()


class Evaluation:
    """Binds an engine to its tuning metric(s)
    (controller/Evaluation.scala:88)."""

    engine: Optional[Engine] = None
    metric: Optional[Metric] = None
    metrics: Sequence[Metric] = ()   # additional informational metrics

    @property
    def evaluator(self) -> "MetricEvaluator":
        assert self.metric is not None, "Evaluation.metric must be set"
        return MetricEvaluator(self.metric, list(self.metrics))


@dataclass(frozen=True)
class MetricScores:
    score: float
    other_scores: Sequence[float]
    engine_params: EngineParams


@dataclass
class MetricEvaluatorResult:
    """(controller/MetricEvaluator.scala:38-80)"""
    best_score: MetricScores
    best_engine_params: EngineParams
    best_idx: int
    metric_header: str
    other_metric_headers: Sequence[str]
    engine_params_scores: List[Tuple[EngineParams, MetricScores]] = field(
        default_factory=list)

    def one_liner(self) -> str:
        return (f"[{self.metric_header}] best: {self.best_score.score:.6f} "
                f"(params set {self.best_idx} of "
                f"{len(self.engine_params_scores)})")

    def to_json(self, engine: Optional[Engine] = None) -> str:
        def ep_json(ep: EngineParams):
            if engine is not None:
                return engine.engine_params_to_json(ep)
            return repr(ep)
        return json.dumps({
            "metric": self.metric_header,
            "otherMetrics": list(self.other_metric_headers),
            "bestScore": self.best_score.score,
            "bestIndex": self.best_idx,
            "bestEngineParams": ep_json(self.best_engine_params),
            "scores": [
                {"engineParams": ep_json(ep), "score": s.score,
                 "otherScores": list(s.other_scores)}
                for ep, s in self.engine_params_scores],
        }, indent=2)

    def to_html(self) -> str:
        rows = "".join(
            f"<tr><td>{i}</td><td>{s.score:.6f}</td>"
            f"<td><pre>{ep}</pre></td></tr>"
            for i, (ep, s) in enumerate(self.engine_params_scores))
        return (f"<html><body><h1>Metric: {self.metric_header}</h1>"
                f"<p>{self.one_liner()}</p>"
                f"<table border=1><tr><th>#</th><th>score</th>"
                f"<th>params</th></tr>{rows}</table></body></html>")


class MetricEvaluator:
    """Scores batch_eval output and picks the best engine params
    (controller/MetricEvaluator.scala:215 evaluateBase)."""

    def __init__(self, metric: Metric,
                 other_metrics: Sequence[Metric] = (),
                 output_path: Optional[str] = None):
        self.metric = metric
        self.other_metrics = list(other_metrics)
        self.output_path = output_path  # best.json target dir

    def evaluate_base(self, engine: Engine,
                      engine_params_list: Sequence[EngineParams],
                      workflow_params: WorkflowParams = WorkflowParams()
                      ) -> MetricEvaluatorResult:
        evaluated = engine.batch_eval(engine_params_list, workflow_params)
        scores: List[Tuple[EngineParams, MetricScores]] = []
        for ep, eval_data in evaluated:
            score = self.metric.calculate(eval_data)
            others = [m.calculate(eval_data) for m in self.other_metrics]
            scores.append((ep, MetricScores(score, others, ep)))
            logger.info("Params %s -> %s = %.6f",
                        ep.algorithm_params_list, self.metric.header(), score)
        best_idx = 0
        for i in range(1, len(scores)):
            if self.metric.compare(scores[i][1].score,
                                   scores[best_idx][1].score) > 0:
                best_idx = i
        best_ep, best_score = scores[best_idx]
        result = MetricEvaluatorResult(
            best_score=best_score, best_engine_params=best_ep,
            best_idx=best_idx, metric_header=self.metric.header(),
            other_metric_headers=[m.header() for m in self.other_metrics],
            engine_params_scores=scores)
        if self.output_path:
            os.makedirs(self.output_path, exist_ok=True)
            # best.json lets `pio train` pick up tuned params
            # (MetricEvaluator.scala writes best.json the same way)
            best = engine.engine_params_to_json(best_ep)
            with open(os.path.join(self.output_path, "best.json"), "w") as f:
                json.dump(best, f, indent=2)
        return result
