"""k-fold cross-validation split helper.

Rebuilds the reference's ``CommonHelperFunctions.splitData``
(reference: e2/src/main/scala/io/prediction/e2/evaluation/CrossValidation.scala):
fold membership by ``index % k``, emitting (trainingData, evalInfo,
[(query, actual)]) per fold — the shape DataSource.read_eval returns.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple, TypeVar

D = TypeVar("D")
TD = TypeVar("TD")
EI = TypeVar("EI")
Q = TypeVar("Q")
A = TypeVar("A")


def split_data(eval_k: int, dataset: Sequence[D], evaluator_info: EI,
               training_data_creator: Callable[[List[D]], TD],
               query_creator: Callable[[D], Q],
               actual_creator: Callable[[D], A]
               ) -> List[Tuple[TD, EI, List[Tuple[Q, A]]]]:
    folds = []
    for fold in range(eval_k):
        training = [d for i, d in enumerate(dataset) if i % eval_k != fold]
        testing = [d for i, d in enumerate(dataset) if i % eval_k == fold]
        folds.append((
            training_data_creator(training),
            evaluator_info,
            [(query_creator(d), actual_creator(d)) for d in testing]))
    return folds
