"""Typed component parameters.

Rebuilds the reference's ``Params`` marker + JSON extraction
(reference: core/src/main/scala/io/prediction/controller/Params.scala:23,
workflow/WorkflowUtils.scala:132-204 `extractParams`). Components declare a
``@dataclass`` subclassing ``Params``; engine.json ``params`` blocks are
deserialized into them by field name (the Doer/reflection analog, but via
dataclass introspection instead of JVM reflection).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import types
import typing
from typing import Any, Dict, Optional, Type

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Params:
    """Marker base class for component parameters."""


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    pass


def _coerce_field(value, hint, where: str):
    """Validate/convert one JSON value against a field's type annotation —
    the typed-extraction step of the reference's JsonExtractor
    (workflow/JsonExtractor.scala + json4s strict extraction): wrong
    types fail HERE with the field named, instead of deep inside a jitted
    kernel. JSON-native conversions only: arrays become tuples for
    Tuple[...] fields, ints widen to float; strings never silently parse
    into numbers. Unrecognized annotations (domain classes, Any, dicts)
    pass through unvalidated."""
    if hint is Any or hint is None:
        return value
    origin = typing.get_origin(hint)
    args = typing.get_args(hint)
    # typing.Union AND PEP 604 `X | Y` (types.UnionType on 3.10+)
    if origin is typing.Union or origin is types.UnionType:
        if value is None and type(None) in args:
            return None
        errors = []
        for a in args:
            if a is type(None):
                continue
            try:
                return _coerce_field(value, a, where)
            except (TypeError, ValueError) as e:
                errors.append(str(e))
        # arm messages already carry the `where` prefix
        raise ValueError(" / ".join(errors))
    if origin in (tuple,):
        if not isinstance(value, (list, tuple)):
            raise ValueError(f"{where}: expected an array, got "
                             f"{type(value).__name__} ({value!r})")
        if args and args[-1] is Ellipsis:
            elem = args[0]
            return tuple(_coerce_field(v, elem, f"{where}[{i}]")
                         for i, v in enumerate(value))
        if args and len(value) != len(args):
            raise ValueError(f"{where}: expected {len(args)} elements, "
                             f"got {len(value)}")
        return tuple(_coerce_field(v, a, f"{where}[{i}]")
                     for i, (v, a) in enumerate(zip(value, args))) \
            if args else tuple(value)
    if origin in (list,):
        if not isinstance(value, (list, tuple)):
            raise ValueError(f"{where}: expected an array, got "
                             f"{type(value).__name__} ({value!r})")
        elem = args[0] if args else Any
        return [_coerce_field(v, elem, f"{where}[{i}]")
                for i, v in enumerate(value)]
    if hint is bool:
        if not isinstance(value, bool):
            raise ValueError(f"{where}: expected a boolean, got "
                             f"{type(value).__name__} ({value!r})")
        return value
    if hint is int:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ValueError(f"{where}: expected an integer, got "
                             f"{type(value).__name__} ({value!r})")
        if isinstance(value, float):
            if not value.is_integer():
                raise ValueError(f"{where}: expected an integer, got "
                                 f"{value!r}")
            return int(value)
        return value
    if hint is float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ValueError(f"{where}: expected a number, got "
                             f"{type(value).__name__} ({value!r})")
        return float(value)
    if hint is str:
        if not isinstance(value, str):
            raise ValueError(f"{where}: expected a string, got "
                             f"{type(value).__name__} ({value!r})")
        return value
    return value    # domain classes, dicts, Any: pass through


def params_from_dict(cls: Optional[Type[Params]], d: Optional[Dict[str, Any]]):
    """Build a Params instance from a JSON dict, tolerating missing optional
    fields, rejecting unknown ones, and type-checking every provided value
    against the dataclass annotations (matching json4s strict
    extraction)."""
    if cls is None or cls is EmptyParams:
        return EmptyParams()
    d = d or {}
    if not dataclasses.is_dataclass(cls):
        raise TypeError(f"params class {cls} must be a dataclass")
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(d) - names
    if unknown:
        raise ValueError(
            f"Unknown parameter(s) {sorted(unknown)} for {cls.__name__}; "
            f"expected subset of {sorted(names)}")
    missing = [f.name for f in dataclasses.fields(cls)
               if f.name not in d and f.default is dataclasses.MISSING
               and f.default_factory is dataclasses.MISSING]
    if missing:
        raise ValueError(
            f"Missing required parameter(s) {missing} for {cls.__name__}")
    try:
        hints = typing.get_type_hints(cls)
    except NameError as e:
        # a genuinely unresolvable annotation (typo, missing import):
        # downgrade to unvalidated extraction, but say so — silently
        # skipping ALL checks would defeat the feature invisibly
        logger.warning("cannot resolve type annotations of %s (%s); "
                       "params extracted without type validation",
                       cls.__name__, e)
        hints = {}
    coerced = {k: _coerce_field(v, hints.get(k, Any),
                                f"{cls.__name__}.{k}")
               for k, v in d.items()}
    return cls(**coerced)


def params_to_dict(p: Optional[Params]) -> Dict[str, Any]:
    if p is None:
        return {}
    if dataclasses.is_dataclass(p):
        return dataclasses.asdict(p)
    if isinstance(p, dict):
        return dict(p)
    raise TypeError(f"cannot serialize params {p!r}")


def params_to_json(p: Optional[Params]) -> str:
    return json.dumps(params_to_dict(p), sort_keys=True)


def params_from_json(cls: Optional[Type[Params]], s: str):
    return params_from_dict(cls, json.loads(s) if s else {})
