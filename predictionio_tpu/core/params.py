"""Typed component parameters.

Rebuilds the reference's ``Params`` marker + JSON extraction
(reference: core/src/main/scala/io/prediction/controller/Params.scala:23,
workflow/WorkflowUtils.scala:132-204 `extractParams`). Components declare a
``@dataclass`` subclassing ``Params``; engine.json ``params`` blocks are
deserialized into them by field name (the Doer/reflection analog, but via
dataclass introspection instead of JVM reflection).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional, Type


@dataclasses.dataclass(frozen=True)
class Params:
    """Marker base class for component parameters."""


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    pass


def params_from_dict(cls: Optional[Type[Params]], d: Optional[Dict[str, Any]]):
    """Build a Params instance from a JSON dict, tolerating missing optional
    fields and rejecting unknown ones (matching json4s strict extraction)."""
    if cls is None or cls is EmptyParams:
        return EmptyParams()
    d = d or {}
    if not dataclasses.is_dataclass(cls):
        raise TypeError(f"params class {cls} must be a dataclass")
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(d) - names
    if unknown:
        raise ValueError(
            f"Unknown parameter(s) {sorted(unknown)} for {cls.__name__}; "
            f"expected subset of {sorted(names)}")
    missing = [f.name for f in dataclasses.fields(cls)
               if f.name not in d and f.default is dataclasses.MISSING
               and f.default_factory is dataclasses.MISSING]
    if missing:
        raise ValueError(
            f"Missing required parameter(s) {missing} for {cls.__name__}")
    return cls(**d)


def params_to_dict(p: Optional[Params]) -> Dict[str, Any]:
    if p is None:
        return {}
    if dataclasses.is_dataclass(p):
        return dataclasses.asdict(p)
    if isinstance(p, dict):
        return dict(p)
    raise TypeError(f"cannot serialize params {p!r}")


def params_to_json(p: Optional[Params]) -> str:
    return json.dumps(params_to_dict(p), sort_keys=True)


def params_from_json(cls: Optional[Type[Params]], s: str):
    return params_from_dict(cls, json.loads(s) if s else {})
