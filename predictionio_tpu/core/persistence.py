"""Model persistence contracts — the three checkpoint modes.

Rebuilds the reference's persistence design (SURVEY.md section 5
"Checkpoint / resume"; reference: controller/PersistentModel.scala:64+,
workflow/PersistentModelManifest.scala:18, controller/Engine.scala:208-230):

  1. automatic  — the trained model object is serialized by the framework
                  into the MODELDATA repository (the Kryo analog is pickle;
                  device arrays are converted to host numpy first).
  2. manual     — the model implements PersistentModel.save(); only a
                  PersistentModelManifest naming its loader is stored, and
                  the loader restores it at deploy (the orbax/tensorstore-
                  style sharded-checkpoint path for mesh models).
  3. retrain    — make_persistent_model returns RETRAIN; deploy re-runs
                  read/prepare/train.
"""

from __future__ import annotations

import abc
import importlib
from dataclasses import dataclass
from typing import Any, Optional


class _Retrain:
    """Sentinel: do not persist; re-train at deploy (the Unit-model case)."""

    _instance: Optional["_Retrain"] = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "RETRAIN"


RETRAIN = _Retrain()


@dataclass(frozen=True)
class PersistentModelManifest:
    """Stored in place of the model blob when the model persists itself
    (workflow/PersistentModelManifest.scala:18). ``loader`` is the
    fully-qualified name of a PersistentModelLoader subclass or of the
    model class itself (which must expose ``load``)."""
    loader: str


class PersistentModel(abc.ABC):
    """Mix-in for models that manage their own storage
    (controller/PersistentModel.scala:64)."""

    @abc.abstractmethod
    def save(self, instance_id: str, params: Any) -> bool:
        """Persist; return True to store only a manifest, False to fall back
        to automatic serialization (PersistentModel.scala docs)."""

    @classmethod
    def loader_name(cls) -> str:
        return f"{cls.__module__}.{cls.__qualname__}"


class PersistentModelLoader(abc.ABC):
    """Restores a PersistentModel at deploy time
    (controller/PersistentModel.scala PersistentModelLoader)."""

    @abc.abstractmethod
    def load(self, instance_id: str, params: Any) -> Any: ...


def resolve_loader(qualname: str):
    """Import the loader named by a manifest (the reflection analog;
    workflow/WorkflowUtils.scala:350 getPersistentModel)."""
    module_name, _, attr = qualname.rpartition(".")
    obj = getattr(importlib.import_module(module_name), attr)
    return obj


def load_persistent_model(manifest: PersistentModelManifest,
                          instance_id: str, params: Any):
    loader = resolve_loader(manifest.loader)
    if isinstance(loader, type) and issubclass(loader, PersistentModelLoader):
        return loader().load(instance_id, params)
    load = getattr(loader, "load", None)
    if load is None:
        raise TypeError(f"{manifest.loader} has no load()")
    return load(instance_id, params)
