"""DASE base components: DataSource, Preparator, Algorithm, Serving.

Rebuilds the reference's controller component hierarchy
(reference: core/src/main/scala/io/prediction/core/Base*.scala and
controller/{PDataSource,LDataSource,PPreparator,LPreparator,PAlgorithm,
P2LAlgorithm,LAlgorithm,LServing}.scala).

The reference's L / P2L / P taxonomy encodes *where the model lives* in a
Spark cluster (driver-local / local-after-cluster-train / RDD-distributed).
The TPU-native translation (SURVEY.md section 2.9) is model *placement*:

  - ``LAlgorithm``   -> model in host RAM; predict runs on host.
  - ``P2LAlgorithm`` -> model trained on the mesh, gathered to one
                        device/host; predict is a jitted single-device call.
  - ``PAlgorithm``   -> model stays sharded across the mesh (jax.Arrays with
                        non-replicated sharding); predict is a jitted gather
                        on the mesh.

All three share one Python base class; the placement split shows up in
``placement`` and in how ``make_persistent_model`` treats the model, not in
the train/predict call signatures (XLA makes single- and multi-device code
identical at this layer).
"""

from __future__ import annotations

import abc
from typing import Any, Generic, Iterable, List, Optional, Sequence, Tuple, TypeVar

from predictionio_tpu.core.params import Params
from predictionio_tpu.core.persistence import PersistentModel, RETRAIN

TD = TypeVar("TD")  # training data
EI = TypeVar("EI")  # evaluation info
PD = TypeVar("PD")  # prepared data
M = TypeVar("M")    # model
Q = TypeVar("Q")    # query
P = TypeVar("P")    # predicted result
A = TypeVar("A")    # actual result


class Doer:
    """Component instantiation: ctor(params) if accepted, else ctor()
    (reference: core/AbstractDoer.scala:43-65 — registry call, not JVM
    reflection)."""

    @staticmethod
    def apply(cls, params: Optional[Params] = None):
        if params is None:
            return cls()
        # Decide by signature inspection, not by catching TypeError: a
        # TypeError raised *inside* a user's __init__ must propagate rather
        # than silently dropping their params.
        import inspect
        try:
            sig = inspect.signature(cls)
            takes_params = any(
                p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD,
                           p.VAR_POSITIONAL)
                for p in sig.parameters.values())
        except (ValueError, TypeError):   # builtins without signatures
            takes_params = True
        return cls(params) if takes_params else cls()


class SanityCheck(abc.ABC):
    """Optional per-stage data check (controller/SanityCheck.scala:24-29),
    invoked by Engine.train after each stage."""

    @abc.abstractmethod
    def sanity_check(self) -> None: ...


class DataSource(Generic[TD, EI, Q, A], abc.ABC):
    """Reads training and evaluation data from the event store
    (controller/PDataSource.scala:34-56). TPU note: return host-side
    structures or already-sharded arrays; the parallel.dataset helpers
    build mesh-sharded jax.Arrays from event streams."""

    def __init__(self, params: Optional[Params] = None):
        self.params = params

    @abc.abstractmethod
    def read_training(self) -> TD: ...

    def read_eval(self) -> List[Tuple[TD, EI, Iterable[Tuple[Q, A]]]]:
        """Eval sets: (trainingData, evalInfo, [(query, actual)])."""
        return []


class Preparator(Generic[TD, PD], abc.ABC):
    """(controller/PPreparator.scala:30)"""

    def __init__(self, params: Optional[Params] = None):
        self.params = params

    @abc.abstractmethod
    def prepare(self, training_data: TD) -> PD: ...


class IdentityPreparator(Preparator):
    """(controller/IdentityPreparator.scala:31)"""

    def prepare(self, training_data):
        return training_data


class Algorithm(Generic[PD, M, Q, P], abc.ABC):
    """One trainable + queryable model (core/BaseAlgorithm.scala:55-123).

    ``placement`` declares where the trained model lives:
      'host' (L), 'device' (P2L), 'mesh' (P).
    """

    placement: str = "device"

    def __init__(self, params: Optional[Params] = None):
        self.params = params

    @abc.abstractmethod
    def train(self, prepared_data: PD) -> M: ...

    @abc.abstractmethod
    def predict(self, model: M, query: Q) -> P: ...

    def batch_predict(self, model: M, queries: Sequence[Tuple[int, Q]]
                      ) -> List[Tuple[int, P]]:
        """Bulk predict for evaluation. Default maps predict() — the
        P2LAlgorithm.batchPredict default (controller/P2LAlgorithm.scala:43).
        TPU algorithms override this with a single jitted batched call."""
        return [(ix, self.predict(model, q)) for ix, q in queries]

    # -- persistence contract (core/BaseAlgorithm.scala:108) ----------------
    def make_persistent_model(self, model: M):
        """Decide the persistence mode for a trained model. Returns either
        the model itself (serialized automatically), a
        PersistentModelManifest (model saved itself; reflect loader at
        deploy), or RETRAIN (re-train at deploy time)."""
        if isinstance(model, PersistentModel):
            return model  # engine core will call .save() and store a manifest
        if self.placement == "mesh":
            # a sharded model can't be naively pickled; default to retrain
            # unless it manages its own persistence (PAlgorithm.scala:109)
            return RETRAIN
        return model

    @property
    def query_class(self):
        """Query type for JSON decode at serve time; None = raw dict."""
        return getattr(self, "QUERY_CLASS", None)


class LAlgorithm(Algorithm[PD, M, Q, P]):
    """Model lives in host RAM (controller/LAlgorithm.scala:42-129)."""
    placement = "host"


class P2LAlgorithm(Algorithm[PD, M, Q, P]):
    """Mesh-trained, single-device model (controller/P2LAlgorithm.scala)."""
    placement = "device"


class PAlgorithm(Algorithm[PD, M, Q, P]):
    """Model sharded across the mesh (controller/PAlgorithm.scala:44-125)."""
    placement = "mesh"

    def batch_predict(self, model, queries):
        raise NotImplementedError(
            "PAlgorithm does not support batch_predict by default "
            "(controller/PAlgorithm.scala:44); override it for evaluation.")


class Serving(Generic[Q, P], abc.ABC):
    """Combines predictions of all algorithms into one result
    (controller/LServing.scala:27-51)."""

    def __init__(self, params: Optional[Params] = None):
        self.params = params

    def supplement(self, query: Q) -> Q:
        """Pre-process query before algorithms see it."""
        return query

    @abc.abstractmethod
    def serve(self, query: Q, predictions: Sequence[P]) -> P: ...


class FirstServing(Serving):
    """Serve the first algorithm's prediction
    (controller/LFirstServing.scala:25)."""

    def serve(self, query, predictions):
        return predictions[0]


class AverageServing(Serving):
    """Average numeric predictions (controller/LAverageServing.scala:25)."""

    def serve(self, query, predictions):
        return sum(predictions) / len(predictions)


def run_sanity_check(obj: Any, enabled: bool) -> None:
    if enabled and isinstance(obj, SanityCheck):
        obj.sanity_check()
