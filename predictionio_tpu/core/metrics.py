"""Metric base classes for evaluation.

Rebuilds the reference's metric hierarchy
(reference: core/src/main/scala/io/prediction/controller/Metric.scala:36 and
the StatsMetricHelper `sc.union(...).stats()` pattern). The Spark StatCounter
becomes a host-side numpy reduction — metric math is tiny compared to
training, so it stays off-device.
"""

from __future__ import annotations

import abc
import math
from typing import Generic, List, Optional, Tuple, TypeVar

EI = TypeVar("EI")
Q = TypeVar("Q")
P = TypeVar("P")
A = TypeVar("A")

EvalDataSet = List[Tuple[EI, List[Tuple[Q, P, A]]]]


class Metric(Generic[EI, Q, P, A], abc.ABC):
    """Computes one score over the full evaluation data set; results are
    compared with ``compare`` (default: greater is better)."""

    def header(self) -> str:
        return type(self).__name__

    @abc.abstractmethod
    def calculate(self, eval_data: EvalDataSet) -> float: ...

    def compare(self, a: float, b: float) -> int:
        if a == b or (math.isnan(a) and math.isnan(b)):
            return 0
        if math.isnan(a):
            return -1
        if math.isnan(b):
            return 1
        return 1 if a > b else -1


def _all_qpa(eval_data: EvalDataSet):
    for _, qpa in eval_data:
        yield from qpa


class AverageMetric(Metric[EI, Q, P, A]):
    """Mean of a per-(Q,P,A) score (Metric.scala AverageMetric)."""

    @abc.abstractmethod
    def calculate_one(self, query: Q, predicted: P, actual: A) -> float: ...

    def calculate(self, eval_data: EvalDataSet) -> float:
        vals = [self.calculate_one(q, p, a) for q, p, a in _all_qpa(eval_data)]
        return float("nan") if not vals else sum(vals) / len(vals)


class OptionAverageMetric(Metric[EI, Q, P, A]):
    """Mean over scores that are not None (Metric.scala OptionAverageMetric)."""

    @abc.abstractmethod
    def calculate_one(self, query: Q, predicted: P, actual: A
                      ) -> Optional[float]: ...

    def calculate(self, eval_data: EvalDataSet) -> float:
        vals = [v for v in (self.calculate_one(q, p, a)
                            for q, p, a in _all_qpa(eval_data))
                if v is not None]
        return float("nan") if not vals else sum(vals) / len(vals)


def _stdev(vals: List[float]) -> float:
    # population stdev, matching Spark StatCounter.stdev
    if not vals:
        return float("nan")
    mean = sum(vals) / len(vals)
    return math.sqrt(sum((v - mean) ** 2 for v in vals) / len(vals))


class StdevMetric(Metric[EI, Q, P, A]):
    @abc.abstractmethod
    def calculate_one(self, query: Q, predicted: P, actual: A) -> float: ...

    def calculate(self, eval_data: EvalDataSet) -> float:
        return _stdev([self.calculate_one(q, p, a)
                       for q, p, a in _all_qpa(eval_data)])


class OptionStdevMetric(Metric[EI, Q, P, A]):
    @abc.abstractmethod
    def calculate_one(self, query: Q, predicted: P, actual: A
                      ) -> Optional[float]: ...

    def calculate(self, eval_data: EvalDataSet) -> float:
        vals = [v for v in (self.calculate_one(q, p, a)
                            for q, p, a in _all_qpa(eval_data))
                if v is not None]
        return _stdev(vals)


class SumMetric(Metric[EI, Q, P, A]):
    @abc.abstractmethod
    def calculate_one(self, query: Q, predicted: P, actual: A) -> float: ...

    def calculate(self, eval_data: EvalDataSet) -> float:
        return float(sum(self.calculate_one(q, p, a)
                         for q, p, a in _all_qpa(eval_data)))


class ZeroMetric(Metric[EI, Q, P, A]):
    """Always 0 — placeholder when only side-effects matter."""

    def calculate(self, eval_data: EvalDataSet) -> float:
        return 0.0


class QPAMetric(Generic[Q, P, A], abc.ABC):
    """Single-(query, prediction, actual) scoring hook
    (controller/Metric.scala QPAMetric) — compose with the aggregate
    metrics above via their calculate_one."""

    @abc.abstractmethod
    def calculate(self, query: Q, predicted: P, actual: A) -> float: ...
