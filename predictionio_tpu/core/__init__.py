"""Core controller API: the DASE component model and engine pipelines.

Rebuilds the reference's `io.prediction.controller` / `io.prediction.core`
packages (reference: core/src/main/scala/io/prediction/controller/).
"""

from predictionio_tpu.core.params import (EmptyParams, Params, params_to_json,
                                          params_from_json)
from predictionio_tpu.core.base import (Algorithm, DataSource, FirstServing,
                                        AverageServing, IdentityPreparator,
                                        LAlgorithm, P2LAlgorithm, PAlgorithm,
                                        Preparator, SanityCheck, Serving)
from predictionio_tpu.core.persistence import (PersistentModel,
                                               PersistentModelLoader,
                                               PersistentModelManifest,
                                               RETRAIN)
from predictionio_tpu.core.engine import (Engine, EngineFactory, EngineParams,
                                          SimpleEngine, TrainResult,
                                          WorkflowParams)
from predictionio_tpu.core.metrics import (AverageMetric, Metric,
                                           OptionAverageMetric,
                                           OptionStdevMetric, StdevMetric,
                                           SumMetric, ZeroMetric)
from predictionio_tpu.core.evaluation import (Evaluation,
                                              EngineParamsGenerator,
                                              MetricEvaluator,
                                              MetricEvaluatorResult)
from predictionio_tpu.core.fast_eval import FastEvalEngine

__all__ = [
    "Params", "EmptyParams", "params_to_json", "params_from_json",
    "DataSource", "Preparator", "IdentityPreparator", "Algorithm",
    "LAlgorithm", "P2LAlgorithm", "PAlgorithm", "Serving", "FirstServing",
    "AverageServing", "SanityCheck",
    "PersistentModel", "PersistentModelLoader", "PersistentModelManifest",
    "RETRAIN",
    "Engine", "EngineFactory", "EngineParams", "SimpleEngine", "TrainResult",
    "WorkflowParams",
    "Metric", "AverageMetric", "OptionAverageMetric", "StdevMetric",
    "OptionStdevMetric", "SumMetric", "ZeroMetric",
    "Evaluation", "EngineParamsGenerator", "MetricEvaluator",
    "MetricEvaluatorResult", "FastEvalEngine",
]
