"""The Engine: chains DASE components into train / eval pipelines.

Rebuilds the reference's ``Engine``
(reference: core/src/main/scala/io/prediction/controller/Engine.scala —
static train pipeline with sanity checks + stop-gates :621-708, eval
cross-product :726-816, params-from-JSON :353, prepareDeploy :196-265)
and ``WorkflowParams`` (workflow/WorkflowParams.scala:29-37).

TPU note: the pipeline itself is host-side control flow; all device work
happens inside component methods. `serialize_models` converts any jax.Array
leaves to host numpy before pickling (the Kryo analog), so models trained on
the mesh persist portably; mesh-resident (PAlgorithm) models instead use the
PersistentModel manifest path or retrain-on-deploy.
"""

from __future__ import annotations

import logging
import pickle
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

from predictionio_tpu.core.base import (Algorithm, DataSource, Doer,
                                        Preparator, Serving, run_sanity_check)
from predictionio_tpu.core.params import (EmptyParams, Params,
                                          params_from_dict, params_to_dict)
from predictionio_tpu.core.persistence import (RETRAIN, PersistentModel,
                                               PersistentModelManifest,
                                               load_persistent_model)

logger = logging.getLogger(__name__)


class StopAfterReadInterruption(Exception):
    pass


class StopAfterPrepareInterruption(Exception):
    pass


@dataclass(frozen=True)
class WorkflowParams:
    """(workflow/WorkflowParams.scala:29-37); sparkEnv becomes mesh config."""
    batch: str = ""
    verbose: int = 10
    save_model: bool = True
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False


@dataclass(frozen=True)
class EngineParams:
    """Named params for each DASE slot (controller/EngineParams.scala:32-80)."""
    data_source_params: Tuple[str, Any] = ("", EmptyParams())
    preparator_params: Tuple[str, Any] = ("", EmptyParams())
    algorithm_params_list: Sequence[Tuple[str, Any]] = field(
        default_factory=lambda: [("", EmptyParams())])
    serving_params: Tuple[str, Any] = ("", EmptyParams())


@dataclass
class TrainResult:
    models: List[Any]                # one per algorithm
    algorithms: List[Algorithm]      # the instances that trained them


def _params_class_of(cls) -> Optional[Type[Params]]:
    return getattr(cls, "PARAMS_CLASS", None)


def _build_params(cls, raw: Optional[dict]):
    pc = _params_class_of(cls)
    if pc is not None:
        return params_from_dict(pc, raw)
    return raw if raw else EmptyParams()


class Engine:
    """An engine is class-maps for each DASE slot plus default params
    (controller/Engine.scala:154)."""

    def __init__(self,
                 data_source_class_map,
                 preparator_class_map,
                 algorithm_class_map,
                 serving_class_map):
        def as_map(x):
            return x if isinstance(x, dict) else {"": x}
        self.data_source_class_map: Dict[str, type] = as_map(data_source_class_map)
        self.preparator_class_map: Dict[str, type] = as_map(preparator_class_map)
        self.algorithm_class_map: Dict[str, type] = as_map(algorithm_class_map)
        self.serving_class_map: Dict[str, type] = as_map(serving_class_map)

    # -- component instantiation -------------------------------------------
    def _lookup(self, class_map: Dict[str, type], name: str, slot: str) -> type:
        if name not in class_map:
            raise KeyError(
                f"{slot} '{name}' not found; available: {sorted(class_map)}")
        return class_map[name]

    def make_data_source(self, ep: EngineParams) -> DataSource:
        name, params = ep.data_source_params
        return Doer.apply(self._lookup(self.data_source_class_map, name,
                                       "datasource"), params)

    def make_preparator(self, ep: EngineParams) -> Preparator:
        name, params = ep.preparator_params
        return Doer.apply(self._lookup(self.preparator_class_map, name,
                                       "preparator"), params)

    def make_algorithms(self, ep: EngineParams) -> List[Algorithm]:
        return [Doer.apply(self._lookup(self.algorithm_class_map, name,
                                        "algorithm"), params)
                for name, params in ep.algorithm_params_list]

    def make_serving(self, ep: EngineParams) -> Serving:
        name, params = ep.serving_params
        return Doer.apply(self._lookup(self.serving_class_map, name,
                                       "serving"), params)

    # -- train (Engine.scala:621-708) --------------------------------------
    def train(self, engine_params: EngineParams,
              workflow_params: WorkflowParams = WorkflowParams()) -> TrainResult:
        check = not workflow_params.skip_sanity_check
        data_source = self.make_data_source(engine_params)
        td = data_source.read_training()
        run_sanity_check(td, check)
        if workflow_params.stop_after_read:
            raise StopAfterReadInterruption()

        preparator = self.make_preparator(engine_params)
        pd = preparator.prepare(td)
        run_sanity_check(pd, check)
        if workflow_params.stop_after_prepare:
            raise StopAfterPrepareInterruption()

        algorithms = self.make_algorithms(engine_params)
        models = []
        for i, algo in enumerate(algorithms):
            logger.info("Training algorithm %d/%d: %s",
                        i + 1, len(algorithms), type(algo).__name__)
            model = algo.train(pd)
            run_sanity_check(model, check)
            models.append(model)
        return TrainResult(models=models, algorithms=algorithms)

    # -- eval (Engine.scala:726-816) ---------------------------------------
    def eval(self, engine_params: EngineParams,
             workflow_params: WorkflowParams = WorkflowParams()
             ) -> List[Tuple[Any, List[Tuple[Any, Any, Any]]]]:
        """Per eval-set: train on its training split, batch-predict every
        algorithm over the queries, serve, and join with actuals.
        Returns [(evalInfo, [(query, prediction, actual)])]."""
        data_source = self.make_data_source(engine_params)
        eval_sets = data_source.read_eval()
        serving = self.make_serving(engine_params)
        out = []
        for td, eval_info, qa in eval_sets:
            preparator = self.make_preparator(engine_params)
            pd = preparator.prepare(td)
            algorithms = self.make_algorithms(engine_params)
            models = [a.train(pd) for a in algorithms]
            qa_list = list(qa)
            indexed = [(ix, serving.supplement(q))
                       for ix, (q, _) in enumerate(qa_list)]
            # per-algo batch predict, joined by query index
            per_algo: List[Dict[int, Any]] = []
            for algo, model in zip(algorithms, models):
                per_algo.append(dict(algo.batch_predict(model, indexed)))
            qpa = []
            for ix, (q, a) in enumerate(qa_list):
                preds = [pa[ix] for pa in per_algo]
                qpa.append((q, serving.serve(q, preds), a))
            out.append((eval_info, qpa))
        return out

    def batch_eval(self, engine_params_list: Sequence[EngineParams],
                   workflow_params: WorkflowParams = WorkflowParams()):
        """(core/BaseEngine.scala:79) — evaluate many params settings."""
        return [(ep, self.eval(ep, workflow_params))
                for ep in engine_params_list]

    # -- persistence (Engine.scala:282, :196-265) --------------------------
    def make_serializable_models(self, train_result: TrainResult,
                                 instance_id: str,
                                 engine_params: EngineParams) -> List[Any]:
        """Per algorithm: model | PersistentModelManifest | RETRAIN."""
        out = []
        algo_params = list(engine_params.algorithm_params_list)
        for (name, params), algo, model in zip(
                algo_params, train_result.algorithms, train_result.models):
            decision = algo.make_persistent_model(model)
            if isinstance(decision, PersistentModel):
                if decision.save(instance_id, params):
                    out.append(PersistentModelManifest(
                        type(decision).loader_name()))
                else:
                    out.append(decision)
            else:
                out.append(decision)  # model object or RETRAIN
        return out

    def serialize_models(self, serializable_models: List[Any]) -> bytes:
        from predictionio_tpu.utils.arrays import to_host
        return pickle.dumps([to_host(m) for m in serializable_models],
                            protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize_models(self, blob: bytes) -> List[Any]:
        return pickle.loads(blob)

    def prepare_deploy(self, engine_params: EngineParams,
                       persisted_models: List[Any], instance_id: str,
                       workflow_params: WorkflowParams = WorkflowParams()
                       ) -> TrainResult:
        """Restore models for serving (Engine.scala:196-265): manifests are
        loaded via their loader; RETRAIN models re-run the train pipeline."""
        algorithms = self.make_algorithms(engine_params)
        algo_params = list(engine_params.algorithm_params_list)
        needs_retrain = any(m is RETRAIN for m in persisted_models)
        retrained: Optional[TrainResult] = None
        if needs_retrain:
            logger.info("Some models request retrain-on-deploy; re-training")
            retrained = self.train(engine_params, workflow_params)
        models = []
        for i, m in enumerate(persisted_models):
            if m is RETRAIN:
                models.append(retrained.models[i])
            elif isinstance(m, PersistentModelManifest):
                models.append(load_persistent_model(
                    m, instance_id, algo_params[i][1]))
            else:
                models.append(m)
        return TrainResult(models=models, algorithms=algorithms)

    # -- engine.json params (Engine.scala:353 jValueToEngineParams) --------
    def json_to_engine_params(self, variant: dict) -> EngineParams:
        def one(slot_key: str, class_map: Dict[str, type]):
            block = variant.get(slot_key) or {}
            name = block.get("name", "")
            cls = self._lookup(class_map, name, slot_key)
            return (name, _build_params(cls, block.get("params")))

        ds = one("datasource", self.data_source_class_map)
        prep = one("preparator", self.preparator_class_map)
        serv = one("serving", self.serving_class_map)
        algo_blocks = variant.get("algorithms")
        if algo_blocks is None:
            algo_blocks = [{"name": "", "params": {}}]
        algos = []
        for block in algo_blocks:
            name = block.get("name", "")
            cls = self._lookup(self.algorithm_class_map, name, "algorithm")
            algos.append((name, _build_params(cls, block.get("params"))))
        return EngineParams(data_source_params=ds, preparator_params=prep,
                            algorithm_params_list=algos, serving_params=serv)

    def engine_params_to_json(self, ep: EngineParams) -> dict:
        def one(pair):
            name, params = pair
            return {"name": name, "params": params_to_dict(params)
                    if not isinstance(params, dict) else params}
        return {
            "datasource": one(ep.data_source_params),
            "preparator": one(ep.preparator_params),
            "algorithms": [one(p) for p in ep.algorithm_params_list],
            "serving": one(ep.serving_params),
        }


class SimpleEngine(Engine):
    """DataSource + single algorithm shortcut
    (controller/EngineParams.scala:127)."""

    def __init__(self, data_source_class, algorithm_class,
                 serving_class=None):
        from predictionio_tpu.core.base import (FirstServing,
                                                IdentityPreparator)
        super().__init__(data_source_class, IdentityPreparator,
                         algorithm_class, serving_class or FirstServing)


class EngineFactory:
    """Engine + default params provider (controller/EngineFactory.scala:28-33).
    Subclasses override apply(); registered under a dotted name used by
    engine.json's engineFactory field."""

    @classmethod
    def apply(cls) -> Engine:
        raise NotImplementedError

    @classmethod
    def engine_params(cls, key: str = "") -> EngineParams:
        """Programmatic engine parameters; `key` selects among named
        sets when a factory defines them (`pio train
        --engine-params-key`, EngineFactory.scala:33 — the reference's
        default likewise ignores the key and returns defaults)."""
        return EngineParams()
