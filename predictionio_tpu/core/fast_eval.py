"""FastEvalEngine: tuning accelerator with per-stage memoization.

Rebuilds the reference's ``FastEvalEngine``
(reference: core/src/main/scala/io/prediction/controller/FastEvalEngine.scala:
prefix keys :50-83, caches :283-302, getDataSourceResult :85,
getPreparatorResult :110, computeAlgorithmsResult :130): when sweeping a
params grid, stages whose params-prefix is unchanged reuse the cached result
— e.g. one data read + prepare shared across every algorithm setting.

Device note: cached prepared data may hold device arrays; entries are keyed
by params JSON so identical settings share HBM rather than re-ingesting.
"""

from __future__ import annotations

import json
from typing import Any, Dict

from predictionio_tpu.core.engine import (Engine, EngineParams,
                                          WorkflowParams)
from predictionio_tpu.core.params import params_to_dict


def _key(*parts) -> str:
    def norm(p):
        if isinstance(p, tuple) and len(p) == 2 and isinstance(p[0], str):
            name, params = p
            return [name, params if isinstance(params, dict)
                    else params_to_dict(params)]
        if isinstance(p, (list, tuple)):
            return [norm(x) for x in p]
        return p
    return json.dumps([norm(p) for p in parts], sort_keys=True, default=repr)


class FastEvalEngine(Engine):
    """Drop-in Engine whose batch_eval memoizes per-stage results keyed by
    params prefix. Cache-hit counters are exposed for tests, mirroring
    FastEvalEngineTest's assertions."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._ds_cache: Dict[str, Any] = {}
        self._prep_cache: Dict[str, Any] = {}
        self._algo_cache: Dict[str, Any] = {}
        self.counters = {"dataSource": 0, "preparator": 0, "algorithms": 0,
                         "serving": 0}

    # -- stage getters (FastEvalEngine.scala:85-281) -----------------------
    def _data_source_result(self, ep: EngineParams):
        k = _key(ep.data_source_params)
        if k not in self._ds_cache:
            self.counters["dataSource"] += 1
            ds = self.make_data_source(ep)
            self._ds_cache[k] = ds.read_eval()
        return self._ds_cache[k]

    def _preparator_result(self, ep: EngineParams):
        k = _key(ep.data_source_params, ep.preparator_params)
        if k not in self._prep_cache:
            self.counters["preparator"] += 1
            eval_sets = self._data_source_result(ep)
            prep = self.make_preparator(ep)
            self._prep_cache[k] = [
                (prep.prepare(td), ei, list(qa)) for td, ei, qa in eval_sets]
        return self._prep_cache[k]

    def _algorithms_result(self, ep: EngineParams):
        k = _key(ep.data_source_params, ep.preparator_params,
                 list(ep.algorithm_params_list))
        if k not in self._algo_cache:
            self.counters["algorithms"] += 1
            prepared_sets = self._preparator_result(ep)
            per_set = []
            for pd, ei, qa_list in prepared_sets:
                algorithms = self.make_algorithms(ep)
                models = [a.train(pd) for a in algorithms]
                indexed = list(enumerate(q for q, _ in qa_list))
                per_algo = [dict(a.batch_predict(m, indexed))
                            for a, m in zip(algorithms, models)]
                per_set.append((ei, qa_list, per_algo))
            self._algo_cache[k] = per_set
        return self._algo_cache[k]

    def eval(self, engine_params: EngineParams,
             workflow_params: WorkflowParams = WorkflowParams()):
        self.counters["serving"] += 1
        serving = self.make_serving(engine_params)
        out = []
        for ei, qa_list, per_algo in self._algorithms_result(engine_params):
            qpa = []
            for ix, (q, a) in enumerate(qa_list):
                preds = [pa[ix] for pa in per_algo]
                qpa.append((q, serving.serve(q, preds), a))
            out.append((ei, qpa))
        return out

    def clear(self):
        self._ds_cache.clear()
        self._prep_cache.clear()
        self._algo_cache.clear()
