"""Engine templates (L7): the four production template families from
BASELINE.json, rebuilt TPU-native (reference: examples/scala-parallel-*).

``ENGINE_FACTORIES`` is the registry engine.json's ``engineFactory`` field
resolves against (the reflection analog of WorkflowUtils.getEngine).
"""

from typing import Dict


def _registry() -> Dict[str, type]:
    from predictionio_tpu.models import (classification, ecommerce,
                                         recommendation, recommendeduser,
                                         similarproduct)
    return {
        "recommendation": recommendation.RecommendationEngineFactory,
        "classification": classification.ClassificationEngineFactory,
        "similarproduct": similarproduct.SimilarProductEngineFactory,
        "ecommercerecommendation": ecommerce.ECommerceEngineFactory,
        "recommendeduser": recommendeduser.RecommendedUserEngineFactory,
    }


def get_engine_factory(name: str):
    """Resolve an engineFactory name: a registry key or a dotted path
    ``package.module.ClassName``."""
    reg = _registry()
    if name in reg:
        return reg[name]
    if "." in name:
        import importlib
        module_name, _, attr = name.rpartition(".")
        return getattr(importlib.import_module(module_name), attr)
    raise KeyError(
        f"Unknown engineFactory {name!r}; registered: {sorted(reg)}")


def list_engine_factories():
    return sorted(_registry())
