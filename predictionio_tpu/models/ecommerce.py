"""E-commerce recommendation template: ALS + live business-rule filters.

Rebuilds `scala-parallel-ecommercerecommendation` (reference:
examples/scala-parallel-ecommercerecommendation/train-with-rate-event/src/
main/scala/ALSAlgorithm.scala — implicit ALS train :100-146; predict-time
live event-store reads with a 200 ms deadline for the user's seen items
:161-192 and the `constraint/unavailableItems` `$set` blacklist :195-215;
known-user scoring = dot(userFeature, productFeatures) with filters
:230-257; unknown users fall back to cosine similarity against their 10 most
recent viewed items :283-364).

The device path mirrors the similarproduct template (masked matmul top-k);
the business-rule reads stay host-side and only mutate the candidate mask,
so a slow event store can never stall the device (SURVEY hard part #4).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.core import (DataSource, Engine, EngineFactory,
                                   EngineParams, FirstServing, P2LAlgorithm,
                                   Params, Preparator, SanityCheck)
from predictionio_tpu.data.bimap import EntityIdIxMap
from predictionio_tpu.data.store import LEventStore, PEventStore
from predictionio_tpu.models.common import (ItemScoreResult, RatingsData,
                                            resolve_ids,
                                            top_scores_to_result)
from predictionio_tpu.models.similarproduct import Item
from predictionio_tpu.ops.als import ALSConfig, als_train
from predictionio_tpu.ops.ratings import RatingsCOO, dedup_ratings
from predictionio_tpu.ops.similarity import (build_filter_mask, cosine_top_k,
                                             normalize_rows)

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class RateEvent:
    user: str
    item: str
    rating: float
    t: int


@dataclass
class TrainingData(SanityCheck):
    """rate_events is columnar (RatingsData); plain RateEvent row lists
    are accepted and converted for hand-built fixtures."""
    users: Dict[str, dict]
    items: Dict[str, Item]
    rate_events: RatingsData

    def __post_init__(self):
        if isinstance(self.rate_events, (list, tuple)):
            self.rate_events = RatingsData.from_rows(self.rate_events)

    def sanity_check(self):
        if not len(self.rate_events):
            raise ValueError("rate_events is empty; check the data source")


@dataclass(frozen=True)
class Query:
    user: str
    num: int
    categories: Optional[Tuple[str, ...]] = None
    white_list: Optional[Tuple[str, ...]] = None
    black_list: Optional[Tuple[str, ...]] = None

    @staticmethod
    def from_dict(d: dict) -> "Query":
        def opt(key):
            v = d.get(key)
            return tuple(v) if v is not None else None
        return Query(user=str(d["user"]), num=int(d["num"]),
                     categories=opt("categories"),
                     white_list=opt("whiteList"),
                     black_list=opt("blackList"))


@dataclass
class PreparedData:
    td: TrainingData


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None
    rate_events: Tuple[str, ...] = ("rate", "buy")
    buy_rating: float = 4.0


class ECommerceDataSource(DataSource):
    PARAMS_CLASS = DataSourceParams

    def __init__(self, params=None):
        super().__init__(params or DataSourceParams())

    def read_training(self) -> TrainingData:
        app = self.params.app_name
        chan = self.params.channel_name
        users = {eid: dict(pm.fields) for eid, pm in
                 PEventStore.aggregate_properties(
                     app_name=app, channel_name=chan,
                     entity_type="user").items()}
        items = {}
        for eid, pm in PEventStore.aggregate_properties(
                app_name=app, channel_name=chan,
                entity_type="item").items():
            cats = pm.get_opt("categories", list)
            items[eid] = Item(tuple(cats) if cats is not None else None)
        # columnar ingest: flat arrays, no per-event Python objects
        rc = PEventStore.find_columnar(
            app_name=app, channel_name=chan, property_field="rating",
            entity_type="user", event_names=list(self.params.rate_events),
            target_entity_type="item")
        is_rate = rc["event"] == "rate"
        missing = is_rate & np.isnan(rc["prop"])
        if missing.any():
            raise ValueError(
                f"{int(missing.sum())} 'rate' event(s) lack the required "
                "'rating' property")
        vals = np.where(is_rate, rc["prop"],
                        np.float32(self.params.buy_rating)
                        ).astype(np.float32)
        rates = RatingsData(rc["entity_id"], rc["target_entity_id"],
                            vals, rc["t"])
        return TrainingData(users=users, items=items, rate_events=rates)


class ECommercePreparator(Preparator):
    def prepare(self, td: TrainingData) -> PreparedData:
        return PreparedData(td)


@dataclass(frozen=True)
class ECommAlgorithmParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None  # serve-time reads use this channel
    unseen_only: bool = True
    seen_events: Tuple[str, ...] = ("buy", "view")
    rank: int = 10
    num_iterations: int = 20
    lam: float = 0.01
    alpha: float = 1.0
    seed: Optional[int] = None
    compute_dtype: Optional[str] = None  # None = bf16 on TPU, f32 on CPU
    # solver-call batching / whole-iteration fusion (ops/als.ALSConfig
    # sweep_chunk / fuse_iteration; 0 = auto)
    sweep_chunk: int = 0
    fuse_iteration: bool = False


@dataclass
class ECommerceModel:
    rank: int
    user_factors: np.ndarray               # [U, R]
    item_factors: np.ndarray               # [I, R]
    item_factors_normalized: np.ndarray    # [I, R]
    user_ix: EntityIdIxMap
    item_ix: EntityIdIxMap
    items: Dict[str, Item]
    item_categories: List[Optional[set]]


class ECommAlgorithm(P2LAlgorithm):
    PARAMS_CLASS = ECommAlgorithmParams
    QUERY_CLASS = Query

    def __init__(self, params=None):
        super().__init__(params or ECommAlgorithmParams())

    def train(self, pd: PreparedData) -> ECommerceModel:
        td = pd.td
        p = self.params
        if not len(td.rate_events):
            raise ValueError("No rate events to train on")
        rd = td.rate_events
        user_ix, ui = EntityIdIxMap.build_with_indices(rd.users)
        item_ix = EntityIdIxMap.build(list(td.items.keys()) +
                                      rd.items.tolist())
        ii = item_ix.to_indices_array(rd.items)
        # train-with-rate-event: duplicate ratings keep the latest value
        ui, ii, vals = dedup_ratings(ui, ii, rd.vals, rd.ts, "latest")
        coo = RatingsCOO(ui, ii, vals, len(user_ix), len(item_ix))
        from predictionio_tpu.ops.als import default_compute_dtype
        cfg = ALSConfig(rank=p.rank, iterations=p.num_iterations, lam=p.lam,
                        sweep_chunk=p.sweep_chunk,
                        fuse_iteration=p.fuse_iteration,
                        implicit_prefs=True, alpha=p.alpha,
                        seed=p.seed if p.seed is not None else 0,
                        compute_dtype=p.compute_dtype
                        or default_compute_dtype())
        self.last_train_telemetry = {}
        model = als_train(coo, cfg,
                          telemetry=self.last_train_telemetry)
        item_categories = []
        for ix in range(len(item_ix)):
            item = td.items.get(item_ix.id_of(ix))
            item_categories.append(
                set(item.categories) if item and item.categories else None)
        return ECommerceModel(
            rank=p.rank,
            user_factors=model.user_factors,
            item_factors=model.item_factors,
            item_factors_normalized=normalize_rows(model.item_factors),
            user_ix=user_ix, item_ix=item_ix, items=dict(td.items),
            item_categories=item_categories)

    # -- live business rules (ALSAlgorithm.scala:161-215) ------------------
    def _seen_items(self, user: str) -> List[str]:
        if not self.params.unseen_only:
            return []
        try:
            events = LEventStore.find_by_entity(
                app_name=self.params.app_name,
                channel_name=self.params.channel_name, entity_type="user",
                entity_id=user, event_names=list(self.params.seen_events),
                target_entity_type="item", timeout_ms=200)
            return [e.target_entity_id for e in events
                    if e.target_entity_id]
        except Exception as e:
            logger.error("Error when reading seen events: %s", e)
            return []

    def _unavailable_items(self) -> List[str]:
        try:
            events = LEventStore.find_by_entity(
                app_name=self.params.app_name,
                channel_name=self.params.channel_name,
                entity_type="constraint",
                entity_id="unavailableItems", event_names=["$set"],
                limit=1, latest=True, timeout_ms=200)
            if events:
                return list(events[0].properties.get_string_list("items"))
        except Exception as e:
            logger.error("Error when reading unavailableItems: %s", e)
        return []

    def _build_mask(self, model: ECommerceModel, query: Query,
                    seen: List[str], unavailable: List[str]) -> np.ndarray:
        """Candidate mask shared by the single and batched paths: query
        blacklist + live seen-items + unavailableItems merged into the
        exclusion set (ALSAlgorithm.scala:217-257)."""
        black = list(query.black_list or ()) + seen + unavailable
        white = (resolve_ids(model.item_ix, query.white_list)
                 if query.white_list is not None else None)
        return build_filter_mask(
            len(model.item_ix),
            exclude=resolve_ids(model.item_ix, black),
            white_list=white,
            item_categories=model.item_categories,
            categories=set(query.categories) if query.categories else None)

    def predict(self, model: ECommerceModel, query: Query
                ) -> ItemScoreResult:
        mask = self._build_mask(model, query, self._seen_items(query.user),
                                self._unavailable_items())
        uix = model.user_ix.get(query.user, -1)
        if uix >= 0:
            # known user: raw dot-product scoring (ALSAlgorithm.scala:230-257)
            scores, idx = self._dot_topk(model, int(uix), query.num, mask)
            return top_scores_to_result(model.item_ix, scores, idx)
        logger.info("No userFeature found for user %s.", query.user)
        return self._predict_new_user(model, query, mask)

    @staticmethod
    def _dot_topk(model: ECommerceModel, uix: int, num: int,
                  mask: np.ndarray):
        from predictionio_tpu.ops.als import ALSModel, recommend_products
        als = ALSModel(model.user_factors, model.item_factors, model.rank)
        exclude = np.nonzero(~mask)[0]
        scores, idx = recommend_products(als, uix, num, exclude=exclude)
        keep = np.isfinite(scores) & (scores > 0)  # reference keeps score>0
        return scores[keep], idx[keep]

    def _recent_view_indices(self, model: ECommerceModel,
                             user: str) -> np.ndarray:
        """Dense indices of the user's 10 most recent viewed items
        (ALSAlgorithm.scala:283-364 fallback input)."""
        try:
            recent = LEventStore.find_by_entity(
                app_name=self.params.app_name,
                channel_name=self.params.channel_name, entity_type="user",
                entity_id=user, event_names=["view"],
                target_entity_type="item", limit=10, latest=True,
                timeout_ms=200)
            recent_items = {e.target_entity_id for e in recent
                            if e.target_entity_id}
        except Exception as e:
            logger.error("Error when reading recent events: %s", e)
            recent_items = set()
        r_ix = resolve_ids(model.item_ix, sorted(recent_items))
        if len(r_ix) == 0:
            logger.info("No productFeatures vector for recent items %s.",
                        recent_items)
        return r_ix

    def _predict_new_user(self, model: ECommerceModel, query: Query,
                          mask: np.ndarray) -> ItemScoreResult:
        """Recent-views cosine fallback (ALSAlgorithm.scala:283-364)."""
        r_ix = self._recent_view_indices(model, query.user)
        if len(r_ix) == 0:
            return ItemScoreResult(())
        query_vecs = model.item_factors_normalized[r_ix]
        scores, idx = cosine_top_k(model.item_factors_normalized, query_vecs,
                                   query.num, mask)
        return top_scores_to_result(model.item_ix, scores, idx)

    def batch_predict(self, model, queries):
        """Batched path (serving coalescer + eval): business-rule event
        reads stay host-side and only mutate candidate masks; the
        query-independent unavailableItems read happens once per batch,
        the per-user reads run concurrently (they are I/O-bound with a
        200 ms deadline each). The batch then needs at most two device
        calls — one masked-matmul top-k for known users (raw dot scoring)
        and one for new-user cosine fallbacks."""
        from concurrent.futures import ThreadPoolExecutor

        from predictionio_tpu.ops.similarity import (masked_top_k_batch,
                                                     unpack_top_k_rows)
        out = {ix: ItemScoreResult(()) for ix, _ in queries}
        unavailable = self._unavailable_items()
        known = []     # (ix, query, user_vec [R], mask [I])
        fallback = []  # (ix, query, qsum [R], mask [I])
        with ThreadPoolExecutor(max_workers=min(8, max(1, len(queries)))) \
                as pool:
            seen_futs = {ix: pool.submit(self._seen_items, q.user)
                         for ix, q in queries}
            recent_futs = {ix: pool.submit(self._recent_view_indices,
                                           model, q.user)
                           for ix, q in queries
                           if model.user_ix.get(q.user, -1) < 0}
            for ix, q in queries:
                mask = self._build_mask(model, q, seen_futs[ix].result(),
                                        unavailable)
                uix = model.user_ix.get(q.user, -1)
                if uix >= 0:
                    known.append((ix, q, model.user_factors[int(uix)], mask))
                    continue
                logger.info("No userFeature found for user %s.", q.user)
                recent = recent_futs[ix].result()
                if len(recent) == 0:
                    continue
                qsum = model.item_factors_normalized[recent].sum(axis=0)
                fallback.append((ix, q, qsum, mask))
        for rows, table in ((known, model.item_factors),
                            (fallback, model.item_factors_normalized)):
            if not rows:
                continue
            k_max = max(q.num for _, q, _, _ in rows)
            scores, idx = masked_top_k_batch(
                table, np.stack([r[2] for r in rows]),
                np.stack([r[3] for r in rows]), k_max)
            for row, (ix, q, _, _) in enumerate(rows):
                s, i = unpack_top_k_rows(scores[row], idx[row], q.num)
                out[ix] = top_scores_to_result(model.item_ix, s, i)
        return list(out.items())


class ECommerceEngineFactory(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            {"": ECommerceDataSource},
            {"": ECommercePreparator},
            {"ecomm": ECommAlgorithm},
            {"": FirstServing})

    @classmethod
    def engine_params(cls, key: str = "") -> EngineParams:
        return EngineParams(
            data_source_params=("", DataSourceParams()),
            preparator_params=("", None),
            algorithm_params_list=[("ecomm", ECommAlgorithmParams())],
            serving_params=("", None))
