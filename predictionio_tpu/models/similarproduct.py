"""Similar-product engine template: implicit ALS + cosine similarity.

Rebuilds `scala-parallel-similarproduct` (reference:
examples/scala-parallel-similarproduct/multi/src/main/scala/
ALSAlgorithm.scala — `ALS.trainImplicit` over view-count "ratings" built by
`((u,i),1).reduceByKey(_+_)` :96-133; predict scores every item by summed
cosine similarity against the query items' factors with category/white/black
filters :146-190). The driver-side cosine scan becomes one jitted masked
matmul + top-k (ops.similarity).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.core import (DataSource, Engine, EngineFactory,
                                   EngineParams, FirstServing, P2LAlgorithm,
                                   Params, Preparator, SanityCheck)
from predictionio_tpu.data.bimap import EntityIdIxMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.common import (ItemScoreResult, resolve_ids,
                                            top_scores_to_result)
from predictionio_tpu.ops.als import ALSConfig, als_train
from predictionio_tpu.ops.ratings import RatingsCOO, dedup_ratings
from predictionio_tpu.ops.similarity import (build_filter_mask, cosine_top_k,
                                             normalize_rows)

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class Item:
    categories: Optional[Tuple[str, ...]] = None


@dataclass(frozen=True)
class ViewEvent:
    user: str
    item: str
    t: int = 0


@dataclass
class TrainingData(SanityCheck):
    users: Dict[str, dict]
    items: Dict[str, Item]
    view_events: List[ViewEvent]

    def sanity_check(self):
        if not self.view_events:
            raise ValueError("view_events is empty; check the data source")
        if not self.items:
            raise ValueError("items is empty; check the data source")


@dataclass(frozen=True)
class Query:
    items: Tuple[str, ...]
    num: int
    categories: Optional[Tuple[str, ...]] = None
    white_list: Optional[Tuple[str, ...]] = None
    black_list: Optional[Tuple[str, ...]] = None

    @staticmethod
    def from_dict(d: dict) -> "Query":
        def opt(key):
            v = d.get(key)
            return tuple(v) if v is not None else None
        return Query(items=tuple(d["items"]), num=int(d["num"]),
                     categories=opt("categories"),
                     white_list=opt("whiteList"),
                     black_list=opt("blackList"))


@dataclass
class PreparedData:
    td: TrainingData


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None


class SimilarProductDataSource(DataSource):
    """(multi/DataSource.scala readTraining: $set user, $set item with
    categories, view events)"""
    PARAMS_CLASS = DataSourceParams

    def __init__(self, params=None):
        super().__init__(params or DataSourceParams())

    def read_training(self) -> TrainingData:
        app = self.params.app_name
        chan = self.params.channel_name
        users = {eid: dict(pm.fields) for eid, pm in
                 PEventStore.aggregate_properties(
                     app_name=app, channel_name=chan,
                     entity_type="user").items()}
        items = {}
        for eid, pm in PEventStore.aggregate_properties(
                app_name=app, channel_name=chan,
                entity_type="item").items():
            cats = pm.get_opt("categories", list)
            items[eid] = Item(tuple(cats) if cats is not None else None)
        views = []
        from predictionio_tpu.data.event import to_millis
        for e in PEventStore.find(app_name=app, channel_name=chan,
                                  entity_type="user",
                                  event_names=["view"],
                                  target_entity_type="item"):
            views.append(ViewEvent(e.entity_id, e.target_entity_id,
                                   to_millis(e.event_time)))
        return TrainingData(users=users, items=items, view_events=views)


class SimilarProductPreparator(Preparator):
    def prepare(self, td: TrainingData) -> PreparedData:
        return PreparedData(td)


@dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 20
    lam: float = 0.01
    alpha: float = 1.0
    seed: Optional[int] = None
    compute_dtype: Optional[str] = None  # None = bf16 on TPU, f32 on CPU


@dataclass
class SimilarProductModel:
    """productFeatures + id maps + item metadata (ALSAlgorithm.scala
    ALSModel)."""
    item_factors_normalized: np.ndarray   # [I, R] L2-normalized rows
    item_ix: EntityIdIxMap
    items: Dict[str, Item]
    item_categories: List[Optional[set]]  # by dense index


class ALSAlgorithm(P2LAlgorithm):
    PARAMS_CLASS = ALSAlgorithmParams
    QUERY_CLASS = Query

    def __init__(self, params=None):
        super().__init__(params or ALSAlgorithmParams())

    def train(self, pd: PreparedData) -> SimilarProductModel:
        td = pd.td
        p = self.params
        if not td.view_events:
            raise ValueError("No view events to train on")
        # item vocabulary covers all $set items (so unseen-in-views items
        # still resolve), users only those with views
        user_ix = EntityIdIxMap.build(v.user for v in td.view_events)
        item_ix = EntityIdIxMap.build(list(td.items.keys()) +
                                      [v.item for v in td.view_events])
        ui = user_ix.to_indices([v.user for v in td.view_events])
        ii = item_ix.to_indices([v.item for v in td.view_events])
        ones = np.ones(len(td.view_events), dtype=np.float32)
        # ((u,i),1).reduceByKey(_+_)  — view counts
        ui, ii, counts = dedup_ratings(ui, ii, ones, policy="sum")
        coo = RatingsCOO(ui, ii, counts, len(user_ix), len(item_ix))
        from predictionio_tpu.ops.als import default_compute_dtype
        cfg = ALSConfig(rank=p.rank, iterations=p.num_iterations, lam=p.lam,
                        implicit_prefs=True, alpha=p.alpha,
                        seed=p.seed if p.seed is not None else 0,
                        compute_dtype=p.compute_dtype
                        or default_compute_dtype())
        model = als_train(coo, cfg)
        item_categories = []
        for ix in range(len(item_ix)):
            item = td.items.get(item_ix.id_of(ix))
            item_categories.append(
                set(item.categories) if item and item.categories else None)
        return SimilarProductModel(
            item_factors_normalized=normalize_rows(model.item_factors),
            item_ix=item_ix,
            items=dict(td.items),
            item_categories=item_categories)

    @staticmethod
    def _build_mask(model: SimilarProductModel, query: Query,
                    q_ix: np.ndarray) -> np.ndarray:
        """Candidate mask shared by the single and batched paths
        (isCandidateItem, ALSAlgorithm.scala:192+); query items excluded."""
        white = (resolve_ids(model.item_ix, query.white_list)
                 if query.white_list is not None else None)
        black = resolve_ids(model.item_ix, query.black_list or ())
        return build_filter_mask(
            len(model.item_ix),
            exclude=np.concatenate([q_ix, black]),
            white_list=white,
            item_categories=model.item_categories,
            categories=set(query.categories) if query.categories else None)

    def predict(self, model: SimilarProductModel, query: Query
                ) -> ItemScoreResult:
        q_ix = resolve_ids(model.item_ix, query.items)
        if len(q_ix) == 0:
            logger.info("No productFeatures vector for query items %s.",
                        query.items)
            return ItemScoreResult(())
        query_vecs = model.item_factors_normalized[q_ix]
        mask = self._build_mask(model, query, q_ix)
        scores, idx = cosine_top_k(model.item_factors_normalized, query_vecs,
                                   query.num, mask)
        return top_scores_to_result(model.item_ix, scores, idx)

    def batch_predict(self, model, queries):
        """Batched path (serving coalescer + eval): the cosine score is
        linear over query items, so each query collapses to one summed
        normalized vector and the whole batch is a single masked matmul +
        top-k device call (vs the reference's per-query driver scan)."""
        from predictionio_tpu.ops.similarity import (masked_top_k_batch,
                                                     unpack_top_k_rows)
        out = {ix: ItemScoreResult(()) for ix, _ in queries}
        rows = []  # (ix, query, qsum [R], mask [I])
        for ix, q in queries:
            q_ix = resolve_ids(model.item_ix, q.items)
            if len(q_ix) == 0:
                logger.info("No productFeatures vector for query items %s.",
                            q.items)
                continue
            qsum = model.item_factors_normalized[q_ix].sum(axis=0)
            rows.append((ix, q, qsum, self._build_mask(model, q, q_ix)))
        if rows:
            k_max = max(q.num for _, q, _, _ in rows)
            scores, idx = masked_top_k_batch(
                model.item_factors_normalized,
                np.stack([r[2] for r in rows]),
                np.stack([r[3] for r in rows]), k_max)
            for row, (ix, q, _, _) in enumerate(rows):
                s, i = unpack_top_k_rows(scores[row], idx[row], q.num)
                out[ix] = top_scores_to_result(model.item_ix, s, i)
        return list(out.items())


class SimilarProductEngineFactory(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            {"": SimilarProductDataSource},
            {"": SimilarProductPreparator},
            {"als": ALSAlgorithm},
            {"": FirstServing})

    @classmethod
    def engine_params(cls) -> EngineParams:
        return EngineParams(
            data_source_params=("", DataSourceParams()),
            preparator_params=("", None),
            algorithm_params_list=[("als", ALSAlgorithmParams())],
            serving_params=("", None))
