"""Similar-product engine template: implicit ALS + cosine similarity.

Rebuilds `scala-parallel-similarproduct` (reference:
examples/scala-parallel-similarproduct/multi/src/main/scala/
ALSAlgorithm.scala — `ALS.trainImplicit` over view-count "ratings" built by
`((u,i),1).reduceByKey(_+_)` :96-133; predict scores every item by summed
cosine similarity against the query items' factors with category/white/black
filters :146-190). The driver-side cosine scan becomes one jitted masked
matmul + top-k (ops.similarity).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.core import (DataSource, Engine, EngineFactory,
                                   EngineParams, FirstServing, P2LAlgorithm,
                                   Params, Preparator, SanityCheck)
from predictionio_tpu.core.persistence import PersistentModel
from predictionio_tpu.data.bimap import EntityIdIxMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.common import (ItemScoreResult, RatingsData,
                                            resolve_ids,
                                            top_scores_to_result)
from predictionio_tpu.ops.als import ALSConfig, als_train
from predictionio_tpu.ops.ratings import RatingsCOO, dedup_ratings
from predictionio_tpu.ops.similarity import (build_filter_mask, cosine_top_k,
                                             item_cosine_similarities,
                                             normalize_rows)

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class Item:
    categories: Optional[Tuple[str, ...]] = None
    # full $set property bag (add-and-return-item-properties variant)
    properties: Optional[dict] = None


@dataclass(frozen=True)
class ViewEvent:
    user: str
    item: str
    t: int = 0


@dataclass(frozen=True)
class LikeEvent:
    """like/dislike event (multi variant: LikeAlgorithm.scala:15-76)."""
    user: str
    item: str
    like: bool
    t: int = 0


@dataclass
class TrainingData(SanityCheck):
    """view_events/like_events are columnar (RatingsData: like=+1,
    dislike=-1); plain ViewEvent/LikeEvent row lists are accepted and
    converted for hand-built fixtures."""
    users: Dict[str, dict]
    items: Dict[str, Item]
    view_events: RatingsData
    like_events: RatingsData = None  # filled when read_like_events on
    # True for an entity-filtered (fold-tick) read: users/items/events
    # cover ONLY the touched entities' complete histories — fold_in
    # merges item metadata with the deployed model's instead of
    # rebuilding it from this partial bag
    touched_only: bool = False

    def __post_init__(self):
        if isinstance(self.view_events, (list, tuple)):
            self.view_events = RatingsData(
                np.array([v.user for v in self.view_events], dtype=str),
                np.array([v.item for v in self.view_events], dtype=str),
                np.ones(len(self.view_events), dtype=np.float32),
                np.array([v.t for v in self.view_events], dtype=np.int64))
        if isinstance(self.like_events, (list, tuple)):
            self.like_events = RatingsData(
                np.array([e.user for e in self.like_events], dtype=str),
                np.array([e.item for e in self.like_events], dtype=str),
                np.array([1.0 if e.like else -1.0
                          for e in self.like_events], dtype=np.float32),
                np.array([e.t for e in self.like_events], dtype=np.int64))

    def sanity_check(self):
        if not len(self.view_events):
            raise ValueError("view_events is empty; check the data source")
        if not self.items:
            raise ValueError("items is empty; check the data source")


@dataclass(frozen=True)
class Query:
    items: Tuple[str, ...]
    num: int
    categories: Optional[Tuple[str, ...]] = None
    white_list: Optional[Tuple[str, ...]] = None
    black_list: Optional[Tuple[str, ...]] = None
    # filterbyyear variant (filterbyyear/Engine.scala:22,
    # ALSAlgorithm.scala:231): only items with year > recommendFromYear
    recommend_from_year: Optional[int] = None

    @staticmethod
    def from_dict(d: dict) -> "Query":
        def opt(key):
            v = d.get(key)
            return tuple(v) if v is not None else None
        rfy = d.get("recommendFromYear")
        return Query(items=tuple(d["items"]), num=int(d["num"]),
                     categories=opt("categories"),
                     white_list=opt("whiteList"),
                     black_list=opt("blackList"),
                     recommend_from_year=(int(rfy) if rfy is not None
                                          else None))


@dataclass
class PreparedData:
    td: TrainingData


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None
    # add-rateevent variant: treat rate events as views as well
    rate_as_view: bool = False
    # multi variant: also read like/dislike events for LikeAlgorithm
    read_like_events: bool = False


class SimilarProductDataSource(DataSource):
    """(multi/DataSource.scala readTraining: $set user, $set item with
    categories, view events, like/dislike events). The add-rateevent
    variant's rate-as-view mapping and the no-set-user variant (users are
    inferred from view events; $set user events are optional) are folded in
    as parameters."""
    PARAMS_CLASS = DataSourceParams

    def __init__(self, params=None):
        super().__init__(params or DataSourceParams())

    def read_training(self) -> TrainingData:
        app = self.params.app_name
        chan = self.params.channel_name
        users = {eid: dict(pm.fields) for eid, pm in
                 PEventStore.aggregate_properties(
                     app_name=app, channel_name=chan,
                     entity_type="user").items()}
        items = {}
        for eid, pm in PEventStore.aggregate_properties(
                app_name=app, channel_name=chan,
                entity_type="item").items():
            cats = pm.get_opt("categories", list)
            items[eid] = Item(tuple(cats) if cats is not None else None,
                              properties=dict(pm.fields))
        view_names = ["view", "rate"] if self.params.rate_as_view \
            else ["view"]
        # columnar ingest: flat arrays, no per-event Python objects
        vc = PEventStore.find_columnar(
            app_name=app, channel_name=chan, entity_type="user",
            event_names=view_names, target_entity_type="item")
        views = RatingsData(vc["entity_id"], vc["target_entity_id"],
                            np.ones(len(vc["t"]), dtype=np.float32),
                            vc["t"])
        likes = None
        if self.params.read_like_events:
            lc = PEventStore.find_columnar(
                app_name=app, channel_name=chan, entity_type="user",
                event_names=["like", "dislike"],
                target_entity_type="item")
            likes = RatingsData(
                lc["entity_id"], lc["target_entity_id"],
                np.where(lc["event"] == "like", 1.0, -1.0
                         ).astype(np.float32), lc["t"])
        return TrainingData(users=users, items=items, view_events=views,
                            like_events=likes)

    def read_training_touched(self, touched_users,
                              touched_items) -> TrainingData:
        """Entity-filtered fold-tick read (see the recommendation
        template's read_training_touched): touched users' complete view
        histories + every view landing on a touched item through the
        backend pushdown, and per-entity property aggregation for the
        touched entities only."""
        app = self.params.app_name
        chan = self.params.channel_name
        tu = [str(u) for u in touched_users]
        ti = [str(i) for i in touched_items]
        users = {u: dict(pm.fields)
                 for u, pm in self._aggregate_for("user", tu).items()}
        items = {}
        for eid, pm in self._aggregate_for("item", ti).items():
            cats = pm.get_opt("categories", list)
            items[eid] = Item(tuple(cats) if cats is not None else None,
                              properties=dict(pm.fields))
        view_names = ["view", "rate"] if self.params.rate_as_view \
            else ["view"]
        vc = PEventStore.find_columnar_by_entities(
            app_name=app, channel_name=chan, entity_ids=tu,
            target_entity_ids=ti, entity_type="user",
            event_names=view_names, target_entity_type="item")
        views = RatingsData(vc["entity_id"], vc["target_entity_id"],
                            np.ones(len(vc["t"]), dtype=np.float32),
                            vc["t"])
        likes = None
        if self.params.read_like_events:
            lc = PEventStore.find_columnar_by_entities(
                app_name=app, channel_name=chan, entity_ids=tu,
                target_entity_ids=ti, entity_type="user",
                event_names=["like", "dislike"],
                target_entity_type="item")
            likes = RatingsData(
                lc["entity_id"], lc["target_entity_id"],
                np.where(lc["event"] == "like", 1.0, -1.0
                         ).astype(np.float32), lc["t"])
        return TrainingData(users=users, items=items, view_events=views,
                            like_events=likes, touched_only=True)

    def _aggregate_for(self, entity_type: str, entity_ids) -> dict:
        """Per-entity property aggregation for an id set: k indexed
        point reads instead of the corpus-wide $set scan; the
        app/channel names resolve ONCE, not per id."""
        from predictionio_tpu.data.aggregator import aggregate_properties
        from predictionio_tpu.data.storage.base import aggregate_event_names
        app_id, channel_id = PEventStore.resolve(
            self.params.app_name, self.params.channel_name)
        ev = PEventStore.events
        events = []
        for eid in entity_ids:
            events.extend(ev.find(
                app_id=app_id, channel_id=channel_id,
                entity_type=entity_type, entity_id=eid,
                event_names=list(aggregate_event_names())))
        return aggregate_properties(events)


class SimilarProductPreparator(Preparator):
    def prepare(self, td: TrainingData) -> PreparedData:
        return PreparedData(td)


@dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 20
    lam: float = 0.01
    alpha: float = 1.0
    seed: Optional[int] = None
    compute_dtype: Optional[str] = None  # None = bf16 on TPU, f32 on CPU
    # add-and-return-item-properties variant: property keys copied onto
    # each ItemScore in the result JSON (missing -> null)
    return_properties: Tuple[str, ...] = ()
    # solver-call batching / whole-iteration fusion (ops/als.ALSConfig
    # sweep_chunk / fuse_iteration; 0 = auto)
    sweep_chunk: int = 0
    fuse_iteration: bool = False


@dataclass(kw_only=True)
class ItemMetadataModel:
    """Id maps + item metadata shared by every similarproduct model flavor
    (the ALSModel fields minus the factors)."""
    item_ix: EntityIdIxMap
    items: Dict[str, Item]
    item_categories: List[Optional[set]]  # by dense index
    item_years: Optional[np.ndarray] = None  # float32, NaN = undated

    @staticmethod
    def derive_years(items: Dict[str, Item],
                     item_ix: EntityIdIxMap) -> np.ndarray:
        years = np.full(len(item_ix), np.nan, dtype=np.float32)
        for ix in range(len(item_ix)):
            item = items.get(item_ix.id_of(ix))
            y = (item.properties or {}).get("year") if item else None
            if y is not None:
                years[ix] = float(y)
        return years

    @classmethod
    def metadata_kwargs(cls, items: Dict[str, Item],
                        item_ix: EntityIdIxMap) -> dict:
        """Constructor kwargs for the shared fields, derived once from the
        training data's item bag."""
        item_categories = []
        for ix in range(len(item_ix)):
            item = items.get(item_ix.id_of(ix))
            item_categories.append(
                set(item.categories) if item and item.categories else None)
        return dict(item_ix=item_ix, items=dict(items),
                    item_categories=item_categories,
                    item_years=cls.derive_years(items, item_ix))

    def properties_of(self, keys: Tuple[str, ...]):
        """ItemScore property passthrough (add-and-return-item-properties
        variant): requested keys always present, missing -> None/null."""
        if not keys:
            return None

        def get(ix: int):
            item = self.items.get(self.item_ix.id_of(ix))
            p = (item.properties if item and item.properties else {})
            return {k: p.get(k) for k in keys}
        return get


@dataclass(kw_only=True)
class SimilarProductModel(ItemMetadataModel):
    """productFeatures + id maps + item metadata (ALSAlgorithm.scala
    ALSModel)."""
    item_factors_normalized: np.ndarray   # [I, R] L2-normalized rows
    # online-update state (ISSUE 1): the serve path needs only the
    # normalized item table, but folding a fresh view into a deployed
    # model needs the raw factors AND the user side the implicit
    # normal equations solve against. Optional so old pickles load.
    item_factors_raw: Optional[np.ndarray] = None   # [I, R]
    user_factors: Optional[np.ndarray] = None       # [U, R]
    user_ix: Optional[EntityIdIxMap] = None


class ALSAlgorithm(P2LAlgorithm):
    PARAMS_CLASS = ALSAlgorithmParams
    QUERY_CLASS = Query

    def __init__(self, params=None):
        super().__init__(params or ALSAlgorithmParams())

    def _build_ratings(self, td: TrainingData
                       ) -> Tuple[EntityIdIxMap, EntityIdIxMap, RatingsCOO]:
        """((u,i),1).reduceByKey(_+_) — view counts. Item vocabulary covers
        all $set items (so unseen-in-views items still resolve), users only
        those with views."""
        if not len(td.view_events):
            raise ValueError("No view events to train on")
        views = td.view_events
        user_ix, ui = EntityIdIxMap.build_with_indices(views.users)
        item_ix = EntityIdIxMap.build(list(td.items.keys()) +
                                      views.items.tolist())
        ii = item_ix.to_indices_array(views.items)
        ui, ii, counts = dedup_ratings(ui, ii, views.vals, policy="sum")
        return user_ix, item_ix, RatingsCOO(ui, ii, counts,
                                            len(user_ix), len(item_ix))

    def train(self, pd: PreparedData) -> SimilarProductModel:
        td = pd.td
        p = self.params
        user_ix, item_ix, coo = self._build_ratings(td)
        from predictionio_tpu.ops.als import default_compute_dtype
        cfg = ALSConfig(rank=p.rank, iterations=p.num_iterations, lam=p.lam,
                        sweep_chunk=p.sweep_chunk,
                        fuse_iteration=p.fuse_iteration,
                        implicit_prefs=True, alpha=p.alpha,
                        seed=p.seed if p.seed is not None else 0,
                        compute_dtype=p.compute_dtype
                        or default_compute_dtype())
        self.last_train_telemetry = {}
        model = als_train(coo, cfg,
                          telemetry=self.last_train_telemetry)
        return SimilarProductModel(
            item_factors_normalized=normalize_rows(model.item_factors),
            item_factors_raw=model.item_factors,
            user_factors=model.user_factors, user_ix=user_ix,
            **ItemMetadataModel.metadata_kwargs(td.items, item_ix))

    # -- online updates (ISSUE 1: predictionio_tpu/online) -----------------
    def _fold_users_present(self, td: TrainingData) -> set:
        """Users with event data — the only ones user-vocab growth may
        mint rows for (a $set-only user stays cold-start)."""
        if not len(td.view_events):
            return set()
        return set(np.unique(td.view_events.users).astype(str))

    def _fold_ratings(self, td: TrainingData, user_ix: EntityIdIxMap,
                      item_ix: EntityIdIxMap) -> RatingsCOO:
        """Fresh ratings against FIXED (grown) vocabularies — the fold-in
        analog of `_build_ratings`, which builds vocabularies itself and
        would shuffle the deployed dense indices."""
        views = td.view_events
        ui = user_ix.to_indices_array(views.users)
        ii = item_ix.to_indices_array(views.items)
        keep = (ui >= 0) & (ii >= 0)
        ui, ii, counts = dedup_ratings(ui[keep], ii[keep],
                                       views.vals[keep], policy="sum")
        return RatingsCOO(ui, ii, counts, len(user_ix), len(item_ix))

    def fold_in(self, model: SimilarProductModel, td: TrainingData,
                touched_users, touched_items, preparator_params=None
                ) -> Tuple[SimilarProductModel, dict]:
        """Implicit (Hu-Koren) fold-in: re-solve only the touched user and
        item rows of the view-count factorization and refresh the
        normalized serve table — a freshly $set + viewed item becomes
        similar-product-recommendable without a retrain. Models persisted
        before online support (no raw factor state) raise."""
        if model.item_factors_raw is None or model.user_factors is None \
                or model.user_ix is None:
            raise ValueError(
                "model lacks online-update state; retrain once with this "
                "build before attaching the delta scheduler")
        from predictionio_tpu.online.fold_in import (FoldInConfig,
                                                     fold_in_coo)
        from predictionio_tpu.ops.als import ALSModel, als_rmse, \
            default_compute_dtype
        p = self.params
        # users grow only with event data; items grow when viewed OR $set
        # (train's item vocabulary likewise covers all $set items)
        present_u = self._fold_users_present(td)
        user_ix, _ = model.user_ix.grow(
            u for u in map(str, touched_users) if u in present_u)
        item_ix, _ = model.item_ix.grow(str(i) for i in touched_items)
        coo = self._fold_ratings(td, user_ix, item_ix)
        tu = user_ix.to_indices([str(u) for u in touched_users])
        ti = item_ix.to_indices([str(i) for i in touched_items])
        cfg = FoldInConfig(
            lam=p.lam, alpha=p.alpha, implicit_prefs=True, sweeps=2,
            compute_dtype=p.compute_dtype or default_compute_dtype(),
            sweep_chunk=p.sweep_chunk)
        als = ALSModel(user_factors=model.user_factors,
                       item_factors=model.item_factors_raw,
                       rank=model.item_factors_raw.shape[1])
        new_als, stats = fold_in_coo(
            als, coo, tu[tu >= 0], ti[ti >= 0], cfg,
            resident_key=f"fold:{type(self).__name__}:{id(self)}")
        if stats.degenerate:
            # nothing solvable (ISSUE 5 satellite): the deployed model
            # object signals a clean no-op to the scheduler
            return model, {"algorithm": type(self).__name__,
                           "degenerate": True, "wallS": stats.wall_s}
        # an entity-filtered read carries only the touched items' $set
        # state: untouched items keep the deployed metadata (categories,
        # years) instead of being wiped by the partial bag
        items = ({**model.items, **td.items}
                 if getattr(td, "touched_only", False) else td.items)
        new_model = SimilarProductModel(
            item_factors_normalized=normalize_rows(new_als.item_factors),
            item_factors_raw=new_als.item_factors,
            user_factors=new_als.user_factors, user_ix=user_ix,
            **ItemMetadataModel.metadata_kwargs(items, item_ix))
        report = {
            "algorithm": type(self).__name__,
            "loss": als_rmse(new_als, coo),
            "userRows": stats.n_user_rows, "itemRows": stats.n_item_rows,
            "newUsers": stats.n_new_users, "newItems": stats.n_new_items,
            "wallS": stats.wall_s, "residentHit": stats.resident_hit,
            "sentinelRollback": stats.sentinel_rollback,
            "guardWallS": stats.guard_wall_s,
        }
        return new_model, report

    @staticmethod
    def _build_mask(model: SimilarProductModel, query: Query,
                    q_ix: np.ndarray) -> np.ndarray:
        """Candidate mask shared by the single and batched paths
        (isCandidateItem, ALSAlgorithm.scala:192+); query items excluded."""
        white = (resolve_ids(model.item_ix, query.white_list)
                 if query.white_list is not None else None)
        black = resolve_ids(model.item_ix, query.black_list or ())
        mask = build_filter_mask(
            len(model.item_ix),
            exclude=np.concatenate([q_ix, black]),
            white_list=white,
            item_categories=model.item_categories,
            categories=set(query.categories) if query.categories else None)
        if query.recommend_from_year is not None and \
                model.item_years is not None:
            # filterbyyear: dated items need year > recommendFromYear
            # (undated items pass)
            dated = ~np.isnan(model.item_years)
            mask &= ~(dated & (model.item_years <= query.recommend_from_year))
        return mask

    def predict(self, model: SimilarProductModel, query: Query
                ) -> ItemScoreResult:
        q_ix = resolve_ids(model.item_ix, query.items)
        if len(q_ix) == 0:
            logger.info("No productFeatures vector for query items %s.",
                        query.items)
            return ItemScoreResult(())
        query_vecs = model.item_factors_normalized[q_ix]
        mask = self._build_mask(model, query, q_ix)
        scores, idx = cosine_top_k(model.item_factors_normalized, query_vecs,
                                   query.num, mask)
        return top_scores_to_result(
            model.item_ix, scores, idx,
            properties_of=model.properties_of(self.params.return_properties))

    # -- compile plane (ISSUE 9) -------------------------------------------
    def aot_warm_specs(self, model, batch_hint: int = 16):
        """(label, bucket-dims) rows for the cosine serve executable —
        compiled at deploy / hot-swap / canary-stage time by
        ``compile.aot.warm_models`` so a fresh model's first query pays
        no XLA compile. Covers the micro-batcher's pow2 coalescing
        ladder; the gates golden-replay answers through the same
        bucketed executable."""
        from predictionio_tpu.compile import buckets as B
        from predictionio_tpu.obs import costmon
        from predictionio_tpu.ops.similarity import (masked_topk_dims,
                                                     register_aot_specs)
        table = model.item_factors_normalized
        register_aot_specs()
        batches = sorted({1} | {1 << e for e in range(
            1, B.bucket_batch(max(batch_hint, 1)).bit_length())})
        return [(costmon.BATCH_PREDICT_MASKED,
                 masked_topk_dims(table.shape[0], table.shape[1], b, 16,
                                  filter_positive=True))
                for b in batches]

    def batch_predict(self, model, queries):
        """Batched path (serving coalescer + eval): the cosine score is
        linear over query items, so each query collapses to one summed
        normalized vector and the whole batch is a single masked matmul +
        top-k device call (vs the reference's per-query driver scan),
        shape-bucketed and AOT-dispatched inside masked_top_k_batch."""
        return self.batch_predict_begin(model, queries)()

    def batch_predict_begin(self, model, queries):
        """Two-phase batch predict (ISSUE 14 pipelined executor):
        enqueue the masked cosine top-k now, defer the device->host
        readback + result building to the returned ``finish()`` —
        callable from the completion stage's thread."""
        from predictionio_tpu.ops.similarity import (
            masked_top_k_batch_begin, unpack_top_k_rows)
        out = {ix: ItemScoreResult(()) for ix, _ in queries}
        rows = []  # (ix, query, qsum [R], mask [I])
        for ix, q in queries:
            q_ix = resolve_ids(model.item_ix, q.items)
            if len(q_ix) == 0:
                logger.info("No productFeatures vector for query items %s.",
                            q.items)
                continue
            qsum = model.item_factors_normalized[q_ix].sum(axis=0)
            rows.append((ix, q, qsum, self._build_mask(model, q, q_ix)))
        fetch = None
        if rows:
            k_max = max(q.num for _, q, _, _ in rows)
            fetch = masked_top_k_batch_begin(
                model.item_factors_normalized,
                np.stack([r[2] for r in rows]),
                np.stack([r[3] for r in rows]), k_max)

        def finish():
            if fetch is not None:
                scores, idx = fetch()
                props_of = model.properties_of(
                    self.params.return_properties)
                for row, (ix, q, _, _) in enumerate(rows):
                    s, i = unpack_top_k_rows(scores[row], idx[row],
                                             q.num)
                    out[ix] = top_scores_to_result(
                        model.item_ix, s, i, properties_of=props_of)
            return list(out.items())
        return finish


class LikeAlgorithm(ALSAlgorithm):
    """Implicit ALS on like/dislike events (multi variant,
    LikeAlgorithm.scala:15-76): latest event per (user, item) wins — a user
    may like an item and change to dislike later — like maps to rating 1,
    dislike to -1 (a negative implicit signal: confidence with preference
    0). Serve path is the same cosine scan as ALSAlgorithm."""

    def _build_ratings(self, td: TrainingData
                       ) -> Tuple[EntityIdIxMap, EntityIdIxMap, RatingsCOO]:
        likes = td.like_events
        if likes is None or not len(likes):
            raise ValueError("No like/dislike events to train on "
                             "(set read_like_events on the data source)")
        user_ix, ui = EntityIdIxMap.build_with_indices(likes.users)
        item_ix = EntityIdIxMap.build(list(td.items.keys()) +
                                      likes.items.tolist())
        ii = item_ix.to_indices_array(likes.items)
        ui, ii, vals = dedup_ratings(ui, ii, likes.vals, likes.ts,
                                     policy="latest")
        return user_ix, item_ix, RatingsCOO(ui, ii, vals,
                                            len(user_ix), len(item_ix))

    def _fold_users_present(self, td: TrainingData) -> set:
        if td.like_events is None or not len(td.like_events):
            return set()
        return set(np.unique(td.like_events.users).astype(str))

    def _fold_ratings(self, td: TrainingData, user_ix: EntityIdIxMap,
                      item_ix: EntityIdIxMap) -> RatingsCOO:
        likes = td.like_events
        if likes is None or not len(likes):
            raise ValueError("No like/dislike events to fold in")
        ui = user_ix.to_indices_array(likes.users)
        ii = item_ix.to_indices_array(likes.items)
        keep = (ui >= 0) & (ii >= 0)
        ui, ii, vals = dedup_ratings(ui[keep], ii[keep], likes.vals[keep],
                                     likes.ts[keep], policy="latest")
        return RatingsCOO(ui, ii, vals, len(user_ix), len(item_ix))


@dataclass(frozen=True)
class DIMSUMAlgorithmParams(Params):
    """dimsum variant (DIMSUMAlgorithm.scala:23): `threshold` drops
    sub-threshold similarity entries. The TPU build computes the exact
    cosine (ops/similarity.item_cosine_similarities) rather than DIMSUM's
    shuffle-bounding sampling approximation."""
    threshold: float = 0.0
    return_properties: Tuple[str, ...] = ()


@dataclass(kw_only=True)
class DIMSUMModel(ItemMetadataModel, PersistentModel):
    """Precomputed item-item similarity rows + id maps
    (DIMSUMAlgorithm.scala DIMSUMModel). Implements the manual-persistence
    contract the variant demonstrates (IPersistentModel.save to
    /tmp/<id> -> here, <PIO_FS_BASEDIR>/dimsum/<instance_id>)."""
    similarities: np.ndarray              # [I, I] f32, zero diagonal

    @classmethod
    def _dir(cls, instance_id: str) -> str:
        import os
        from predictionio_tpu.data.storage.registry import base_dir
        return os.path.join(base_dir(), "dimsum", instance_id)

    def save(self, instance_id: str, params) -> bool:
        import os
        import pickle
        d = self._dir(instance_id)
        os.makedirs(d, exist_ok=True)
        np.save(os.path.join(d, "similarities.npy"), self.similarities)
        with open(os.path.join(d, "maps.pkl"), "wb") as f:
            pickle.dump({"item_ix": self.item_ix, "items": self.items,
                         "item_categories": self.item_categories,
                         "item_years": self.item_years}, f)
        return True

    @classmethod
    def load(cls, instance_id: str, params) -> "DIMSUMModel":
        import os
        import pickle
        d = cls._dir(instance_id)
        sims = np.load(os.path.join(d, "similarities.npy"))
        with open(os.path.join(d, "maps.pkl"), "rb") as f:
            maps = pickle.load(f)
        return cls(similarities=sims, **maps)


class DIMSUMAlgorithm(P2LAlgorithm):
    """dimsum variant (DIMSUMAlgorithm.scala:67-220): all-pairs item
    cosine similarity from binary view co-occurrence, precomputed at train
    time; predict sums the query items' similarity rows and applies the
    standard candidate filters. Serving is a host row-gather — the model
    IS the score table (the reference serves it from an RDD lookup)."""
    PARAMS_CLASS = DIMSUMAlgorithmParams
    QUERY_CLASS = Query

    def __init__(self, params=None):
        super().__init__(params or DIMSUMAlgorithmParams())

    def train(self, pd: PreparedData) -> DIMSUMModel:
        td = pd.td
        if not len(td.view_events):
            raise ValueError("No view events to train on")
        views = td.view_events
        user_ix, ui = EntityIdIxMap.build_with_indices(views.users)
        item_ix = EntityIdIxMap.build(list(td.items.keys()) +
                                      views.items.tolist())
        ii = item_ix.to_indices_array(views.items)
        sims = item_cosine_similarities(
            ui, ii, len(user_ix), len(item_ix),
            threshold=self.params.threshold)
        return DIMSUMModel(
            similarities=sims,
            **ItemMetadataModel.metadata_kwargs(td.items, item_ix))

    def predict(self, model: DIMSUMModel, query: Query) -> ItemScoreResult:
        q_ix = resolve_ids(model.item_ix, query.items)
        if len(q_ix) == 0:
            logger.info("No similarity row for query items %s.", query.items)
            return ItemScoreResult(())
        scores = model.similarities[q_ix].sum(axis=0)
        mask = ALSAlgorithm._build_mask(model, query, q_ix)
        scores = np.where(mask & (scores > 0), scores, -np.inf)
        k = min(query.num, len(scores))
        idx = np.argpartition(-scores, k - 1)[:k]
        idx = idx[np.argsort(-scores[idx], kind="stable")]
        keep = np.isfinite(scores[idx])
        return top_scores_to_result(
            model.item_ix, scores[idx][keep], idx[keep],
            properties_of=model.properties_of(self.params.return_properties))


class SimilarProductEngineFactory(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            {"": SimilarProductDataSource},
            {"": SimilarProductPreparator},
            {"als": ALSAlgorithm, "likealgo": LikeAlgorithm,
             "dimsum": DIMSUMAlgorithm},
            {"": FirstServing})

    @classmethod
    def engine_params(cls, key: str = "") -> EngineParams:
        return EngineParams(
            data_source_params=("", DataSourceParams()),
            preparator_params=("", None),
            algorithm_params_list=[("als", ALSAlgorithmParams())],
            serving_params=("", None))
