"""Recommended-user engine template: implicit ALS over follow events.

Rebuilds `scala-parallel-similarproduct/recommended-user` (reference:
examples/scala-parallel-similarproduct/recommended-user/src/main/scala/ —
DataSource.scala:30-85 reads `$set` user entities and `(user, follow,
followedUser)` events; ALSAlgorithm.scala:60-110 runs `ALS.trainImplicit`
over (user, followedUser, 1) triples; predict :110-165 scores every
followed user by summed cosine similarity of the query users' factors with
white/black-list filters, query users excluded, score > 0 kept).

The serve path is the same masked-matmul + on-device top-k as the
similarproduct template — the "item" table is the followed-user factor
table.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.core import (DataSource, Engine, EngineFactory,
                                   EngineParams, FirstServing, P2LAlgorithm,
                                   Params, Preparator, SanityCheck)
from predictionio_tpu.data.bimap import EntityIdIxMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.common import RatingsData, resolve_ids
from predictionio_tpu.ops.als import ALSConfig, als_train
from predictionio_tpu.ops.ratings import RatingsCOO, dedup_ratings
from predictionio_tpu.ops.similarity import (build_filter_mask, cosine_top_k,
                                             normalize_rows)

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class FollowEvent:
    user: str
    followed_user: str
    t: int = 0


@dataclass
class TrainingData(SanityCheck):
    """follow_events is columnar (RatingsData: users=follower,
    items=followed); FollowEvent row lists are accepted and converted."""
    users: Dict[str, dict]
    follow_events: RatingsData

    def __post_init__(self):
        if isinstance(self.follow_events, (list, tuple)):
            self.follow_events = RatingsData(
                np.array([e.user for e in self.follow_events], dtype=str),
                np.array([e.followed_user for e in self.follow_events],
                         dtype=str),
                np.ones(len(self.follow_events), dtype=np.float32),
                np.array([e.t for e in self.follow_events],
                         dtype=np.int64))

    def sanity_check(self):
        if not len(self.follow_events):
            raise ValueError("follow_events is empty; check the data source")


@dataclass(frozen=True)
class Query:
    """(Engine.scala:6-11: users list + num + white/black lists)"""
    users: Tuple[str, ...]
    num: int
    white_list: Optional[Tuple[str, ...]] = None
    black_list: Optional[Tuple[str, ...]] = None

    @staticmethod
    def from_dict(d: dict) -> "Query":
        def opt(key):
            v = d.get(key)
            return tuple(v) if v is not None else None
        return Query(users=tuple(d["users"]), num=int(d["num"]),
                     white_list=opt("whiteList"), black_list=opt("blackList"))


@dataclass(frozen=True)
class UserScore:
    user: str
    score: float


@dataclass(frozen=True)
class UserScoreResult:
    """PredictedResult of similarUserScores (ALSAlgorithm.scala:160-165)."""
    similar_user_scores: Tuple[UserScore, ...]

    def to_dict(self) -> dict:
        return {"similarUserScores": [{"user": s.user, "score": s.score}
                                      for s in self.similar_user_scores]}


@dataclass
class PreparedData:
    td: TrainingData


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None


class RecommendedUserDataSource(DataSource):
    PARAMS_CLASS = DataSourceParams

    def __init__(self, params=None):
        super().__init__(params or DataSourceParams())

    def read_training(self) -> TrainingData:
        app = self.params.app_name
        chan = self.params.channel_name
        users = {eid: dict(pm.fields) for eid, pm in
                 PEventStore.aggregate_properties(
                     app_name=app, channel_name=chan,
                     entity_type="user").items()}
        # columnar ingest: flat arrays, no per-event Python objects
        fc = PEventStore.find_columnar(
            app_name=app, channel_name=chan, entity_type="user",
            event_names=["follow"], target_entity_type="user")
        follows = RatingsData(fc["entity_id"], fc["target_entity_id"],
                              np.ones(len(fc["t"]), dtype=np.float32),
                              fc["t"])
        return TrainingData(users=users, follow_events=follows)


class RecommendedUserPreparator(Preparator):
    def prepare(self, td: TrainingData) -> PreparedData:
        return PreparedData(td)


@dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 20
    lam: float = 0.01
    seed: Optional[int] = None
    compute_dtype: Optional[str] = None  # None = bf16 on TPU, f32 on CPU
    # solver-call batching / whole-iteration fusion (ops/als.ALSConfig
    # sweep_chunk / fuse_iteration; 0 = auto)
    sweep_chunk: int = 0
    fuse_iteration: bool = False


@dataclass
class RecommendedUserModel:
    """similarUserFeatures + id map (ALSAlgorithm.scala ALSModel)."""
    followed_factors_normalized: np.ndarray   # [F, R] L2-normalized rows
    followed_ix: EntityIdIxMap


class RecommendedUserALSAlgorithm(P2LAlgorithm):
    PARAMS_CLASS = ALSAlgorithmParams
    QUERY_CLASS = Query

    def __init__(self, params=None):
        super().__init__(params or ALSAlgorithmParams())

    def train(self, pd: PreparedData) -> RecommendedUserModel:
        td = pd.td
        p = self.params
        if not len(td.follow_events):
            raise ValueError("No follow events to train on")
        fd = td.follow_events
        follower_ix, ui = EntityIdIxMap.build_with_indices(fd.users)
        followed_ix, ii = EntityIdIxMap.build_with_indices(fd.items)
        ui, ii, counts = dedup_ratings(ui, ii, fd.vals, policy="sum")
        coo = RatingsCOO(ui, ii, counts, len(follower_ix), len(followed_ix))
        from predictionio_tpu.ops.als import default_compute_dtype
        cfg = ALSConfig(rank=p.rank, iterations=p.num_iterations, lam=p.lam,
                        sweep_chunk=p.sweep_chunk,
                        fuse_iteration=p.fuse_iteration,
                        implicit_prefs=True, alpha=1.0,
                        seed=p.seed if p.seed is not None else 0,
                        compute_dtype=p.compute_dtype
                        or default_compute_dtype())
        self.last_train_telemetry = {}
        model = als_train(coo, cfg,
                          telemetry=self.last_train_telemetry)
        return RecommendedUserModel(
            followed_factors_normalized=normalize_rows(model.item_factors),
            followed_ix=followed_ix)

    def _query_rows(self, model: RecommendedUserModel, query: Query
                    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Resolve query users to factor rows + the candidate mask."""
        q_ix = resolve_ids(model.followed_ix, query.users)
        if len(q_ix) == 0:
            logger.info("No similarUserFeatures vector for query users %s.",
                        query.users)
            return q_ix, None
        white = (resolve_ids(model.followed_ix, query.white_list)
                 if query.white_list is not None else None)
        black = resolve_ids(model.followed_ix, query.black_list or ())
        mask = build_filter_mask(
            len(model.followed_ix),
            exclude=np.concatenate([q_ix, black]),  # query users excluded
            white_list=white)
        return q_ix, mask

    @staticmethod
    def _to_result(model: RecommendedUserModel, scores: np.ndarray,
                   idx: np.ndarray) -> UserScoreResult:
        return UserScoreResult(tuple(
            UserScore(model.followed_ix.id_of(int(i)), float(s))
            for s, i in zip(scores, idx)))

    def predict(self, model: RecommendedUserModel, query: Query
                ) -> UserScoreResult:
        q_ix, mask = self._query_rows(model, query)
        if mask is None:
            return UserScoreResult(())
        query_vecs = model.followed_factors_normalized[q_ix]
        scores, idx = cosine_top_k(model.followed_factors_normalized,
                                   query_vecs, query.num, mask)
        return self._to_result(model, scores, idx)

    def batch_predict(self, model, queries):
        """Batched path: summed normalized query vectors, one masked
        matmul + top-k device call for the batch."""
        from predictionio_tpu.ops.similarity import (masked_top_k_batch,
                                                     unpack_top_k_rows)
        out = {ix: UserScoreResult(()) for ix, _ in queries}
        rows = []
        for ix, q in queries:
            q_ix, mask = self._query_rows(model, q)
            if mask is None:
                continue
            qsum = model.followed_factors_normalized[q_ix].sum(axis=0)
            rows.append((ix, q, qsum, mask))
        if rows:
            k_max = max(q.num for _, q, _, _ in rows)
            scores, idx = masked_top_k_batch(
                model.followed_factors_normalized,
                np.stack([r[2] for r in rows]),
                np.stack([r[3] for r in rows]), k_max)
            for row, (ix, q, _, _) in enumerate(rows):
                s, i = unpack_top_k_rows(scores[row], idx[row], q.num)
                out[ix] = self._to_result(model, s, i)
        return list(out.items())


class RecommendedUserEngineFactory(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            {"": RecommendedUserDataSource},
            {"": RecommendedUserPreparator},
            {"als": RecommendedUserALSAlgorithm},
            {"": FirstServing})

    @classmethod
    def engine_params(cls, key: str = "") -> EngineParams:
        return EngineParams(
            data_source_params=("", DataSourceParams()),
            preparator_params=("", None),
            algorithm_params_list=[("als", ALSAlgorithmParams())],
            serving_params=("", None))
