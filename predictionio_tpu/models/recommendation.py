"""Recommendation engine template: explicit ALS on rate/buy events.

Rebuilds `scala-parallel-recommendation` (reference:
examples/scala-parallel-recommendation/custom-prepartor/src/main/scala/
ALSAlgorithm.scala:27-86 — MLlib `ALS.train` on rate/buy events, predict =
`model.recommendProducts`; DataSource.scala:20-46 reads rate/buy from the
event store, buy counts as rating 4.0; duplicate ratings keep the latest
event). The MLlib call becomes ops.als explicit training on the mesh.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from predictionio_tpu.core import (DataSource, Engine, EngineFactory,
                                   EngineParams, FirstServing, Metric,
                                   P2LAlgorithm, Params, Preparator,
                                   SanityCheck)
from predictionio_tpu.data.bimap import EntityIdIxMap
from predictionio_tpu.data.event import to_millis
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.common import (ItemScoreResult,
                                            top_scores_to_result)
from predictionio_tpu.ops.als import ALSConfig, ALSModel, als_train, \
    recommend_products
from predictionio_tpu.ops.ratings import RatingsCOO, dedup_ratings

logger = logging.getLogger(__name__)


# -- data shapes ------------------------------------------------------------

@dataclass(frozen=True)
class Rating:
    user: str
    item: str
    rating: float
    t: int = 0  # event-time millis (dedup tie-break)


@dataclass
class TrainingData(SanityCheck):
    ratings: List[Rating]

    def sanity_check(self):
        if not self.ratings:
            raise ValueError("ratings is empty; check the data source")


@dataclass(frozen=True)
class Query:
    user: str
    num: int

    @staticmethod
    def from_dict(d: dict) -> "Query":
        return Query(user=str(d["user"]), num=int(d["num"]))


@dataclass
class PreparedData:
    ratings_coo: RatingsCOO
    user_ix: EntityIdIxMap
    item_ix: EntityIdIxMap


# -- DASE components --------------------------------------------------------

@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None
    event_names: Tuple[str, ...] = ("rate", "buy")
    buy_rating: float = 4.0  # implicit rating assigned to buy events
    eval_k: Optional[int] = None    # enable k-fold read_eval when set
    eval_query_num: int = 10        # query.num used for eval queries


@dataclass(frozen=True)
class ActualResult:
    """Ratings the test fold holds for the queried user (the template
    evaluation's ground truth)."""
    ratings: Tuple[Rating, ...]


class RecommendationDataSource(DataSource):
    PARAMS_CLASS = DataSourceParams

    def __init__(self, params=None):
        super().__init__(params or DataSourceParams())

    def _read_ratings(self) -> List[Rating]:
        p = self.params
        ratings = []
        for e in PEventStore.find(app_name=p.app_name,
                                  channel_name=p.channel_name,
                                  entity_type="user",
                                  target_entity_type="item",
                                  event_names=list(p.event_names)):
            if e.event == "rate":
                rating = e.properties.get("rating", float)
            else:  # buy
                rating = p.buy_rating
            ratings.append(Rating(e.entity_id, e.target_entity_id, rating,
                                  to_millis(e.event_time)))
        return ratings

    def read_training(self) -> TrainingData:
        return TrainingData(self._read_ratings())

    def read_eval(self):
        """k-fold split of rating events; one query per test-fold user with
        that user's held-out ratings as the actual (the recommendation
        template's Evaluation DataSource shape)."""
        p = self.params
        if not p.eval_k:
            return []
        ratings = self._read_ratings()
        folds = []
        for fold in range(p.eval_k):
            train = [r for i, r in enumerate(ratings) if i % p.eval_k != fold]
            test = [r for i, r in enumerate(ratings) if i % p.eval_k == fold]
            by_user = {}
            for r in test:
                by_user.setdefault(r.user, []).append(r)
            qa = [(Query(user=user, num=p.eval_query_num),
                   ActualResult(tuple(rs)))
                  for user, rs in sorted(by_user.items())]
            folds.append((TrainingData(train), None, qa))
        return folds


@dataclass(frozen=True)
class PreparatorParams(Params):
    dedup: str = "latest"


class RecommendationPreparator(Preparator):
    """Builds the dense vocabulary + dedup'd COO (the BiMap.stringInt step
    of the reference's preparator/algorithm, done once host-side)."""
    PARAMS_CLASS = PreparatorParams

    def __init__(self, params=None):
        super().__init__(params or PreparatorParams())

    def prepare(self, td: TrainingData) -> PreparedData:
        user_ix = EntityIdIxMap.build((r.user for r in td.ratings))
        item_ix = EntityIdIxMap.build((r.item for r in td.ratings))
        ui = user_ix.to_indices([r.user for r in td.ratings])
        ii = item_ix.to_indices([r.item for r in td.ratings])
        vals = np.array([r.rating for r in td.ratings], dtype=np.float32)
        ts = np.array([r.t for r in td.ratings], dtype=np.int64)
        ui, ii, vals = dedup_ratings(ui, ii, vals, ts, self.params.dedup)
        coo = RatingsCOO(ui, ii, vals, len(user_ix), len(item_ix))
        return PreparedData(coo, user_ix, item_ix)


@dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lam: float = 0.01
    seed: Optional[int] = None
    compute_dtype: Optional[str] = None  # None = bf16 on TPU, f32 on CPU


@dataclass
class RecommendationModel:
    als: ALSModel
    user_ix: EntityIdIxMap
    item_ix: EntityIdIxMap


class ALSAlgorithm(P2LAlgorithm):
    """Explicit ALS (ALSAlgorithm.scala:27-86)."""
    PARAMS_CLASS = ALSAlgorithmParams
    QUERY_CLASS = Query

    def __init__(self, params=None):
        super().__init__(params or ALSAlgorithmParams())

    def train(self, pd: PreparedData) -> RecommendationModel:
        p = self.params
        if pd.ratings_coo.nnz == 0:
            raise ValueError("No ratings to train on")
        from predictionio_tpu.ops.als import default_compute_dtype
        cfg = ALSConfig(rank=p.rank, iterations=p.num_iterations, lam=p.lam,
                        seed=p.seed if p.seed is not None else 0,
                        compute_dtype=p.compute_dtype
                        or default_compute_dtype())
        model = als_train(pd.ratings_coo, cfg)
        return RecommendationModel(model, pd.user_ix, pd.item_ix)

    def predict(self, model: RecommendationModel, query: Query
                ) -> ItemScoreResult:
        uix = model.user_ix.get(query.user, -1)
        if uix < 0:
            logger.info("No prediction for unknown user %s.", query.user)
            return ItemScoreResult(())
        scores, idx = recommend_products(model.als, int(uix), query.num)
        return top_scores_to_result(model.item_ix, scores, idx)

    def batch_predict(self, model, queries):
        """Evaluation path: one batched device top-k for all known users
        (vs the reference's per-query driver loop)."""
        from predictionio_tpu.ops.als import _users_topk
        from predictionio_tpu.utils.device_cache import cached_put
        out = {ix: ItemScoreResult(()) for ix, _ in queries}
        known = [(ix, q, int(model.user_ix.get(q.user, -1)))
                 for ix, q in queries]
        known = [(ix, q, uix) for ix, q, uix in known if uix >= 0]
        if known:
            k_max = min(max(q.num for _, q, _ in known), model.als.n_items)
            # pad the batch dim to a power of two so the jitted scorer
            # compiles once per size class, not per request-batch size;
            # only the [B] index vector crosses to the device
            b = 1 << (len(known) - 1).bit_length()
            user_ixs = np.zeros(b, dtype=np.int32)
            user_ixs[:len(known)] = [uix for _, _, uix in known]
            scores, idx = _users_topk(
                cached_put(model.als.user_factors),
                cached_put(model.als.item_factors), user_ixs, k_max)
            scores = np.asarray(scores)
            idx = np.asarray(idx)
            for row, (ix, q, _) in enumerate(known):
                out[ix] = top_scores_to_result(
                    model.item_ix, scores[row][:q.num], idx[row][:q.num])
        return list(out.items())


class PrecisionAtK(Metric):
    """Precision@K with a positive-rating threshold (the recommendation
    template's tuning metric). None (skipped) when a user has no positive
    actuals, matching OptionAverageMetric semantics."""

    def __init__(self, k: int = 10, rating_threshold: float = 2.0):
        self.k = k
        self.rating_threshold = rating_threshold

    def header(self) -> str:
        return f"PrecisionAtK(k={self.k}, threshold={self.rating_threshold})"

    def calculate(self, eval_data) -> float:
        vals = []
        for _, qpa in eval_data:
            for q, p, a in qpa:
                positives = {r.item for r in a.ratings
                             if r.rating >= self.rating_threshold}
                if not positives:
                    continue
                top = [s.item for s in p.item_scores[:self.k]]
                if not top:
                    vals.append(0.0)
                    continue
                hits = sum(1 for item in top if item in positives)
                vals.append(hits / min(self.k, len(top)))
        return float("nan") if not vals else float(np.mean(vals))


class RecommendationEngineFactory(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            {"": RecommendationDataSource},
            {"": RecommendationPreparator},
            {"als": ALSAlgorithm},
            {"": FirstServing})

    @classmethod
    def engine_params(cls) -> EngineParams:
        return EngineParams(
            data_source_params=("", DataSourceParams()),
            preparator_params=("", PreparatorParams()),
            algorithm_params_list=[("als", ALSAlgorithmParams())],
            serving_params=("", None))
