"""Recommendation engine template: explicit ALS on rate/buy events.

Rebuilds `scala-parallel-recommendation` (reference:
examples/scala-parallel-recommendation/custom-prepartor/src/main/scala/
ALSAlgorithm.scala:27-86 — MLlib `ALS.train` on rate/buy events, predict =
`model.recommendProducts`; DataSource.scala:20-46 reads rate/buy from the
event store, buy counts as rating 4.0; duplicate ratings keep the latest
event). The MLlib call becomes ops.als explicit training on the mesh.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from predictionio_tpu.core import (DataSource, Engine, EngineFactory,
                                   EngineParams, FirstServing, Metric,
                                   P2LAlgorithm, Params, Preparator,
                                   SanityCheck)
from predictionio_tpu.data.bimap import EntityIdIxMap
from predictionio_tpu.core.persistence import (PersistentModel,
                                               PersistentModelLoader)
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models.common import (ItemScoreResult, RatingsData,
                                            top_scores_to_result)
from predictionio_tpu.ops.als import ALSConfig, ALSModel, als_train, \
    recommend_products
from predictionio_tpu.ops.ratings import RatingsCOO, dedup_ratings

logger = logging.getLogger(__name__)


# -- data shapes ------------------------------------------------------------

@dataclass(frozen=True)
class Rating:
    user: str
    item: str
    rating: float
    t: int = 0  # event-time millis (dedup tie-break)


@dataclass
class TrainingData(SanityCheck):
    """`ratings` is columnar (RatingsData); a plain list of Rating rows is
    accepted and converted, so hand-built fixtures keep working."""
    ratings: RatingsData
    items: Optional[dict] = None  # id -> property dict (read_items variants)
    # True when this payload came from an entity-filtered read
    # (read_training_touched): it holds ONLY the touched entities'
    # complete histories, not the corpus — valid fold-in input, never
    # valid retrain input
    touched_only: bool = False

    def __post_init__(self):
        if isinstance(self.ratings, (list, tuple)):
            self.ratings = RatingsData.from_rows(self.ratings)

    def sanity_check(self):
        if not len(self.ratings):
            raise ValueError("ratings is empty; check the data source")


@dataclass(frozen=True)
class Query:
    """Base query is (user, num); the custom-query and filter-by-category
    variants add creationYear and categories (custom-query/Engine.scala:6,
    filter-by-category/Engine.scala:6-10) — optional here, so the base wire
    format is unchanged."""
    user: str
    num: int
    categories: Optional[Tuple[str, ...]] = None
    creation_year: Optional[int] = None

    @staticmethod
    def from_dict(d: dict) -> "Query":
        cats = d.get("categories")
        return Query(user=str(d["user"]), num=int(d["num"]),
                     categories=tuple(cats) if cats is not None else None,
                     creation_year=(int(d["creationYear"])
                                    if d.get("creationYear") is not None
                                    else None))


@dataclass
class PreparedData:
    ratings_coo: RatingsCOO
    user_ix: EntityIdIxMap
    item_ix: EntityIdIxMap
    items: Optional[dict] = None  # id -> property dict


# -- DASE components --------------------------------------------------------

@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None
    event_names: Tuple[str, ...] = ("rate", "buy")
    buy_rating: float = 4.0  # implicit rating assigned to buy events
    eval_k: Optional[int] = None    # enable k-fold read_eval when set
    eval_query_num: int = 10        # query.num used for eval queries
    # custom-query / filter-by-category variants: read $set item properties
    # (categories, creationYear, ...) for predict-time filters
    read_items: bool = False
    # bulk data plane (ISSUE 16): stream the training read through
    # chunked store cursors + double-buffered device staging instead of
    # one monolithic scan. None defers to PIO_DATAPLANE_STREAM; the
    # streamed read is exact-parity with the batch one (chunk-wise
    # _ratings_from_cols concat == global; the preparator's sorted
    # np.unique vocabulary is order-independent), so this is a
    # throughput knob, never a semantics knob.
    stream: Optional[bool] = None


@dataclass(frozen=True)
class ActualResult:
    """Ratings the test fold holds for the queried user (the template
    evaluation's ground truth)."""
    ratings: Tuple[Rating, ...]


class RecommendationDataSource(DataSource):
    PARAMS_CLASS = DataSourceParams

    def __init__(self, params=None):
        super().__init__(params or DataSourceParams())

    def _read_ratings(self) -> RatingsData:
        """Columnar ingest: one projected scan into flat numpy arrays
        (DataSource.scala:20-46 eventsRDD -> ratingsRDD, without 20M
        Python objects at ML-20M scale)."""
        p = self.params
        if self._stream_active():
            return self._read_ratings_streamed()
        cols = PEventStore.find_columnar(
            app_name=p.app_name, channel_name=p.channel_name,
            property_field="rating", entity_type="user",
            target_entity_type="item", event_names=list(p.event_names))
        return self._ratings_from_cols(cols, p)

    def _stream_active(self) -> bool:
        s = getattr(self.params, "stream", None)
        if s is not None:
            return bool(s)
        return os.environ.get("PIO_DATAPLANE_STREAM", "").lower() in (
            "1", "true", "yes", "on")

    def _read_ratings_streamed(self) -> RatingsData:
        """The same read through the bulk data plane: chunked store
        cursors decoded per chunk (overlapped with the reader thread)
        while the numeric training columns double-buffer onto the
        device. Chunk-wise ``_ratings_from_cols`` + concat is
        row-for-row identical to the monolithic scan — the chunk
        contract never splits a millisecond, and every conversion here
        is row-wise."""
        from predictionio_tpu.dataplane import (BulkLoadExecutor,
                                                StreamInterner)
        p = self.params
        users_in, items_in = StreamInterner(), StreamInterner()

        def decode(chunk):
            return self._ratings_from_cols(chunk, p)

        def encode(rd):
            # interned dense ids now; remap_to_sorted reconciles them
            # with the preparator's sorted vocabulary at finalize
            return {"user_ix": users_in.encode(rd.users),
                    "item_ix": items_in.encode(rd.items),
                    "vals": rd.vals, "t": rd.ts}

        result = BulkLoadExecutor().run(
            p.app_name, channel_name=p.channel_name,
            property_field="rating", decode=decode, encode=encode,
            entity_type="user", target_entity_type="item",
            event_names=list(p.event_names))
        st = result.stats
        logger.info(
            "streamed ratings read: %d rows / %d chunks, read %.2fs "
            "decode %.2fs h2d %.1f MB overlap %.0f%% compiles(steady) %d",
            st.rows, st.chunks, st.read_s, st.decode_s,
            st.h2d_bytes / 1e6, 100.0 * st.h2d_overlap_frac,
            st.steady_compiles)
        parts = result.decoded
        if not parts:
            return RatingsData(
                np.array([], dtype=str), np.array([], dtype=str),
                np.array([], dtype=np.float32),
                np.array([], dtype=np.int64))
        return RatingsData(
            np.concatenate([r.users for r in parts]),
            np.concatenate([r.items for r in parts]),
            np.concatenate([r.vals for r in parts]),
            np.concatenate([r.ts for r in parts]))

    @staticmethod
    def _ratings_from_cols(cols, p) -> RatingsData:
        is_rate = cols["event"] == "rate"
        missing = is_rate & np.isnan(cols["prop"])
        if missing.any():
            raise ValueError(
                f"{int(missing.sum())} 'rate' event(s) lack the required "
                f"'rating' property (first entity: "
                f"{cols['entity_id'][missing][0]!r})")
        vals = np.where(is_rate, cols["prop"],
                        np.float32(p.buy_rating)).astype(np.float32)
        return RatingsData(cols["entity_id"], cols["target_entity_id"],
                           vals, cols["t"])

    def _read_items(self) -> Optional[dict]:
        if not self.params.read_items:
            return None
        return {eid: dict(pm.fields) for eid, pm in
                PEventStore.aggregate_properties(
                    app_name=self.params.app_name,
                    channel_name=self.params.channel_name,
                    entity_type="item").items()}

    def read_training(self) -> TrainingData:
        return TrainingData(self._read_ratings(), items=self._read_items())

    def read_training_touched(self, touched_users,
                              touched_items) -> TrainingData:
        """Entity-filtered fold-tick read: only the touched users'
        complete rating histories plus every rating landing on a touched
        item — exactly the rows the touched-row least-squares solves
        consume (their dedup and per-entity regularizers see complete
        histories, so the folded factors match the full-scan path). Cost
        is O(touched histories) through each backend's pushdown
        (``find_columnar_by_entities``), not a corpus scan."""
        p = self.params
        cols = PEventStore.find_columnar_by_entities(
            app_name=p.app_name, channel_name=p.channel_name,
            entity_ids=[str(u) for u in touched_users],
            target_entity_ids=[str(i) for i in touched_items],
            property_field="rating", entity_type="user",
            target_entity_type="item", event_names=list(p.event_names))
        items = None
        if p.read_items:
            items = self._read_items_for([str(i) for i in touched_items])
        return TrainingData(self._ratings_from_cols(cols, p),
                            items=items, touched_only=True)

    def _read_items_for(self, item_ids) -> dict:
        """Aggregate $set/$unset/$delete for the given items only (k
        indexed point reads instead of the corpus-wide property scan;
        the app/channel names resolve ONCE, not per id)."""
        from predictionio_tpu.data.aggregator import aggregate_properties
        from predictionio_tpu.data.storage.base import aggregate_event_names
        app_id, channel_id = PEventStore.resolve(
            self.params.app_name, self.params.channel_name)
        ev = PEventStore.events
        events = []
        for iid in item_ids:
            events.extend(ev.find(
                app_id=app_id, channel_id=channel_id,
                entity_type="item", entity_id=iid,
                event_names=list(aggregate_event_names())))
        return {eid: dict(pm.fields)
                for eid, pm in aggregate_properties(events).items()}

    def read_eval(self):
        """k-fold split of rating events; one query per test-fold user with
        that user's held-out ratings as the actual (the recommendation
        template's Evaluation DataSource shape)."""
        p = self.params
        if not p.eval_k:
            return []
        ratings = self._read_ratings()
        row_ix = np.arange(len(ratings))
        folds = []
        for fold in range(p.eval_k):
            test_mask = (row_ix % p.eval_k) == fold
            train = ratings.select(~test_mask)
            by_user = {}
            for r in ratings.select(test_mask):
                by_user.setdefault(r.user, []).append(r)
            qa = [(Query(user=user, num=p.eval_query_num),
                   ActualResult(tuple(rs)))
                  for user, rs in sorted(by_user.items())]
            folds.append((TrainingData(train), None, qa))
        return folds


@dataclass(frozen=True)
class PreparatorParams(Params):
    dedup: str = "latest"
    # custom-prepartor variant (Preparator.scala:13-27): newline-separated
    # item ids excluded from training before the vocabulary is built.
    exclude_items_file: Optional[str] = None


class RecommendationPreparator(Preparator):
    """Builds the dense vocabulary + dedup'd COO (the BiMap.stringInt step
    of the reference's preparator/algorithm, done once host-side)."""
    PARAMS_CLASS = PreparatorParams

    def __init__(self, params=None):
        super().__init__(params or PreparatorParams())

    def prepare(self, td: TrainingData) -> PreparedData:
        rd = td.ratings
        if self.params.exclude_items_file:
            with open(self.params.exclude_items_file) as f:
                no_train = sorted({line.strip() for line in f
                                   if line.strip()})
            rd = rd.select(~np.isin(rd.items, no_train))
        # one np.unique pass per side builds the sorted vocabulary AND the
        # dense indices (no per-row dict probes)
        user_ix, ui = EntityIdIxMap.build_with_indices(rd.users)
        item_ix, ii = EntityIdIxMap.build_with_indices(rd.items)
        ui, ii, vals = dedup_ratings(ui, ii, rd.vals, rd.ts,
                                     self.params.dedup)
        coo = RatingsCOO(ui, ii, vals, len(user_ix), len(item_ix))
        return PreparedData(coo, user_ix, item_ix, items=td.items)


@dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lam: float = 0.01
    seed: Optional[int] = None
    compute_dtype: Optional[str] = None  # None = bf16 on TPU, f32 on CPU
    # custom-query variant: property keys copied onto each ItemScore in the
    # result JSON (e.g. ("creationYear",)); requires data source read_items
    return_properties: Tuple[str, ...] = ()
    # solver-call batching / whole-iteration fusion (ops/als.ALSConfig
    # sweep_chunk / fuse_iteration; 0 = auto)
    sweep_chunk: int = 0
    fuse_iteration: bool = False
    # sharded online plane (ISSUE 12): 'model' trains, folds AND
    # serves the factor tables row-sharded over the mesh model axis
    # (ShardedTable handles end to end) — the configuration for
    # vocabularies whose table bytes exceed one device's budget.
    # 'replicated' (default) keeps the single-device-table layout.
    factor_sharding: str = "replicated"


@dataclass
class RecommendationModel:
    als: ALSModel
    user_ix: EntityIdIxMap
    item_ix: EntityIdIxMap
    # by dense item index; present when the data source read item properties
    item_properties: Optional[List[Optional[dict]]] = None
    # derived at train time so per-query masks are vectorized, not
    # O(n_items) Python loops on the serve path
    item_categories: Optional[List[Optional[set]]] = None
    item_years: Optional[np.ndarray] = None  # float32, NaN = undated

    @staticmethod
    def derive_filters(item_properties):
        if item_properties is None:
            return None, None
        cats = [set(p["categories"]) if p and p.get("categories") else None
                for p in item_properties]
        years = np.array(
            [float(p["creationYear"])
             if p and p.get("creationYear") is not None else np.nan
             for p in item_properties], dtype=np.float32)
        return cats, years

    def properties_of(self, keys: Tuple[str, ...]):
        """ItemScore property passthrough: requested keys always present
        (missing -> None/null, the Option[Int] wire shape of
        custom-query/Engine.scala:12)."""
        if not keys or self.item_properties is None:
            return None
        props = self.item_properties

        def get(ix: int):
            p = props[ix] or {}
            return {k: p.get(k) for k in keys}
        return get

    def allowed_mask(self, query: Query) -> Optional[np.ndarray]:
        """Candidate mask for the filter variants; None = no filtering.
        categories: item must share a category (filter-by-category; empty
        list = no filter, as in the other templates); creationYear: undated
        items pass, dated items need year >= query's
        (custom-query/ALSAlgorithm.scala:141-148)."""
        from predictionio_tpu.ops.similarity import build_filter_mask
        want_cats = set(query.categories) if query.categories else None
        if want_cats is None and query.creation_year is None:
            return None
        n = len(self.item_ix)
        mask = build_filter_mask(
            n, item_categories=self.item_categories, categories=want_cats)
        if query.creation_year is not None and self.item_years is not None:
            dated = ~np.isnan(self.item_years)
            mask &= ~(dated & (self.item_years < query.creation_year))
        return mask


class ALSAlgorithm(P2LAlgorithm):
    """Explicit ALS (ALSAlgorithm.scala:27-86)."""
    PARAMS_CLASS = ALSAlgorithmParams
    QUERY_CLASS = Query

    def __init__(self, params=None):
        super().__init__(params or ALSAlgorithmParams())

    def train(self, pd: PreparedData) -> RecommendationModel:
        p = self.params
        if pd.ratings_coo.nnz == 0:
            raise ValueError("No ratings to train on")
        from predictionio_tpu.ops.als import default_compute_dtype
        sharded = getattr(p, "factor_sharding", "replicated") == "model"
        mesh = None
        if sharded:
            # the process-wide model mesh: fold ticks and server
            # threads resolve the same one for this shard count
            from predictionio_tpu.parallel.mesh import model_mesh
            import jax
            mesh = model_mesh(len(jax.devices()))
        cfg = ALSConfig(rank=p.rank, iterations=p.num_iterations, lam=p.lam,
                        sweep_chunk=p.sweep_chunk,
                        fuse_iteration=p.fuse_iteration,
                        seed=p.seed if p.seed is not None else 0,
                        compute_dtype=p.compute_dtype
                        or default_compute_dtype(),
                        factor_sharding=("model" if sharded
                                         else "replicated"),
                        keep_sharded=sharded)
        # per-phase timing of the train that just ran (plan/upload/iters/
        # fetch) — consumed by bench.py's product-path mode; the hard
        # syncs it adds are negligible next to a real train
        self.last_train_telemetry = {}
        model = als_train(pd.ratings_coo, cfg, mesh=mesh,
                          telemetry=self.last_train_telemetry)
        item_properties = None
        if pd.items is not None:
            item_properties = [pd.items.get(pd.item_ix.id_of(ix))
                               for ix in range(len(pd.item_ix))]
        cats, years = RecommendationModel.derive_filters(item_properties)
        return RecommendationModel(model, pd.user_ix, pd.item_ix,
                                   item_properties=item_properties,
                                   item_categories=cats, item_years=years)

    def predict(self, model: RecommendationModel, query: Query
                ) -> ItemScoreResult:
        uix = model.user_ix.get(query.user, -1)
        if uix < 0:
            logger.info("No prediction for unknown user %s.", query.user)
            return ItemScoreResult(())
        props_of = model.properties_of(self.params.return_properties)
        mask = model.allowed_mask(query)
        from predictionio_tpu.parallel.sharded_table import (is_sharded,
                                                             table_rows)
        if mask is None:
            if is_sharded(model.als.item_factors):
                # sharded single-query route: the same per-shard
                # top-k + merge executables the batched path runs
                from predictionio_tpu.ops.als import users_topk_serve
                from predictionio_tpu.ops.similarity import \
                    unpack_top_k_rows
                scores, idx = users_topk_serve(model.als, [int(uix)],
                                               query.num)
                s, i = unpack_top_k_rows(scores[0], idx[0], query.num)
                return top_scores_to_result(model.item_ix, s, i,
                                            properties_of=props_of)
            scores, idx = recommend_products(model.als, int(uix), query.num)
            return top_scores_to_result(model.item_ix, scores, idx,
                                        properties_of=props_of)
        # filtered path: ship the fixed-shape [I] bool mask, not a dense
        # exclude-index array whose length would recompile the kernel
        from predictionio_tpu.ops.similarity import (masked_top_k_batch,
                                                     unpack_top_k_rows)
        scores, idx = masked_top_k_batch(
            model.als.item_factors,
            table_rows(model.als.user_factors, [int(uix)]), mask[None],
            query.num, filter_positive=False)
        s, i = unpack_top_k_rows(scores[0], idx[0], query.num)
        return top_scores_to_result(model.item_ix, s, i,
                                    properties_of=props_of)

    # -- online updates (ISSUE 1: predictionio_tpu/online) -----------------
    def fold_in(self, model: RecommendationModel, td: TrainingData,
                touched_users, touched_items,
                preparator_params: Optional[PreparatorParams] = None
                ) -> Tuple[RecommendationModel, dict]:
        """Absorb fresh events without a retrain: grow the vocabularies
        with unseen touched entities (existing dense indices — and the
        deployed factor rows behind them — never move), then re-solve
        ONLY the touched user/item rows against the current data
        (online/fold_in.fold_in_coo; explicit ALS-WR normal equations,
        the same math `train` runs per sweep).

        ``td`` must be the CURRENT training data (the scheduler re-reads
        it through the data source): the touched rows' solves are
        least-squares over exactly what they are given, so a partial
        history would bias them toward the fresh slice.
        ``preparator_params`` replays the deployed Preparator's data
        policy (dedup mode, exclude_items_file) — the fold cannot run
        prepare() itself because prepare rebuilds vocabularies and would
        shuffle the deployed dense indices. Returns (new_model, report)
        where report carries the post-fold training loss the scheduler's
        drift gate consumes."""
        from predictionio_tpu.online.fold_in import (FoldInConfig,
                                                     fold_in_coo)
        from predictionio_tpu.ops.als import als_rmse
        p = self.params
        prep = preparator_params or PreparatorParams()
        rd = td.ratings
        if prep.exclude_items_file:
            with open(prep.exclude_items_file) as f:
                no_train = sorted({line.strip() for line in f
                                   if line.strip()})
            if no_train:
                rd = rd.select(~np.isin(rd.items, no_train))
                touched_items = [i for i in touched_items
                                 if str(i) not in set(no_train)]
        # grow only entities that actually have ratings: a property-only
        # $set for an unseen user/item must NOT mint a zero factor row
        # (an unknown user answers cold-start-empty, which is honest;
        # a zero row would answer all-zero scores)
        present_u = set(np.unique(rd.users).astype(str))
        present_i = set(np.unique(rd.items).astype(str))
        user_ix, _ = model.user_ix.grow(
            u for u in map(str, touched_users) if u in present_u)
        item_ix, _ = model.item_ix.grow(
            i for i in map(str, touched_items) if i in present_i)
        ui = user_ix.to_indices_array(rd.users)
        ii = item_ix.to_indices_array(rd.items)
        keep = (ui >= 0) & (ii >= 0)
        ui, ii, vals = dedup_ratings(ui[keep], ii[keep], rd.vals[keep],
                                     rd.ts[keep], prep.dedup)
        coo = RatingsCOO(ui, ii, vals, len(user_ix), len(item_ix))
        tu = user_ix.to_indices([str(u) for u in touched_users])
        ti = item_ix.to_indices([str(i) for i in touched_items])
        from predictionio_tpu.ops.als import default_compute_dtype
        from predictionio_tpu.parallel.sharded_table import is_sharded
        sharded = is_sharded(model.als.user_factors)
        cfg = FoldInConfig(
            lam=p.lam, sweeps=2,
            compute_dtype=p.compute_dtype or default_compute_dtype(),
            sweep_chunk=p.sweep_chunk,
            factor_sharding="model" if sharded else "replicated")
        # residency slot per deployed algorithm instance: consecutive
        # ticks through the same scheduler reuse the device tables and
        # upload only touched-row plans (fold_in_coo validates the slot
        # against the model's host arrays, so a swapped-out model misses)
        new_als, stats = fold_in_coo(
            model.als, coo, tu[tu >= 0], ti[ti >= 0], cfg,
            resident_key=f"fold:{type(self).__name__}:{id(self)}")
        if stats.degenerate:
            # nothing solvable this tick (ISSUE 5 satellite: touched
            # set emptied by filtering, or all-zero ratings): keep the
            # deployed model OBJECT so the scheduler can tell a no-op
            # from a publishable fold
            return model, {"algorithm": type(self).__name__,
                           "degenerate": True, "wallS": stats.wall_s}
        item_properties = model.item_properties
        if item_properties is not None and len(item_ix) > len(item_properties):
            # new items: carry fresh $set properties when the data source
            # read them, else None (no filter metadata yet)
            items = td.items or {}
            item_properties = list(item_properties) + [
                items.get(item_ix.id_of(ix))
                for ix in range(len(item_properties), len(item_ix))]
        cats, years = RecommendationModel.derive_filters(item_properties)
        new_model = RecommendationModel(
            new_als, user_ix, item_ix, item_properties=item_properties,
            item_categories=cats, item_years=years)
        report = {
            "algorithm": type(self).__name__,
            "loss": als_rmse(new_als, coo),
            "userRows": stats.n_user_rows, "itemRows": stats.n_item_rows,
            "newUsers": stats.n_new_users, "newItems": stats.n_new_items,
            "wallS": stats.wall_s, "residentHit": stats.resident_hit,
            "sentinelRollback": stats.sentinel_rollback,
            "guardWallS": stats.guard_wall_s,
        }
        if stats.sharded:
            report["sharding"] = {
                "layout": "model",
                "shards": new_als.user_factors.n_shards}
        return new_model, report

    # -- compile plane (ISSUE 9) -------------------------------------------
    def aot_warm_specs(self, model, batch_hint: int = 16):
        """(label, bucket-dims) rows for this model's serve executables
        — consumed by ``compile.aot.warm_models`` at deploy / hot-swap /
        canary-stage time so the FIRST query after a swap compiles
        nothing. Covers the micro-batcher's coalescing ladder (1..the
        configured window, pow2) and the gates golden-replay bucket
        (the probe answers through the same executable)."""
        from predictionio_tpu.compile import buckets as B
        from predictionio_tpu.obs import costmon
        from predictionio_tpu.ops.als import (batch_predict_dims,
                                              register_aot_specs)
        register_aot_specs()
        batches = sorted({1} | {1 << e for e in range(
            1, B.bucket_batch(max(batch_hint, 1)).bit_length())})
        return [(costmon.BATCH_PREDICT,
                 batch_predict_dims(model.als, b, 16))
                for b in batches]

    def batch_predict(self, model, queries):
        """Evaluation/serving path: one batched device top-k for all known
        users (vs the reference's per-query driver loop), through the
        compile plane — vocab/batch/k shape-buckets + AOT registry
        dispatch (ops.als.users_topk_serve), so a warmed server answers
        with zero trace and zero compile. Queries carrying category/year
        filters take a second batched call with per-query candidate
        masks."""
        return self.batch_predict_begin(model, queries)()

    def batch_predict_begin(self, model, queries):
        """Two-phase batch predict for the pipelined serving executor
        (ISSUE 14): partition + enqueue the device top-k NOW (async
        dispatch returns the moment the work is queued) and return
        ``finish() -> [(ix, result)]`` performing the deferred
        device->host readback and result building — the completion
        stage, callable from another thread, so window N's readback /
        serialization overlaps window N+1's formation and dispatch."""
        props_of = model.properties_of(self.params.return_properties)
        out = {ix: ItemScoreResult(()) for ix, _ in queries}
        plain, masked = [], []
        for ix, q in queries:
            uix = int(model.user_ix.get(q.user, -1))
            if uix < 0:
                logger.info("No prediction for unknown user %s.", q.user)
                continue
            mask = model.allowed_mask(q)
            (plain if mask is None else masked).append((ix, q, uix, mask))
        plain_fetch = masked_fetch = None
        if plain:
            from predictionio_tpu.ops.als import users_topk_serve_begin
            k_max = min(max(q.num for _, q, _, _ in plain),
                        model.als.n_items)
            # compile attribution (obs/costmon): a gates golden-query
            # replay keeps its gates_probe label; live serving books
            # under batch_predict
            from predictionio_tpu.obs import costmon
            with costmon.executable(costmon.BATCH_PREDICT,
                                    defer_to_outer=True):
                plain_fetch = users_topk_serve_begin(
                    model.als, [uix for _, _, uix, _ in plain], k_max)
        if masked:
            from predictionio_tpu.ops.similarity import \
                masked_top_k_batch_begin
            from predictionio_tpu.parallel.sharded_table import table_rows
            k_max = max(q.num for _, q, _, _ in masked)
            masked_fetch = masked_top_k_batch_begin(
                model.als.item_factors,
                table_rows(model.als.user_factors,
                           [uix for _, _, uix, _ in masked]),
                np.stack([mask for _, _, _, mask in masked]),
                k_max, filter_positive=False)

        def finish():
            from predictionio_tpu.ops.similarity import unpack_top_k_rows
            if plain_fetch is not None:
                scores, idx = plain_fetch()
                for row, (ix, q, _, _) in enumerate(plain):
                    # bucketed k may exceed n_items: padding slots carry
                    # -inf and are dropped here
                    s, i = unpack_top_k_rows(scores[row], idx[row],
                                             q.num)
                    out[ix] = top_scores_to_result(
                        model.item_ix, s, i, properties_of=props_of)
            if masked_fetch is not None:
                scores, idx = masked_fetch()
                for row, (ix, q, _, _) in enumerate(masked):
                    s, i = unpack_top_k_rows(scores[row], idx[row],
                                             q.num)
                    out[ix] = top_scores_to_result(
                        model.item_ix, s, i, properties_of=props_of)
            return list(out.items())
        return finish


class ShardedALSModelCheckpoint(PersistentModel, PersistentModelLoader):
    """Persistence mode 2 for the mesh model: factor tables checkpoint
    through orbax/tensorstore (each host writes its shards; restore
    re-shards on read) instead of being gathered into a pickle — the
    TPU-native replacement for the reference's 'persist the model RDD'
    pattern (controller/PersistentModel.scala:64; SURVEY §5
    checkpoint/resume). Only a manifest naming this loader is stored in
    MODELDATA."""

    def __init__(self, model: Optional[RecommendationModel] = None):
        self.model = model

    def save(self, instance_id: str, params) -> bool:
        import os
        from predictionio_tpu.parallel.sharded_table import is_sharded
        from predictionio_tpu.utils.checkpoint import (checkpoint_dir,
                                                       save_sharded)

        def _np(t):
            return t.to_numpy() if is_sharded(t) else t

        d = checkpoint_dir(instance_id)
        ok = save_sharded(
            os.path.join(d, "factors"),
            {"user_factors": _np(self.model.als.user_factors),
             "item_factors": _np(self.model.als.item_factors)})
        np.savez(os.path.join(d, "vocab.npz"),
                 users=np.asarray(self.model.user_ix._ids, dtype=str),
                 items=np.asarray(self.model.item_ix._ids, dtype=str))
        return ok

    def load(self, instance_id: str, params) -> "RecommendationModel":
        import os
        from predictionio_tpu.data.bimap import BiMap
        from predictionio_tpu.utils.checkpoint import (checkpoint_dir,
                                                       restore_sharded)
        d = checkpoint_dir(instance_id)
        arrays = restore_sharded(os.path.join(d, "factors"))
        with np.load(os.path.join(d, "vocab.npz")) as z:
            user_ix = EntityIdIxMap(BiMap(
                {str(u): i for i, u in enumerate(z["users"])}))
            item_ix = EntityIdIxMap(BiMap(
                {str(it): i for i, it in enumerate(z["items"])}))
        uf = np.asarray(arrays["user_factors"], dtype=np.float32)
        vf = np.asarray(arrays["item_factors"], dtype=np.float32)
        als = ALSModel(user_factors=uf, item_factors=vf,
                       rank=uf.shape[1])
        return RecommendationModel(als, user_ix, item_ix)


class MeshALSAlgorithm(ALSAlgorithm):
    """P-placement variant: factor tables are trained AND SERVED
    model-sharded across the mesh — nothing is ever replicated to one
    device, so catalogs larger than a single chip's HBM serve directly
    (reference: controller/PAlgorithm.scala:44-125 distributed-model
    lookup; enable with algorithm name 'als-mesh' in engine.json).
    Persistence: sharded checkpoint + manifest (ShardedALSModelCheckpoint)
    instead of the PAlgorithm retrain-on-deploy default."""
    placement = "mesh"

    def make_persistent_model(self, model: RecommendationModel):
        return ShardedALSModelCheckpoint(model)

    def train(self, pd: PreparedData) -> RecommendationModel:
        p = self.params
        if pd.ratings_coo.nnz == 0:
            raise ValueError("No ratings to train on")
        from predictionio_tpu.ops.als import default_compute_dtype
        cfg = ALSConfig(rank=p.rank, iterations=p.num_iterations, lam=p.lam,
                        sweep_chunk=p.sweep_chunk,
                        fuse_iteration=p.fuse_iteration,
                        seed=p.seed if p.seed is not None else 0,
                        compute_dtype=p.compute_dtype
                        or default_compute_dtype(),
                        factor_sharding="model")
        self.last_train_telemetry = {}
        model = als_train(pd.ratings_coo, cfg,
                          telemetry=self.last_train_telemetry)
        item_properties = None
        if pd.items is not None:
            item_properties = [pd.items.get(pd.item_ix.id_of(ix))
                               for ix in range(len(pd.item_ix))]
        cats, years = RecommendationModel.derive_filters(item_properties)
        return RecommendationModel(model, pd.user_ix, pd.item_ix,
                                   item_properties=item_properties,
                                   item_categories=cats, item_years=years)

    def predict(self, model: RecommendationModel, query: Query
                ) -> ItemScoreResult:
        from predictionio_tpu.ops.als import recommend_products_sharded
        uix = model.user_ix.get(query.user, -1)
        if uix < 0:
            logger.info("No prediction for unknown user %s.", query.user)
            return ItemScoreResult(())
        scores, idx = recommend_products_sharded(
            model.als, int(uix), query.num,
            allowed_mask=model.allowed_mask(query))
        return top_scores_to_result(
            model.item_ix, scores, idx,
            properties_of=model.properties_of(
                self.params.return_properties))

    def batch_predict(self, model, queries):
        # sharded ranking is already a collective per query; map predict
        return [(ix, self.predict(model, q)) for ix, q in queries]

    def aot_warm_specs(self, model, batch_hint: int = 16):
        # the sharded serve path runs GSPMD collectives per query —
        # per-process AOT Compiled dispatch does not apply (and the
        # single-device batch_predict executable is never used here)
        return []


class PrecisionAtK(Metric):
    """Precision@K with a positive-rating threshold (the recommendation
    template's tuning metric). None (skipped) when a user has no positive
    actuals, matching OptionAverageMetric semantics."""

    def __init__(self, k: int = 10, rating_threshold: float = 2.0):
        self.k = k
        self.rating_threshold = rating_threshold

    def header(self) -> str:
        return f"PrecisionAtK(k={self.k}, threshold={self.rating_threshold})"

    def calculate(self, eval_data) -> float:
        vals = []
        for _, qpa in eval_data:
            for q, p, a in qpa:
                positives = {r.item for r in a.ratings
                             if r.rating >= self.rating_threshold}
                if not positives:
                    continue
                top = [s.item for s in p.item_scores[:self.k]]
                if not top:
                    vals.append(0.0)
                    continue
                hits = sum(1 for item in top if item in positives)
                vals.append(hits / min(self.k, len(top)))
        return float("nan") if not vals else float(np.mean(vals))


class RecommendationEngineFactory(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            {"": RecommendationDataSource},
            {"": RecommendationPreparator},
            {"als": ALSAlgorithm, "als-mesh": MeshALSAlgorithm},
            {"": FirstServing})

    @classmethod
    def engine_params(cls, key: str = "") -> EngineParams:
        return EngineParams(
            data_source_params=("", DataSourceParams()),
            preparator_params=("", PreparatorParams()),
            algorithm_params_list=[("als", ALSAlgorithmParams())],
            serving_params=("", None))
