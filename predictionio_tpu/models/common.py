"""Shared query/result shapes and helpers for the engine templates.

The JSON wire shapes (ItemScore / PredictedResult) match the reference
templates byte-for-byte (reference: examples/scala-parallel-*/src/main/scala/
Engine.scala Query/PredictedResult/ItemScore case classes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Optional, Sequence

import numpy as np

from predictionio_tpu.data.bimap import EntityIdIxMap


@dataclass(frozen=True)
class ItemScore:
    """Optional extra item properties ride along in the result JSON — the
    custom-query variant returns creationYear on each ItemScore
    (custom-query/Engine.scala:12) and add-and-return-item-properties does
    the same for arbitrary properties."""
    item: str
    score: float
    properties: Optional[Mapping] = field(default=None, compare=False)

    def to_dict(self):
        d = {"item": self.item, "score": float(self.score)}
        if self.properties:
            # never let a property named "item"/"score" clobber the wire
            # fields
            d.update({k: v for k, v in self.properties.items()
                      if k not in ("item", "score")})
        return d


@dataclass(frozen=True)
class ItemScoreResult:
    item_scores: Sequence[ItemScore]

    def to_dict(self):
        return {"itemScores": [s.to_dict() for s in self.item_scores]}


def resolve_ids(ix_map: EntityIdIxMap, ids: Optional[Sequence[str]]
                ) -> np.ndarray:
    """String ids -> known dense indices (unknowns dropped, matching the
    reference's `.map(map.get).flatten`)."""
    if not ids:
        return np.array([], dtype=np.int32)
    ixs = ix_map.to_indices(list(ids))
    return ixs[ixs >= 0]


def top_scores_to_result(ix_map: EntityIdIxMap, scores: np.ndarray,
                         idx: np.ndarray,
                         properties_of=None) -> ItemScoreResult:
    """properties_of: optional callable dense-index -> property dict (or
    None) merged into each ItemScore's JSON."""
    items = ix_map.ids_of(idx) if len(idx) else []
    if properties_of is None:
        return ItemScoreResult(tuple(
            ItemScore(item, float(s)) for item, s in zip(items, scores)))
    return ItemScoreResult(tuple(
        ItemScore(item, float(s), properties_of(int(i)))
        for item, s, i in zip(items, scores, idx)))
