"""Shared query/result shapes and helpers for the engine templates.

The JSON wire shapes (ItemScore / PredictedResult) match the reference
templates byte-for-byte (reference: examples/scala-parallel-*/src/main/scala/
Engine.scala Query/PredictedResult/ItemScore case classes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Optional, Sequence

import numpy as np

from predictionio_tpu.data.bimap import EntityIdIxMap


@dataclass(frozen=True)
class ItemScore:
    """Optional extra item properties ride along in the result JSON — the
    custom-query variant returns creationYear on each ItemScore
    (custom-query/Engine.scala:12) and add-and-return-item-properties does
    the same for arbitrary properties."""
    item: str
    score: float
    properties: Optional[Mapping] = field(default=None, compare=False)

    def to_dict(self):
        d = {"item": self.item, "score": float(self.score)}
        if self.properties:
            # never let a property named "item"/"score" clobber the wire
            # fields
            d.update({k: v for k, v in self.properties.items()
                      if k not in ("item", "score")})
        return d


@dataclass(frozen=True)
class ItemScoreResult:
    item_scores: Sequence[ItemScore]

    def to_dict(self):
        return {"itemScores": [s.to_dict() for s in self.item_scores]}


class RatingsData:
    """Columnar (user, item, rating, t) quadruples — the template training
    payload as four flat numpy arrays instead of a list of per-event
    objects (the RDD[Rating] role of the reference DataSources, e.g.
    scala-parallel-recommendation DataSource.scala:20-46, kept columnar so
    ML-20M-scale ingest never builds 20M Python objects).

    Iteration yields lightweight row views for code that wants per-row
    access (eval fold grouping, tests); the hot paths slice the arrays.
    """

    __slots__ = ("users", "items", "vals", "ts")

    def __init__(self, users, items, vals, ts=None):
        self.users = np.asarray(users)
        self.items = np.asarray(items)
        self.vals = np.asarray(vals, dtype=np.float32)
        self.ts = (np.zeros(len(self.vals), dtype=np.int64)
                   if ts is None else np.asarray(ts, dtype=np.int64))

    @staticmethod
    def from_rows(rows: Sequence) -> "RatingsData":
        """Rows with .user/.item/.rating (and optional .t) attributes."""
        return RatingsData(
            np.array([r.user for r in rows], dtype=str),
            np.array([r.item for r in rows], dtype=str),
            np.array([r.rating for r in rows], dtype=np.float32),
            np.array([getattr(r, "t", 0) for r in rows], dtype=np.int64))

    def __len__(self) -> int:
        return int(self.vals.shape[0])

    def __bool__(self) -> bool:
        return len(self) > 0

    def __iter__(self):
        from collections import namedtuple
        Row = namedtuple("Rating", ("user", "item", "rating", "t"))
        for u, i, v, t in zip(self.users, self.items, self.vals, self.ts):
            yield Row(str(u), str(i), float(v), int(t))

    def select(self, mask_or_idx) -> "RatingsData":
        return RatingsData(self.users[mask_or_idx], self.items[mask_or_idx],
                           self.vals[mask_or_idx], self.ts[mask_or_idx])


def resolve_ids(ix_map: EntityIdIxMap, ids: Optional[Sequence[str]]
                ) -> np.ndarray:
    """String ids -> known dense indices (unknowns dropped, matching the
    reference's `.map(map.get).flatten`)."""
    if not ids:
        return np.array([], dtype=np.int32)
    ixs = ix_map.to_indices(list(ids))
    return ixs[ixs >= 0]


def top_scores_to_result(ix_map: EntityIdIxMap, scores: np.ndarray,
                         idx: np.ndarray,
                         properties_of=None) -> ItemScoreResult:
    """properties_of: optional callable dense-index -> property dict (or
    None) merged into each ItemScore's JSON."""
    items = ix_map.ids_of(idx) if len(idx) else []
    if properties_of is None:
        return ItemScoreResult(tuple(
            ItemScore(item, float(s)) for item, s in zip(items, scores)))
    return ItemScoreResult(tuple(
        ItemScore(item, float(s), properties_of(int(i)))
        for item, s, i in zip(items, scores, idx)))
