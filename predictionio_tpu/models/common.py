"""Shared query/result shapes and helpers for the engine templates.

The JSON wire shapes (ItemScore / PredictedResult) match the reference
templates byte-for-byte (reference: examples/scala-parallel-*/src/main/scala/
Engine.scala Query/PredictedResult/ItemScore case classes).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from predictionio_tpu.data.bimap import EntityIdIxMap


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float

    def to_dict(self):
        return {"item": self.item, "score": float(self.score)}


@dataclass(frozen=True)
class ItemScoreResult:
    item_scores: Sequence[ItemScore]

    def to_dict(self):
        return {"itemScores": [s.to_dict() for s in self.item_scores]}


def resolve_ids(ix_map: EntityIdIxMap, ids: Optional[Sequence[str]]
                ) -> np.ndarray:
    """String ids -> known dense indices (unknowns dropped, matching the
    reference's `.map(map.get).flatten`)."""
    if not ids:
        return np.array([], dtype=np.int32)
    ixs = ix_map.to_indices(list(ids))
    return ixs[ixs >= 0]


def top_scores_to_result(ix_map: EntityIdIxMap, scores: np.ndarray,
                         idx: np.ndarray) -> ItemScoreResult:
    items = ix_map.ids_of(idx) if len(idx) else []
    return ItemScoreResult(tuple(
        ItemScore(item, float(s)) for item, s in zip(items, scores)))
