"""Classification engine template: naive bayes over aggregated user
properties.

Rebuilds `scala-parallel-classification` (reference:
examples/scala-parallel-classification/add-algorithm/src/main/scala/
NaiveBayesAlgorithm.scala:19-25 — MLlib NaiveBayes on `$set`-aggregated user
properties attr0/attr1/attr2 with label `plan`; DataSource.scala
readTraining uses aggregateProperties). Includes the template's evaluation
wiring (k-fold Accuracy, as in the quickstart's Evaluation.scala).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from predictionio_tpu.core import (AverageMetric, DataSource, Engine,
                                   EngineFactory, EngineParams, FirstServing,
                                   P2LAlgorithm, Params, Preparator,
                                   SanityCheck)
from predictionio_tpu.core.cross_validation import split_data
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.forest import ForestModel, forest_train
from predictionio_tpu.ops.naive_bayes import (MultinomialNBModel,
                                              multinomial_nb_train)

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class LabeledPoint:
    label: float
    features: Tuple[float, ...]


@dataclass
class TrainingData(SanityCheck):
    labeled_points: List[LabeledPoint]

    def sanity_check(self):
        if not self.labeled_points:
            raise ValueError("labeled_points is empty; check the data source")


@dataclass(frozen=True)
class Query:
    attr0: float
    attr1: float
    attr2: float

    @staticmethod
    def from_dict(d: dict) -> "Query":
        return Query(attr0=float(d["attr0"]), attr1=float(d["attr1"]),
                     attr2=float(d["attr2"]))

    @property
    def features(self) -> np.ndarray:
        return np.array([self.attr0, self.attr1, self.attr2],
                        dtype=np.float32)


@dataclass(frozen=True)
class PredictedResult:
    label: float

    def to_dict(self):
        return {"label": self.label}


@dataclass(frozen=True)
class ActualResult:
    label: float


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel_name: Optional[str] = None
    eval_k: Optional[int] = None  # enable k-fold read_eval when set


class ClassificationDataSource(DataSource):
    PARAMS_CLASS = DataSourceParams

    def __init__(self, params=None):
        super().__init__(params or DataSourceParams())

    def _read_points(self) -> List[LabeledPoint]:
        props = PEventStore.aggregate_properties(
            app_name=self.params.app_name,
            channel_name=self.params.channel_name, entity_type="user",
            required=["plan", "attr0", "attr1", "attr2"])
        points = []
        for entity_id, pm in props.items():
            try:
                points.append(LabeledPoint(
                    label=pm.get("plan", float),
                    features=(pm.get("attr0", float), pm.get("attr1", float),
                              pm.get("attr2", float))))
            except Exception as e:
                logger.error("Cannot convert %s to LabeledPoint: %s",
                             entity_id, e)
                raise
        return points

    def read_training(self) -> TrainingData:
        return TrainingData(self._read_points())

    def read_eval(self):
        if not self.params.eval_k:
            return []
        points = self._read_points()
        return split_data(
            self.params.eval_k, points, None,
            training_data_creator=TrainingData,
            query_creator=lambda p: Query(*p.features),
            actual_creator=lambda p: ActualResult(p.label))


class ClassificationPreparator(Preparator):
    def prepare(self, td: TrainingData) -> TrainingData:
        return td


@dataclass(frozen=True)
class NaiveBayesAlgorithmParams(Params):
    lam: float = 1.0  # MLlib's lambda smoothing


class NaiveBayesAlgorithm(P2LAlgorithm):
    """(NaiveBayesAlgorithm.scala:19-25)"""
    PARAMS_CLASS = NaiveBayesAlgorithmParams
    QUERY_CLASS = Query

    def __init__(self, params=None):
        super().__init__(params or NaiveBayesAlgorithmParams())

    def train(self, td: TrainingData) -> MultinomialNBModel:
        X = np.array([p.features for p in td.labeled_points],
                     dtype=np.float32)
        y = np.array([p.label for p in td.labeled_points], dtype=np.float64)
        return multinomial_nb_train(X, y, lam=self.params.lam)

    def predict(self, model: MultinomialNBModel, query: Query
                ) -> PredictedResult:
        return PredictedResult(label=model.predict(query.features))

    def batch_predict(self, model, queries):
        if not queries:
            return []
        X = np.stack([q.features for _, q in queries])
        scores = model.pi[None, :] + X.astype(np.float64) @ model.theta.T
        labels = model.labels[np.argmax(scores, axis=1)]
        return [(ix, PredictedResult(label=float(lab)))
                for (ix, _), lab in zip(queries, labels)]


@dataclass(frozen=True)
class RandomForestAlgorithmParams(Params):
    """Knob-for-knob with the add-algorithm variant's
    RandomForestAlgorithmParams (RandomForestAlgorithm.scala:12-19)."""
    num_classes: int = 4
    num_trees: int = 10
    feature_subset_strategy: str = "auto"
    impurity: str = "gini"
    max_depth: int = 5
    max_bins: int = 32
    seed: int = 42


class RandomForestAlgorithm(P2LAlgorithm):
    """add-algorithm variant (RandomForestAlgorithm.scala:23-52): same
    P2L placement — cluster-scale train, host-resident model — with the
    level-synchronous TPU forest of ops/forest.py replacing MLlib's
    RandomForest.trainClassifier."""
    PARAMS_CLASS = RandomForestAlgorithmParams
    QUERY_CLASS = Query

    def __init__(self, params=None):
        super().__init__(params or RandomForestAlgorithmParams())

    def train(self, td: TrainingData) -> ForestModel:
        X = np.array([p.features for p in td.labeled_points],
                     dtype=np.float32)
        y = np.array([p.label for p in td.labeled_points], dtype=np.float64)
        p = self.params
        return forest_train(
            X, y, num_classes=p.num_classes, num_trees=p.num_trees,
            feature_subset_strategy=p.feature_subset_strategy,
            impurity=p.impurity, max_depth=p.max_depth,
            max_bins=p.max_bins, seed=p.seed)

    def predict(self, model: ForestModel, query: Query) -> PredictedResult:
        return PredictedResult(label=model.predict(query.features))

    def batch_predict(self, model, queries):
        if not queries:
            return []
        X = np.stack([q.features for _, q in queries]).astype(np.float32)
        labels = model.predict_batch(X)
        return [(ix, PredictedResult(label=float(lab)))
                for (ix, _), lab in zip(queries, labels)]


class Accuracy(AverageMetric):
    """(quickstart Evaluation.scala Accuracy metric)"""

    def calculate_one(self, query, predicted, actual) -> float:
        return 1.0 if predicted.label == actual.label else 0.0


class ClassificationEngineFactory(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            {"": ClassificationDataSource},
            {"": ClassificationPreparator},
            {"naive": NaiveBayesAlgorithm,
             "randomforest": RandomForestAlgorithm},
            {"": FirstServing})

    @classmethod
    def engine_params(cls, key: str = "") -> EngineParams:
        return EngineParams(
            data_source_params=("", DataSourceParams()),
            preparator_params=("", None),
            algorithm_params_list=[("naive", NaiveBayesAlgorithmParams())],
            serving_params=("", None))
