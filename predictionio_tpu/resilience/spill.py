"""Durable ingest spill: a local write-ahead log behind the event store.

The event server's production promise is that an ACKed event is never
lost — but the primary store is a network dependency that fails. When a
write fails (or its circuit breaker is open), the server appends the
event to a local append-only WAL and ACKs ``201 {"spilled": true}``; a
background ``SpillReplayer`` drains the WAL into the primary backend on
recovery, preserving insertion order and deduplicating by event id, so
the spill is invisible to everything downstream (the scheduler's tail
read sees replayed events exactly once).

Framing reuses the nativelog discipline (storage/nativelog.py; the C
log's record = length-prefixed JSON blob + integrity check, torn tail
repaired on open): each record here is

    <u32 payload_len> <u32 crc32(payload)> <payload bytes>

where the payload is the same compact-JSON event dict the nativelog
appends, wrapped in a ``{"appId", "channelId", "event"}`` envelope (the
WAL spans namespaces). A crash mid-append leaves a torn tail that fails
the length/CRC check; ``_recover()`` truncates to the last valid record
on open — any byte-prefix of a flushed WAL is a valid WAL.

Replay durability: the drain cursor (byte offset of the first
un-replayed record) lives in a sidecar file written via temp +
``os.replace`` (crash-atomic). The worst crash outcome is re-replaying
the record between an insert and its cursor advance — idempotent,
because events spill with their ids already assigned and the replayer
get-checks before insert (event-id dedup), the same client-assigned-id
idempotency the eventserver/pgsql backends rely on.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
import time
import zlib
from typing import Iterator, List, Optional, Tuple

from predictionio_tpu.data.event import Event, new_event_id
from predictionio_tpu.obs.slo import lock_probe, timed_acquire
from predictionio_tpu.obs.trace import TRACER
from predictionio_tpu.resilience.policy import TRANSIENT_ERRORS

logger = logging.getLogger(__name__)

_HEADER = struct.Struct("<II")   # payload length, crc32(payload)


class SpillWAL:
    """Append-only spill log + crash-atomic drain cursor.

    Thread-safe: ingest threads append while the replayer reads; the
    lock covers file mutation (append, truncate-on-drain, cursor
    write), reads run against a size snapshot taken under it.
    """

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.cursor_path = path + ".cursor"
        self.fsync = fsync
        self._lock = threading.RLock()
        # contention probe (ISSUE 8 satellite): spill appends are the
        # ingest ACK path during an outage — writer wait on this lock
        # is ack latency, surfaced as
        # pio_lock_wait_seconds{lock=spill_wal_append}
        self._append_lock_wait = lock_probe("spill_wal_append")
        # serializes cursor-file persistence OUTSIDE the append lock
        # (ISSUE 8 triage: checkpoint held _lock across the cursor
        # fsync, convoying concurrent spill acks behind replayer IO)
        self._cursor_io_lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._cursor = self._read_cursor()
        self._size = self._recover()
        if self._cursor > self._size:
            # cursor outlived a WAL the recovery truncated: clamp
            self._cursor = self._size
            self._write_cursor(self._cursor)
        # O(1) pending_count: maintained on append/checkpoint, seeded
        # by one header-only scan (payloads skipped) at open
        self._pending_records = self._count_records_from(self._cursor)
        self._f = open(self.path, "ab")

    # -- framing ------------------------------------------------------------
    def _recover(self) -> int:
        """Scan the log, truncating a torn tail (crash mid-append) to
        the last whole record; returns the valid size."""
        if not os.path.exists(self.path):
            return 0
        valid = 0
        with open(self.path, "rb") as f:
            while True:
                header = f.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    break
                length, crc = _HEADER.unpack(header)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    break
                valid += _HEADER.size + length
        actual = os.path.getsize(self.path)
        if actual != valid:
            logger.warning("spill WAL %s: truncating torn tail "
                           "(%d -> %d bytes)", self.path, actual, valid)
            with open(self.path, "r+b") as f:
                f.truncate(valid)
        return valid

    def _count_records_from(self, offset: int) -> int:
        """Header-only record count from ``offset`` to the valid end
        (payloads are seeked over, not read/decoded)."""
        if offset >= self._size or not os.path.exists(self.path):
            return 0
        n = 0
        with open(self.path, "rb") as f:
            f.seek(offset)
            pos = offset
            while pos < self._size:
                header = f.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    break
                length, _ = _HEADER.unpack(header)
                f.seek(length, 1)
                pos += _HEADER.size + length
                n += 1
        return n

    def _read_cursor(self) -> int:
        try:
            with open(self.cursor_path) as f:
                return int(f.read().strip() or 0)
        except (FileNotFoundError, ValueError):
            return 0

    def _write_cursor(self, offset: int):
        tmp = f"{self.cursor_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(offset))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.cursor_path)

    # -- write side ---------------------------------------------------------
    def append(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        """Durably spill one event; assigns an event id if the event has
        none (the id the client is ACKed with, and the replay dedup
        key). Returns the id."""
        eid = event.event_id or new_event_id()
        envelope = {"appId": app_id, "channelId": channel_id,
                    "event": event.with_id(eid).to_dict()}
        # the ORIGINAL ingest trace id rides the WAL frame (ISSUE 13):
        # a replay — even by a restarted process whose in-memory event
        # map is gone — re-enters the store under the trace the client
        # was ACKed with, not as an untraced write
        tid = TRACER.current_trace_id()
        if tid:
            envelope["traceId"] = tid
        payload = json.dumps(envelope,
                             separators=(",", ":")).encode("utf-8")
        record = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        with timed_acquire(self._lock, self._append_lock_wait):
            self._f.write(record)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
            self._size += len(record)
            self._pending_records += 1
        return eid

    def append_many(self, events, app_id: int,
                    channel_id: Optional[int] = None) -> List[str]:
        """Durably spill a whole batch under ONE lock / write / fsync —
        the columnar bulk-write route's outage path (per-event fsyncs
        during an outage would throttle exactly the burst the WAL
        exists to absorb). Ids are assigned where missing; insertion
        order is the list order, as the replayer expects."""
        eids = []
        frames = []
        tid = TRACER.current_trace_id()
        for event in events:
            eid = event.event_id or new_event_id()
            eids.append(eid)
            envelope = {"appId": app_id, "channelId": channel_id,
                        "event": event.with_id(eid).to_dict()}
            if tid:
                envelope["traceId"] = tid
            payload = json.dumps(envelope,
                                 separators=(",", ":")).encode("utf-8")
            frames.append(
                _HEADER.pack(len(payload), zlib.crc32(payload)) + payload)
        blob = b"".join(frames)
        with timed_acquire(self._lock, self._append_lock_wait):
            self._f.write(blob)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
            self._size += len(blob)
            self._pending_records += len(frames)
        return eids

    # -- read side ----------------------------------------------------------
    def pending(self) -> Iterator[
            Tuple[int, int, Optional[int], Event, Optional[str]]]:
        """Yield ``(offset_after_record, app_id, channel_id, event,
        trace_id)`` for every un-replayed record, in insertion order
        (``trace_id`` is the original ingest trace, None for frames
        written before ISSUE 13 or outside any trace)."""
        with self._lock:
            start, end = self._cursor, self._size
        if start >= end:
            return
        with open(self.path, "rb") as f:
            f.seek(start)
            pos = start
            while pos < end:
                header = f.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    return               # racing recovery truncation
                length, crc = _HEADER.unpack(header)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    return
                pos += _HEADER.size + length
                d = json.loads(payload.decode("utf-8"))
                yield (pos, d["appId"], d.get("channelId"),
                       Event.from_dict(d["event"]), d.get("traceId"))

    def pending_count(self) -> int:
        with self._lock:
            return self._pending_records

    def pending_bytes(self) -> int:
        with self._lock:
            return max(self._size - self._cursor, 0)

    def checkpoint(self, offset: int, records: Optional[int] = None):
        """Advance the drain cursor (crash-atomic). ``records`` is how
        many records the caller consumed up to ``offset`` (the replayer
        always knows); without it the count is recomputed by a
        header-only scan. A fully-drained WAL is compacted back to zero
        bytes so it never grows unboundedly across spill episodes.

        Cursor-file persistence (open + fsync + replace) runs OUTSIDE
        the append lock: a replayer checkpointing mid-recovery must not
        convoy concurrent spill ACKs behind its cursor IO (`pio lint`
        LOCK002). Safe because the cursor is advisory-monotonic: the
        write under ``_cursor_io_lock`` re-reads the latest in-memory
        cursor, and a crash that persists a stale (lower) offset only
        re-replays records the drain already id-dedups."""
        with self._lock:
            if offset <= self._cursor:
                return
            self._cursor = min(offset, self._size)
            if self._cursor >= self._size:
                # fully drained: reset file + cursor instead of letting
                # the pair creep upward forever
                self._f.truncate(0)
                self._f.seek(0)
                self._size = 0
                self._cursor = 0
                self._pending_records = 0
            elif records is not None:
                self._pending_records = max(
                    0, self._pending_records - records)
            else:
                self._pending_records = self._count_records_from(
                    self._cursor)
        self._persist_cursor()

    def _persist_cursor(self):
        """Write the freshest in-memory cursor to the sidecar. The IO
        lock serializes writers; each one re-snapshots ``_cursor`` so
        out-of-order checkpoint threads still persist the newest
        value."""
        with self._cursor_io_lock:
            with self._lock:
                cur = self._cursor
            self._write_cursor(cur)

    def close(self):
        with self._lock:
            self._f.close()


# -- operator inspection (ISSUE 5 satellite: `pio spill`) -------------------
#
# Read-only views over a WAL another process may be writing: no handle
# is kept, nothing is truncated (a torn tail is REPORTED, not repaired —
# repair belongs to the owning server's SpillWAL open).

def _iter_frames(path: str):
    """Read-only frame walk: yield ``(end_offset, payload_bytes)`` for
    every whole CRC-valid record, stopping at a torn tail. The one
    framing parser behind every CLI-side view (the owning server's
    SpillWAL keeps its own handle-and-lock-based readers)."""
    try:
        f = open(path, "rb")
    except FileNotFoundError:
        return
    with f:
        pos = 0
        while True:
            header = f.read(_HEADER.size)
            if len(header) < _HEADER.size:
                return
            length, crc = _HEADER.unpack(header)
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                return
            pos += _HEADER.size + length
            yield pos, payload


def _read_cursor_file(path: str) -> int:
    try:
        with open(path + ".cursor") as f:
            return int(f.read().strip() or 0)
    except (FileNotFoundError, ValueError):
        return 0


def scan_wal(path: str) -> dict:
    """Frame-walk a WAL file without mutating it. Returns totals plus
    the quarantine sidecar's record count."""
    out = {"path": path, "exists": os.path.exists(path),
           "totalRecords": 0, "pendingRecords": 0, "pendingBytes": 0,
           "cursor": _read_cursor_file(path), "validBytes": 0,
           "tornBytes": 0, "quarantined": 0}
    if out["exists"]:
        valid = 0
        for end, _payload in _iter_frames(path):
            valid = end
            out["totalRecords"] += 1
            if end > out["cursor"]:
                out["pendingRecords"] += 1
        out["validBytes"] = valid
        out["tornBytes"] = max(os.path.getsize(path) - valid, 0)
        out["pendingBytes"] = max(valid - min(out["cursor"], valid), 0)
    out["quarantined"] = count_quarantined(path)
    return out


def count_quarantined(path: str) -> int:
    """Record count of WAL ``path``'s quarantine sidecar — a line
    count (the sidecar is one JSON record per line), NOT a WAL
    frame-walk. The cheap read incident capture uses mid-outage."""
    qpath = path + ".quarantine"
    if not os.path.exists(qpath):
        return 0
    with open(qpath) as f:
        return sum(1 for line in f if line.strip())


def iter_pending(path: str, limit: Optional[int] = None):
    """Yield the un-replayed records' envelopes
    (``{"appId", "channelId", "event"}`` dicts) read-only, oldest
    first."""
    n = 0
    cursor = _read_cursor_file(path)
    for end, payload in _iter_frames(path):
        if end <= cursor:
            continue
        yield json.loads(payload.decode("utf-8"))
        n += 1
        if limit is not None and n >= limit:
            return


def read_quarantine(path: str) -> list:
    """The quarantine sidecar's records (``path`` is the WAL path)."""
    qpath = path + ".quarantine"
    out = []
    try:
        with open(qpath) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    except FileNotFoundError:
        pass
    return out


def requeue_quarantined(path: str, events=None) -> Tuple[int, int]:
    """Retry every quarantined record against the primary event store
    directly (the operator fixed whatever made the healthy store reject
    it — a schema change rolled back, a property whitelist updated).

    Deliberately NOT a WAL re-append: the owning server's ``SpillWAL``
    caches its size/cursor, so a second writer's records would be
    invisible to the live replayer — and a drain that empties the
    server's view truncates the file, silently deleting them. Direct
    inserts (id-deduped, the replayer's own idempotency rule) have no
    multi-writer hazard. Records the store still rejects stay
    quarantined. Returns ``(inserted_or_deduped, still_quarantined)``.
    """
    records = read_quarantine(path)
    if not records:
        return 0, 0
    if events is None:
        from predictionio_tpu.data.storage.registry import Storage
        events = Storage.get_events()
    kept = []
    done = 0
    for rec in records:
        event = Event.from_dict(rec["event"])
        app_id, channel_id = rec["appId"], rec.get("channelId")
        try:
            existing = (events.get(event.event_id, app_id, channel_id)
                        if event.event_id else None)
            if existing is None:
                events.insert(event, app_id, channel_id)
            done += 1
        except Exception as e:
            kept.append(dict(rec, error=str(e)))
            logger.warning("requeue: store still rejects event %s: %s",
                           event.event_id, e)
    qpath = path + ".quarantine"
    tmp = f"{qpath}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for rec in kept:
            f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, qpath)
    if not kept:
        os.remove(qpath)
    logger.info("requeue: %d record(s) into the store, %d still "
                "quarantined (%s)", done, len(kept), path)
    return done, len(kept)


class SpillReplayer:
    """Background drain of a ``SpillWAL`` into the primary event store.

    Order-preserving (records replay in insertion order; a failure
    stops the drain at that record rather than skipping it) and
    idempotent (get-check by event id before insert — a crash between
    an insert and its cursor advance re-replays into an overwrite/skip,
    never a duplicate). Inserts run under the store's circuit breaker
    and a jittered retry policy, so a replayer probing a still-down
    backend backs off instead of hammering it.
    """

    def __init__(self, wal: SpillWAL, events, app_breaker=None,
                 policy=None, interval_s: float = 1.0, registry=None,
                 batch_checkpoint: int = 32, quarantine_after: int = 5):
        from predictionio_tpu.resilience.policy import RetryPolicy
        self.wal = wal
        self.events = events
        self.breaker = app_breaker
        self.policy = policy or RetryPolicy(max_attempts=2,
                                            base_delay_s=0.05)
        self.interval_s = interval_s
        self.batch_checkpoint = max(1, batch_checkpoint)
        # poisoned-record guard: a record the HEALTHY store rejects
        # this many drains in a row is moved to the quarantine sidecar
        # so it cannot wedge every later-spilled event behind it
        self.quarantine_after = max(1, quarantine_after)
        self._head_fail_offset: Optional[int] = None
        self._head_fail_count = 0
        self.replayed = 0
        self.deduped = 0
        self.quarantined = 0
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if registry is None:
            from predictionio_tpu.obs import get_registry
            registry = get_registry()
        self._c_replayed = registry.counter(
            "pio_spill_replayed_total",
            "Spilled events drained into the primary event store")
        self._c_deduped = registry.counter(
            "pio_spill_deduped_total",
            "Replay records skipped because the id already existed "
            "(crash-window re-replays)")
        self._c_quarantined = registry.counter(
            "pio_spill_quarantined_total",
            "Replay records the healthy store rejected repeatedly, "
            "moved to the .quarantine sidecar (alert: these need "
            "operator attention)")

    #: the shared outage-class error set (resilience.TRANSIENT_ERRORS —
    #: the same classification the event server spills on). Anything
    #: else is a deterministic rejection by a REACHABLE store — a
    #: breaker success, and quarantine bait.
    TRANSIENT_ERRORS = TRANSIENT_ERRORS

    def _insert_one(self, app_id, channel_id, event: Event,
                    trace_id: Optional[str] = None) -> bool:
        """One record into the primary store; True = inserted, False =
        deduped. Raises on (breaker-gated, retried) failure.

        ``trace_id`` (the frame's original ingest trace, ISSUE 13) is
        re-activated around the insert — discarded from the ring (the
        original trace already committed; a duplicate commit under the
        same id would shadow it) but LIVE as context, so a remote
        store hop carries X-PIO-Trace-Id and any flight record emitted
        under the write stamps the original id — and re-registered in
        the event map so a later fold tick links the original trace,
        not nothing."""
        if trace_id:
            with TRACER.trace("spill_replay_write",
                              trace_id=trace_id) as t:
                t.discard = True
                ok = self._insert_one(app_id, channel_id, event)
            TRACER.register_event(event.event_id, trace_id)
            return ok

        def attempt():
            if self.breaker is not None:
                self.breaker.allow()
            try:
                existing = self.events.get(event.event_id, app_id,
                                           channel_id)
                if existing is None:
                    self.events.insert(event, app_id, channel_id)
            except self.TRANSIENT_ERRORS:
                if self.breaker is not None:
                    self.breaker.record_failure()
                raise
            except Exception:
                # the store ANSWERED (with a rejection): reachable —
                # breaker success, so repeated rejections are visible
                # to the quarantine guard instead of opening the breaker
                if self.breaker is not None:
                    self.breaker.record_success()
                raise
            if self.breaker is not None:
                self.breaker.record_success()
            return existing is None

        return self.policy.call(attempt)

    def _note_head_failure(self, offset: int, app_id, channel_id,
                           event: Event, error: Exception,
                           trace_id: Optional[str] = None) -> bool:
        """Track repeated failures of the record at the drain head.
        Returns True when the record was quarantined (drain may step
        past it). Only DETERMINISTIC rejections count — transient
        (outage-class) errors, including policy-wrapped retries and
        breaker fast-fails, are never quarantine grounds; neither is
        anything that happens while the breaker is not closed."""
        from predictionio_tpu.resilience.policy import CLOSED
        if isinstance(error, self.TRANSIENT_ERRORS):
            # RetryBudgetExceeded and CircuitOpenError are IOErrors,
            # so wrapped transient retries land here too
            return False
        if self.breaker is not None and self.breaker.state != CLOSED:
            return False
        if self._head_fail_offset != offset:
            self._head_fail_offset = offset
            self._head_fail_count = 0
        self._head_fail_count += 1
        if self._head_fail_count < self.quarantine_after:
            return False
        qpath = self.wal.path + ".quarantine"
        rec = {"appId": app_id, "channelId": channel_id,
               "event": event.to_dict(), "error": str(error)}
        if trace_id:
            # the original ingest trace rides into quarantine (ISSUE
            # 13): `pio spill peek --quarantine` keeps the pivot into
            # the outage narrative
            rec["traceId"] = trace_id
        with open(qpath, "a") as f:
            f.write(json.dumps(rec) + "\n")
        self.quarantined += 1
        self._c_quarantined.inc()
        self._head_fail_offset = None
        self._head_fail_count = 0
        logger.error(
            "spill replay: healthy store rejected event %s %d times "
            "(%s) — quarantined to %s; later records resume draining",
            event.event_id, self.quarantine_after, error, qpath)
        try:
            from predictionio_tpu.obs.flight import FLIGHT
            FLIGHT.record("spill_quarantine", eventId=event.event_id,
                          error=str(error))
        except Exception:
            pass
        return True

    #: consecutive same-namespace records per bulk replay flush
    REPLAY_BATCH = 256

    def _insert_batch(self, app_id, channel_id, events,
                      trace_ids=()) -> int:
        """A same-namespace run into the primary via ONE
        ``insert_batch`` (ISSUE 7 satellite: recovery drains at bulk
        speed — exactly when throughput matters), id-deduped by
        get-probes first. Returns the inserted count. Transient
        failures raise after breaker gating + retry; a partial commit
        re-replays as dedups (ids were pre-assigned at spill time).

        ``trace_ids`` parallels ``events``: the original ingest trace
        ids are re-registered on success (ISSUE 13 — the fold tick's
        link source), and when the whole run shares ONE id (a spilled
        batch/columnar write) the insert runs under it as live
        context, so a remote-store hop propagates the header."""
        tids = {t for t in trace_ids if t}
        if len(tids) == 1:
            with TRACER.trace("spill_replay_write",
                              trace_id=next(iter(tids))) as t:
                t.discard = True
                n = self._insert_batch_inner(app_id, channel_id,
                                             events)
        else:
            n = self._insert_batch_inner(app_id, channel_id, events)
        self._register_replayed(events, trace_ids)
        return n

    @staticmethod
    def _register_replayed(events, trace_ids):
        for e, tid in zip(events, trace_ids):
            if tid:
                TRACER.register_event(e.event_id, tid)

    def _insert_batch_inner(self, app_id, channel_id, events) -> int:
        def attempt():
            if self.breaker is not None:
                self.breaker.allow()
            try:
                fresh = [e for e in events
                         if self.events.get(e.event_id, app_id,
                                            channel_id) is None]
                if fresh:
                    self.events.insert_batch(fresh, app_id, channel_id)
            except self.TRANSIENT_ERRORS:
                if self.breaker is not None:
                    self.breaker.record_failure()
                raise
            except Exception:
                # reachable store, deterministic rejection: breaker
                # success; the caller's per-record fallback pinpoints it
                if self.breaker is not None:
                    self.breaker.record_success()
                raise
            if self.breaker is not None:
                self.breaker.record_success()
            return len(fresh)

        return self.policy.call(attempt)

    def drain(self, max_records: Optional[int] = None) -> int:
        """Replay pending records in order until the WAL is empty, the
        cap is hit, or an insert fails. Consecutive records for the
        same (app, channel) land as ONE ``insert_batch`` per
        REPLAY_BATCH run (one group commit / multi-row INSERT instead
        of a per-frame insert — the slowest possible path during
        recovery, which ISSUE 7 retires); a run the store rejects
        deterministically re-replays per record so the poisoned frame
        is pinpointed (and eventually quarantined) exactly as before.
        A transient failure stops the drain AT the failing run
        (nothing is skipped). Returns records replayed+deduped."""
        done = 0
        buf: list = []   # [(offset, event, trace_id)] — one namespace run
        key: Optional[tuple] = None

        def flush_per_record() -> bool:
            """PR 3 semantics for one buffered run: pinpoint / maybe
            quarantine the poisoned record. True = keep draining."""
            nonlocal done
            ok_since = 0
            last = None
            keep = True
            app_id, channel_id = key
            try:
                for offset, event, tid in buf:
                    try:
                        inserted = self._insert_one(app_id, channel_id,
                                                    event,
                                                    trace_id=tid)
                    except Exception as e:
                        self.last_error = str(e)
                        if self._note_head_failure(offset, app_id,
                                                   channel_id, event, e,
                                                   trace_id=tid):
                            # quarantined: step past, keep draining
                            self.wal.checkpoint(offset,
                                                records=ok_since + 1)
                            ok_since = 0
                            last = None
                            continue
                        logger.warning(
                            "spill replay stopped at event %s: %s",
                            event.event_id, e)
                        keep = False
                        break
                    if inserted:
                        self.replayed += 1
                        self._c_replayed.inc()
                    else:
                        self.deduped += 1
                        self._c_deduped.inc()
                    done += 1
                    ok_since += 1
                    last = offset
            finally:
                if last is not None:
                    self.wal.checkpoint(last, records=ok_since)
                buf.clear()
            return keep

        def flush() -> bool:
            """Land one buffered run; True = keep draining."""
            nonlocal done
            if not buf:
                return True
            try:
                inserted = self._insert_batch(
                    key[0], key[1], [e for _, e, _t in buf],
                    trace_ids=[t for _, _e, t in buf])
            except self.TRANSIENT_ERRORS as e:
                # outage-class: stop AT the run head; nothing skipped
                self.last_error = str(e)
                logger.warning("spill replay stopped at event %s: %s",
                               buf[0][1].event_id, e)
                buf.clear()
                return False
            except Exception:
                return flush_per_record()
            self.replayed += inserted
            self._c_replayed.inc(inserted)
            self.deduped += len(buf) - inserted
            self._c_deduped.inc(len(buf) - inserted)
            done += len(buf)
            self.wal.checkpoint(buf[-1][0], records=len(buf))
            buf.clear()
            return True

        exhausted = True
        for offset, app_id, channel_id, event, tid \
                in self.wal.pending():
            k = (app_id, channel_id)
            if key != k or len(buf) >= self.REPLAY_BATCH:
                if buf and not flush():
                    exhausted = False
                    break
                key = k
            buf.append((offset, event, tid))
            if max_records is not None \
                    and done + len(buf) >= max_records:
                exhausted = False
                break
        clean = flush() if buf else True
        if exhausted and clean:
            self.last_error = None
            self._head_fail_offset = None
            self._head_fail_count = 0
        return done

    # -- background loop ----------------------------------------------------
    def start(self) -> "SpillReplayer":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    if self.wal.pending_bytes():
                        from predictionio_tpu.obs import TRACER
                        with TRACER.trace("spill_replay") as tr:
                            n = self.drain()
                            tr.root.attrs["events"] = n
                            tr.discard = n == 0
                            # inside the trace: the record's traceId
                            # is the operator's pivot into the
                            # spill_replay trace just committed
                            if n:
                                from predictionio_tpu.obs.flight \
                                    import FLIGHT
                                FLIGHT.record(
                                    "spill_replay", events=n,
                                    pending=self.wal.pending_count())
                except Exception:
                    logger.exception("spill replay tick failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="pio-spill-replayer")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def stats(self) -> dict:
        return {"pending": self.wal.pending_count(),
                "pendingBytes": self.wal.pending_bytes(),
                "replayed": self.replayed,
                "deduped": self.deduped,
                "lastError": self.last_error}
