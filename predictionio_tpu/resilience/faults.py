"""Seeded chaos injection: wrap storage backends and HTTP hops with
deterministic error / latency / partition faults.

Production failure modes are rehearsed, not hoped about: the chaos test
suite (``pytest -m chaos``, ``scripts/chaos_smoke.sh``) runs the real
ingest -> spill -> replay and serve -> shed paths against injected
faults and asserts zero loss. Faults are SEEDED — the same spec + seed
yields the same decision sequence, so a chaos failure reproduces.

Spec syntax (``PIO_FAULTS`` env var or ``pio faults`` CLI)::

    target:key=value[,key=value...][;target:...]

    PIO_FAULTS='storage.write:error=0.3,seed=42'
    PIO_FAULTS='storage:latency_ms=50,latency_rate=0.5;http:error=0.1'

Targets are dotted names matched by segment prefix: a ``storage``
clause applies to ``storage.write`` and ``storage.read``; operations
consult ``FaultInjector.before(target)`` at their entry point. Keys:

    error=P        raise InjectedFault with probability P
    partition=P    raise ConnectionError (network partition) with prob P
    latency_ms=D   inject D ms of latency ...
    latency_rate=P ... with probability P (default 1.0 when latency set)
    corrupt=P      (alias: nan=P) NaN-corrupt the data at targets that
                   opt in via ``maybe_corrupt_array`` (``fold.ratings``,
                   ``fold.factors``) with probability P — the model-
                   fault analog of error= (ISSUE 5: prove the guard
                   layer keeps poisoned models off live traffic)
    seed=N         RNG seed (whole spec; first clause naming it wins)

``FaultyEvents`` wraps any ``Events`` DAO (write ops consult
``storage.write``, read ops ``storage.read``); the storage registry
applies it automatically when ``PIO_FAULTS`` names a storage target, so
ANY entry point — event server, scheduler, pio import — runs against
the faulted backend with zero code changes. ``wrap_callable`` does the
same for an HTTP hop. Injections are counted per (target, kind) in the
metrics registry (``pio_faults_injected_total``) so a chaos run's
pressure is observable next to the breaker/spill instruments it is
meant to exercise.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from predictionio_tpu.data.storage import base

logger = logging.getLogger(__name__)

ENV_VAR = "PIO_FAULTS"


class InjectedFault(IOError):
    """A fault the chaos harness injected on purpose."""


@dataclass(frozen=True)
class FaultRule:
    """Per-target fault settings. ``None`` = the clause said nothing
    about this field (so a broader clause may supply it) — distinct
    from an explicit 0, which OVERRIDES a broader clause (the way a
    sub-target is exempted: ``storage:error=0.3;storage.write:error=0``
    faults reads only)."""

    error: Optional[float] = None        # P(raise InjectedFault)
    partition: Optional[float] = None    # P(raise ConnectionError)
    latency_ms: Optional[float] = None
    latency_rate: Optional[float] = None  # P(apply latency); default 1
    corrupt: Optional[float] = None      # P(NaN-corrupt opted-in data)

    def merged_over(self, other: "FaultRule") -> "FaultRule":
        """This rule layered over a less specific one: specific wins
        per field where it says ANYTHING (including an explicit 0)."""
        return FaultRule(*(
            s if s is not None else o
            for s, o in zip(
                (self.error, self.partition, self.latency_ms,
                 self.latency_rate, self.corrupt),
                (other.error, other.partition, other.latency_ms,
                 other.latency_rate, other.corrupt))))


@dataclass(frozen=True)
class FaultSpec:
    rules: Dict[str, FaultRule] = field(default_factory=dict)
    seed: Optional[int] = None

    @staticmethod
    def parse(spec: str) -> "FaultSpec":
        rules: Dict[str, FaultRule] = {}
        seed = None
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if ":" not in clause:
                raise ValueError(
                    f"bad fault clause {clause!r}: want target:key=value")
            target, _, kvs = clause.partition(":")
            target = target.strip()
            kw: Dict[str, float] = {}
            for item in kvs.split(","):
                item = item.strip()
                if not item:
                    continue
                if "=" not in item:
                    raise ValueError(
                        f"bad fault setting {item!r} in {clause!r}")
                k, _, v = item.partition("=")
                k = k.strip()
                try:
                    val = float(v)
                except ValueError:
                    raise ValueError(
                        f"fault setting {k}={v!r} is not a number")
                if k == "seed":
                    if seed is None:
                        seed = int(val)
                    continue
                if k == "nan":   # operator-friendly alias
                    k = "corrupt"
                if k not in ("error", "partition", "latency_ms",
                             "latency_rate", "corrupt"):
                    raise ValueError(f"unknown fault key {k!r}")
                kw[k] = val
            for p in ("error", "partition", "latency_rate", "corrupt"):
                if p in kw and not 0.0 <= kw[p] <= 1.0:
                    raise ValueError(f"{p} must be in [0, 1]")
            rules[target] = FaultRule(**kw)
        return FaultSpec(rules=rules, seed=seed)

    def rule_for(self, target: str) -> Optional[FaultRule]:
        """Most-specific match layered over broader ones: for target
        ``storage.write``, a ``storage.write`` clause wins per field
        over a ``storage`` clause."""
        matched = None
        # broadest first so later (more specific) layers override
        parts = target.split(".")
        for i in range(1, len(parts) + 1):
            prefix = ".".join(parts[:i])
            rule = self.rules.get(prefix)
            if rule is not None:
                matched = rule if matched is None \
                    else rule.merged_over(matched)
        return matched


class FaultInjector:
    """Seeded decision engine. One shared RNG under a lock: decisions
    are deterministic in call order for a given (spec, seed) — the
    chaos suite serializes its faulted ops, so runs reproduce."""

    def __init__(self, spec: FaultSpec, seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 registry=None):
        self.spec = spec
        self.seed = seed if seed is not None else (
            spec.seed if spec.seed is not None else 0)
        self.rng = random.Random(self.seed)
        self.sleep = sleep
        self._lock = threading.Lock()
        if registry is None:
            from predictionio_tpu.obs import get_registry
            registry = get_registry()
        self._c_injected = registry.counter(
            "pio_faults_injected_total",
            "Chaos-harness injections by target and kind",
            labelnames=("target", "kind"))

    def before(self, target: str):
        """Consult the spec at an operation's entry: maybe inject
        latency, then maybe raise. Call sites place this BEFORE the
        real work so an injected error never half-applies the op."""
        rule = self.spec.rule_for(target)
        if rule is None:
            return
        error = rule.error or 0.0
        partition = rule.partition or 0.0
        latency_ms = rule.latency_ms or 0.0
        latency_rate = 1.0 if rule.latency_rate is None \
            else rule.latency_rate
        with self._lock:
            r_lat = self.rng.random() if latency_ms > 0 else 1.0
            r_err = self.rng.random() if error > 0 else 1.0
            r_part = self.rng.random() if partition > 0 else 1.0
        if latency_ms > 0 and r_lat < latency_rate:
            self._c_injected.labels(target=target, kind="latency").inc()
            self.sleep(latency_ms / 1000.0)
        if partition > 0 and r_part < partition:
            self._c_injected.labels(target=target, kind="partition").inc()
            raise ConnectionError(
                f"injected network partition on {target}")
        if error > 0 and r_err < error:
            self._c_injected.labels(target=target, kind="error").inc()
            raise InjectedFault(f"injected fault on {target}")

    def corrupt_array(self, target: str, arr):
        """Maybe NaN-corrupt a float numpy array at an opted-in site
        (``fold.ratings``, ``fold.factors``). Returns
        ``(array, injected)`` — the original object untouched when the
        seeded decision says no. The whole array goes NaN, which is the
        realistic shape of an ALS blow-up: one non-finite row poisons
        the shared Gram and the next sweep spreads it to every solve."""
        import numpy as _np
        rule = self.spec.rule_for(target)
        p = (rule.corrupt or 0.0) if rule is not None else 0.0
        if p <= 0.0:
            return arr, False
        with self._lock:
            r = self.rng.random()
        if r >= p:
            return arr, False
        self._c_injected.labels(target=target, kind="corrupt").inc()
        logger.warning("chaos: NaN-corrupting %s", target)
        return _np.full_like(_np.asarray(arr, dtype=_np.float32),
                             _np.nan), True

    def wrap_callable(self, target: str, fn: Callable) -> Callable:
        """Chaos-wrap any hop (an HTTP request function, a publish):
        the injector consults ``target`` before each call."""
        def wrapped(*args, **kwargs):
            self.before(target)
            return fn(*args, **kwargs)
        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped


class FaultyEvents(base.Events):
    """An ``Events`` DAO with chaos injection at every operation entry.
    Write ops consult ``storage.write``, read ops ``storage.read`` —
    the granularity the spill/replay and breaker-gated-tail paths
    degrade on."""

    def __init__(self, inner: base.Events, injector: FaultInjector):
        self.inner = inner
        self.injector = injector

    # -- writes -------------------------------------------------------------
    def insert(self, event, app_id, channel_id=None):
        self.injector.before("storage.write")
        return self.inner.insert(event, app_id, channel_id)

    def insert_batch(self, events, app_id, channel_id=None):
        self.injector.before("storage.write")
        return self.inner.insert_batch(events, app_id, channel_id)

    def insert_columnar(self, batch, app_id, channel_id=None):
        # explicit forward: base.Events has a materialize-and-batch
        # default, so __getattr__ would bypass the backend's fast path
        self.injector.before("storage.write")
        return self.inner.insert_columnar(batch, app_id, channel_id)

    def delete(self, event_id, app_id, channel_id=None):
        self.injector.before("storage.write")
        return self.inner.delete(event_id, app_id, channel_id)

    # -- reads --------------------------------------------------------------
    def get(self, event_id, app_id, channel_id=None):
        self.injector.before("storage.read")
        return self.inner.get(event_id, app_id, channel_id)

    def find(self, app_id, channel_id=None, **kw):
        self.injector.before("storage.read")
        return self.inner.find(app_id, channel_id=channel_id, **kw)

    def find_columnar(self, app_id, channel_id=None, **kw):
        self.injector.before("storage.read")
        return self.inner.find_columnar(app_id, channel_id=channel_id,
                                        **kw)

    def find_columnar_by_entities(self, app_id, channel_id=None, **kw):
        # explicit forward (not __getattr__): base.Events defines a
        # fallback impl, so attribute lookup would otherwise run the
        # un-faulted full-scan default instead of the backend's pushdown
        self.injector.before("storage.read")
        return self.inner.find_columnar_by_entities(
            app_id, channel_id=channel_id, **kw)

    def aggregate_properties(self, app_id, channel_id=None, **kw):
        self.injector.before("storage.read")
        return self.inner.aggregate_properties(app_id,
                                               channel_id=channel_id, **kw)

    # -- lifecycle / passthrough -------------------------------------------
    def init(self, app_id, channel_id=None):
        return self.inner.init(app_id, channel_id)

    def remove(self, app_id, channel_id=None):
        return self.inner.remove(app_id, channel_id)

    def close(self):
        return self.inner.close()

    def __getattr__(self, name):
        # backend-specific extras (nativelog's snapshot_files, ...)
        # pass through un-faulted; only the Events CRUD surface above
        # is chaos-gated
        return getattr(self.inner, name)


_ENV_INJECTOR: Optional[FaultInjector] = None
_ENV_LOCK = threading.Lock()


def injector_from_env() -> Optional[FaultInjector]:
    """The process-wide injector for ``PIO_FAULTS``, or None when the
    env is unset/empty. One injector per process so the seeded decision
    stream is shared by every wrapped surface."""
    global _ENV_INJECTOR
    spec_s = os.environ.get(ENV_VAR, "").strip()
    if not spec_s:
        return None
    with _ENV_LOCK:
        if _ENV_INJECTOR is None or _ENV_INJECTOR._spec_string != spec_s:
            spec = FaultSpec.parse(spec_s)
            inj = FaultInjector(spec)
            inj._spec_string = spec_s
            _ENV_INJECTOR = inj
            logger.warning("chaos harness ACTIVE: %s=%s (seed=%d)",
                           ENV_VAR, spec_s, inj.seed)
        return _ENV_INJECTOR


def reset_env_injector():
    """Forget the cached env injector (tests toggling PIO_FAULTS)."""
    global _ENV_INJECTOR
    with _ENV_LOCK:
        _ENV_INJECTOR = None


def maybe_corrupt_array(target: str, arr):
    """Module-level corruption hook for the fold path: consults the
    process-wide ``PIO_FAULTS`` injector; identity when chaos is off or
    the target has no ``corrupt=`` clause. Returns ``(array, bool)``."""
    inj = injector_from_env()
    if inj is None:
        return arr, False
    return inj.corrupt_array(target, arr)


def maybe_wrap_events(events: base.Events) -> base.Events:
    """Chaos-wrap an events DAO when ``PIO_FAULTS`` names any
    ``storage*`` target; identity otherwise. The storage registry calls
    this on every events object it hands out."""
    inj = injector_from_env()
    if inj is None:
        return events
    if not any(t == "storage" or t.startswith("storage.")
               for t in inj.spec.rules):
        return events
    if isinstance(events, FaultyEvents):
        return events
    return FaultyEvents(events, inj)
