"""Retry policies and circuit breakers — the fault-tolerance substrate.

At production scale transient infra failure is the steady state, not the
exception (the reference rode HBase/ZooKeeper client retries for this;
ALX and MLlib papers make the same point for TPU/cluster-scale training).
Every network or storage hop in the stack composes the same two
primitives from here:

- ``RetryPolicy`` — bounded retries with exponential backoff and FULL
  jitter (each delay is uniform in [0, min(cap, base*2^attempt)]; the
  AWS-architecture result that full jitter de-synchronizes retry storms
  better than equal/decorrelated jitter), under an optional total
  **deadline budget** so a caller-facing operation never retries past
  its own SLO. Server-provided ``Retry-After`` hints (the shed path's
  503s carry one) override the computed delay, clamped to the budget.

- ``CircuitBreaker`` — per-backend closed -> open -> half-open gate.
  ``failure_threshold`` consecutive failures open the circuit; while
  open every ``allow()`` fails fast with ``CircuitOpenError`` (callers
  degrade: the event server spills to the WAL, the scheduler skips its
  tail read) instead of stacking threads on a dead dependency. After
  ``reset_timeout_s`` ONE probe call is admitted (half-open); its
  success closes the circuit, its failure re-opens with the timeout
  doubled up to ``max_reset_timeout_s``.

Both are observable through the PR 2 metrics registry:
``pio_breaker_state{breaker=...}`` (0 closed / 1 open / 2 half-open)
and ``pio_breaker_transitions_total{breaker=...,to=...}``.

Clocks and sleeps are injectable so the chaos/regression tests run in
virtual time.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple, Type

logger = logging.getLogger(__name__)

# breaker state encoding for the state gauge
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

#: THE transient (outage-class) error set: what retries retry, what the
#: ingest path spills on, and what the replayer refuses to quarantine.
#: One definition so the spill/replay loss-and-dedup contract cannot
#: silently diverge between producers and consumers.
TRANSIENT_ERRORS = (IOError, OSError, ConnectionError, TimeoutError)


class RetryBudgetExceeded(IOError):
    """Retries exhausted (attempt cap or deadline budget). Carries the
    last underlying error as ``__cause__``."""


class TransientHTTPError(IOError):
    """A retryable HTTP verdict — the routing layer's bridge between
    status codes and :data:`TRANSIENT_ERRORS`. The fleet tenant router
    (tenancy/controller.py) raises it for responses that mean "the
    placement you routed by is stale or the host is momentarily
    unhappy" (404 unknown-tenant mid-failover, 409 generation fence,
    503 shed): an ``IOError`` subclass, so a stock ``RetryPolicy``
    retries it — after the router has refreshed its routes — and the
    client sees slow, not 5xx. A 400 is NOT transient and must not be
    mapped here."""

    def __init__(self, message: str, status: int = 503,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.http_status = int(status)
        if retry_after_s is not None:
            self.retry_after_s = float(retry_after_s)


class CircuitOpenError(IOError):
    """Fail-fast: the breaker guarding this backend is open. Maps to 503
    on HTTP surfaces; ``retry_after_s`` tells clients when the next
    half-open probe will be admitted."""

    http_status = 503

    def __init__(self, name: str, retry_after_s: float):
        super().__init__(
            f"circuit breaker {name!r} is open; retry in "
            f"{retry_after_s:.1f}s")
        self.breaker = name
        self.retry_after_s = retry_after_s


def retry_after_hint(exc: BaseException) -> Optional[float]:
    """A server-suggested delay carried by an exception (the shed path's
    503 + Retry-After, a breaker's probe deadline), if any."""
    v = getattr(exc, "retry_after_s", None)
    try:
        return float(v) if v is not None else None
    except (TypeError, ValueError):
        return None


@dataclass(frozen=True)
class RetryPolicy:
    """Composable retry schedule: exponential backoff + full jitter
    under a deadline budget.

    ``deadline_s`` bounds the WHOLE operation (attempts + sleeps) from
    the first ``call``; a computed delay that would overshoot it is
    clamped, and when no attempt can complete inside the budget the
    last error is raised wrapped in ``RetryBudgetExceeded``.
    """

    max_attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 5.0
    deadline_s: Optional[float] = None
    retry_on: Tuple[Type[BaseException], ...] = TRANSIENT_ERRORS
    # injectable for virtual-time tests
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)
    clock: Callable[[], float] = field(default=time.monotonic, repr=False)
    rng: random.Random = field(default_factory=random.Random, repr=False)

    def delay_for(self, attempt: int) -> float:
        """Full-jitter delay before retry number ``attempt`` (1-based)."""
        cap = min(self.max_delay_s,
                  self.base_delay_s * (2 ** max(attempt - 1, 0)))
        return self.rng.uniform(0.0, cap)

    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` under this policy. Exceptions not in ``retry_on``
        propagate immediately (a 400 is not transient)."""
        t0 = self.clock()
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                last = e
                if attempt >= self.max_attempts:
                    break
                delay = self.delay_for(attempt)
                hint = retry_after_hint(e)
                if hint is not None:
                    # clamp to [0, max_delay_s]: a server-suggested wait
                    # (or an open breaker's probe deadline) must not
                    # park this caller past its own backoff ceiling,
                    # and a buggy negative value must not hit sleep()
                    delay = max(0.0, min(hint, self.max_delay_s))
                if self.deadline_s is not None:
                    remaining = self.deadline_s - (self.clock() - t0)
                    if remaining <= delay:
                        # no room for the sleep AND another attempt:
                        # the budget is the caller's SLO — stop here
                        break
                logger.debug("retry %d/%d after %.3fs: %s", attempt,
                             self.max_attempts, delay, e)
                self.sleep(delay)
        raise RetryBudgetExceeded(
            f"gave up after {self.max_attempts} attempt(s): {last}"
        ) from last


class CircuitBreaker:
    """Per-backend closed -> open -> half-open breaker.

    Usage (both equivalent)::

        br.call(store.insert, event, app_id)

        with br.guard():
            store.insert(event, app_id)

    ``allow()`` raises ``CircuitOpenError`` while open; callers that
    degrade rather than fail (spill, skip-tick) catch it. State changes
    are pushed to the process metrics registry at transition time, so
    ``/metrics`` shows each breaker's live state and its transition
    history without the breaker owning a scrape surface.
    """

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_timeout_s: float = 10.0,
                 max_reset_timeout_s: float = 120.0,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.name = name
        self.failure_threshold = failure_threshold
        self.base_reset_timeout_s = reset_timeout_s
        self.max_reset_timeout_s = max_reset_timeout_s
        self.clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._reset_timeout_s = reset_timeout_s
        self._probe_inflight = False
        if registry is None:
            from predictionio_tpu.obs import get_registry
            registry = get_registry()
        self._g_state = registry.gauge(
            "pio_breaker_state",
            "Circuit-breaker state (0 closed, 1 open, 2 half-open)",
            labelnames=("breaker",)).labels(breaker=name)
        self._c_transitions = registry.counter(
            "pio_breaker_transitions_total",
            "Circuit-breaker state transitions",
            labelnames=("breaker", "to"))
        self._c_fast_fail = registry.counter(
            "pio_breaker_fast_failures_total",
            "Calls rejected while a breaker was open",
            labelnames=("breaker",)).labels(breaker=name)
        self._g_state.set(_STATE_CODE[CLOSED])

    # -- state machine ------------------------------------------------------
    def _transition(self, to: str):
        """Caller holds self._lock."""
        if to == self._state:
            return
        came_from = self._state
        self._state = to
        self._g_state.set(_STATE_CODE[to])
        self._c_transitions.labels(breaker=self.name, to=to).inc()
        logger.info("breaker %s -> %s", self.name, to)
        # diagnostics plane (ISSUE 6): every transition is a flight
        # record; an OPEN transition is an incident (the dependency is
        # down and callers are now degrading). Both calls are
        # non-blocking by contract — safe under self._lock.
        try:
            from predictionio_tpu.obs.flight import FLIGHT
            FLIGHT.record("breaker", breaker=self.name, to=to,
                          from_=came_from,
                          consecutiveFailures=self._consecutive_failures)
            if to == OPEN and came_from == CLOSED:
                from predictionio_tpu.obs.incidents import INCIDENTS
                INCIDENTS.capture(
                    "breaker_open",
                    f"breaker {self.name!r} opened after "
                    f"{self._consecutive_failures} consecutive failures",
                    context={"breaker": self.name,
                             "failures": self._consecutive_failures,
                             "resetTimeoutS": self._reset_timeout_s})
        except Exception:   # diagnosis must never worsen the fault
            logger.debug("flight/incident hook failed", exc_info=True)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self):
        """Caller holds self._lock: open -> half-open once the probe
        window arrives."""
        if (self._state == OPEN
                and self.clock() - self._opened_at >= self._reset_timeout_s):
            self._transition(HALF_OPEN)
            self._probe_inflight = False

    def allow(self):
        """Admission check: raises ``CircuitOpenError`` when the call
        must fail fast. In half-open, exactly one probe is admitted at a
        time; concurrent callers fail fast until it reports."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return
            if self._state == HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return
            retry_in = (self._reset_timeout_s
                        - (self.clock() - self._opened_at))
            self._c_fast_fail.inc()
            raise CircuitOpenError(self.name, max(retry_in, 0.0))

    def record_success(self):
        with self._lock:
            self._consecutive_failures = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._reset_timeout_s = self.base_reset_timeout_s
                self._transition(CLOSED)

    def record_failure(self):
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                # failed probe: re-open with the timeout doubled so a
                # hard-down backend is probed ever more gently
                self._probe_inflight = False
                self._reset_timeout_s = min(self._reset_timeout_s * 2,
                                            self.max_reset_timeout_s)
                self._opened_at = self.clock()
                self._transition(OPEN)
            elif (self._state == CLOSED and
                  self._consecutive_failures >= self.failure_threshold):
                self._opened_at = self.clock()
                self._transition(OPEN)

    # -- call surfaces ------------------------------------------------------
    def guard(self):
        """Context manager: admission on enter, success/failure recorded
        on exit. ``CircuitOpenError`` from the admission is NOT counted
        as a backend failure."""
        return _BreakerGuard(self)

    def call(self, fn: Callable, *args, **kwargs):
        self.allow()
        try:
            result = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result


class _BreakerGuard:
    def __init__(self, breaker: CircuitBreaker):
        self.breaker = breaker

    def __enter__(self):
        self.breaker.allow()
        return self.breaker

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.breaker.record_success()
        else:
            self.breaker.record_failure()
        return False
