"""Fault-tolerance layer: retries, circuit breakers, durable ingest
spill, and seeded chaos injection (ISSUE 3 tentpole).

Transient infra failure is the steady state at production scale; this
package is the shared substrate every layer degrades through instead of
crashing:

- ``policy`` — ``RetryPolicy`` (exponential backoff + full jitter under
  a deadline budget) and ``CircuitBreaker`` (closed/open/half-open per
  backend, observable via the metrics registry).
- ``spill`` — ``SpillWAL`` + ``SpillReplayer``: the event server's
  never-lose-an-accepted-event guarantee when the primary store is down.
- ``faults`` — ``PIO_FAULTS`` seeded chaos harness wrapping storage
  backends and HTTP hops; drives the ``-m chaos`` test suite.
"""

from predictionio_tpu.resilience.policy import (  # noqa: F401
    TRANSIENT_ERRORS, CircuitBreaker, CircuitOpenError,
    RetryBudgetExceeded, RetryPolicy, TransientHTTPError,
    retry_after_hint)
from predictionio_tpu.resilience.spill import (  # noqa: F401
    SpillReplayer, SpillWAL)
from predictionio_tpu.resilience.faults import (  # noqa: F401
    FaultInjector, FaultSpec, FaultyEvents, InjectedFault,
    injector_from_env, maybe_corrupt_array, maybe_wrap_events,
    reset_env_injector)
