"""Sharded-array checkpointing for mesh-placed models.

The persistence mode 2 ("manual" / PersistentModel) backend for P-placement
models: factor tables that live model-sharded on the mesh are saved through
orbax/tensorstore — each host writes its own shards, restore re-shards to
whatever mesh the deploy process has — instead of being gathered into a
pickle. This replaces the reference's "model is a lazy RDD, persist to
HDFS" pattern (controller/PersistentModel.scala:64 + HDFSModels role) with
the TPU-native equivalent (SURVEY.md §5 checkpoint/resume: the
orbax-style sharded-checkpoint hook).

Falls back to plain npz when orbax is unavailable (single-host only).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np


def checkpoint_dir(instance_id: str, base: Optional[str] = None) -> str:
    base = base or os.path.join(
        os.environ.get("PIO_FS_BASEDIR",
                       os.path.expanduser("~/.pio_store")),
        "sharded_models")
    return os.path.join(base, instance_id)


def save_sharded(path: str, arrays: Dict[str, Any]) -> bool:
    """Save a flat dict of (possibly sharded) jax arrays. Returns True on
    success."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(ocp.test_utils.erase_and_create_empty(path)
                   if os.path.exists(path) else path,
                   {k: v for k, v in arrays.items()})
        ckptr.wait_until_finished()
        return True
    except Exception:
        # single-host fallback: host-gather + npz. Crash-atomic: the
        # blob is written to a temp file in the same directory and
        # os.replace()d into place, so a crash mid-save leaves either
        # the previous complete checkpoint or none — never a torn
        # arrays.npz that restore_sharded half-loads.
        import jax
        if jax.process_count() > 1:
            raise
        host = {k: np.asarray(v) for k, v in arrays.items()}
        os.makedirs(path, exist_ok=True)
        final = os.path.join(path, "arrays.npz")
        tmp = os.path.join(path, f".arrays.npz.tmp.{os.getpid()}")
        try:
            with open(tmp, "wb") as f:
                np.savez(f, **host)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        return True


def restore_sharded(path: str,
                    shardings: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """Restore a dict of arrays; with `shardings` given, arrays come back
    as jax.Arrays with those shardings (orbax re-shards on read), else as
    host numpy."""
    npz = os.path.join(path, "arrays.npz")
    if os.path.exists(npz):
        with np.load(npz) as z:
            host = {k: z[k] for k in z.files}
    else:
        import jax
        import orbax.checkpoint as ocp
        ckptr = ocp.StandardCheckpointer()
        if shardings:
            restored = ckptr.restore(
                path,
                ocp.args.StandardRestore({
                    k: jax.ShapeDtypeStruct(
                        s["shape"], s["dtype"], sharding=s["sharding"])
                    for k, s in shardings.items()}))
            return dict(restored)
        host = {k: np.asarray(v)
                for k, v in dict(ckptr.restore(path)).items()}
    if shardings:
        import jax
        return {k: jax.device_put(host[k], shardings[k]["sharding"])
                for k in host}
    return host
