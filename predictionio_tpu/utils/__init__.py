"""Shared utilities."""
