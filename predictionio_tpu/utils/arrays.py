"""Host <-> device pytree conversion helpers.

The serialization analog of the reference's Kryo step
(reference: core/src/main/scala/io/prediction/workflow/CoreWorkflow.scala:74-79):
before pickling a trained model, every jax.Array leaf is materialized to host
numpy (gathering sharded arrays if needed); after unpickling, models are
plain numpy until an algorithm's predict path puts them back on device.
"""

from __future__ import annotations

from typing import Any

import numpy as np


def _is_jax_array(x) -> bool:
    try:
        import jax
        return isinstance(x, jax.Array)
    except ImportError:
        return False


def to_host(obj: Any) -> Any:
    """Recursively convert jax.Array leaves to numpy. Handles dataclasses,
    dicts, lists, tuples (incl. namedtuples), and leaves everything else."""
    if _is_jax_array(obj):
        host = np.asarray(obj)
        # device->host transfer accounting (obs.jaxmon): model gathers
        # are the big D2H movers on a tunneled chip
        from predictionio_tpu.obs import jaxmon
        jaxmon.record_d2h(host.nbytes)
        return host
    if isinstance(obj, dict):
        return {k: to_host(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        if hasattr(obj, "_fields"):  # namedtuple
            return type(obj)(*(to_host(v) for v in obj))
        return tuple(to_host(v) for v in obj)
    if isinstance(obj, list):
        return [to_host(v) for v in obj]
    import dataclasses
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return dataclasses.replace(obj, **{
            f.name: to_host(getattr(obj, f.name))
            for f in dataclasses.fields(obj)})
    return obj
