"""Host-array -> device-array cache.

Serving-path fix for SURVEY hard part #4 (serve-time latency from HBM):
model factor tables live in host numpy after deserialization; without a
cache every jitted predict call would re-transfer them host->device (hundreds
of ms for an ML-20M-sized table through a remote-chip tunnel). `cached_put`
uploads once per (array identity, sharding) and evicts when the host array
is garbage-collected.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Dict, Tuple

_lock = threading.Lock()
_cache: Dict[Tuple[int, Any], Tuple[Any, Any]] = {}


def _record_upload(arr):
    """Host->device transfer accounting (obs.jaxmon): only cache MISSES
    move bytes, so counting here — not per call — is what makes the
    counter mean actual link traffic."""
    from predictionio_tpu.obs import jaxmon
    jaxmon.record_h2d(int(getattr(arr, "nbytes", 0) or 0))


def cached_put(arr, sharding=None):
    """device_put with identity-based memoization. `arr` must be a
    weakref-able host array (numpy ndarray)."""
    import jax

    key = (id(arr), sharding)
    with _lock:
        entry = _cache.get(key)
        if entry is not None and entry[0]() is arr:
            return entry[1]
    dev = jax.device_put(arr, sharding) if sharding is not None \
        else jax.device_put(arr)
    _record_upload(arr)
    try:
        ref = weakref.ref(arr, lambda r, k=key: _cache.pop(k, None))
    except TypeError:
        return dev  # not weakref-able; skip caching
    with _lock:
        _cache[key] = (ref, dev)
    return dev


def cached_put_padded(arr, sharding, row_multiple: int):
    """cached_put for sharded uploads whose dim-0 must divide the axis
    size: pads rows with zeros before upload, memoized on
    (array identity, sharding, multiple) so per-query serve calls reuse
    the resident padded table."""
    import jax
    import numpy as np

    key = (id(arr), sharding, row_multiple)
    with _lock:
        entry = _cache.get(key)
        if entry is not None and entry[0]() is arr:
            return entry[1]
    n = arr.shape[0]
    target = ((n + row_multiple - 1) // row_multiple) * row_multiple
    padded = arr if target == n else np.concatenate(
        [arr, np.zeros((target - n,) + arr.shape[1:], arr.dtype)])
    dev = jax.device_put(padded, sharding)
    _record_upload(padded)
    try:
        ref = weakref.ref(arr, lambda r, k=key: _cache.pop(k, None))
    except TypeError:
        return dev
    with _lock:
        _cache[key] = (ref, dev)
    return dev


def cached_put_rows(arr, target_rows: int, sharding=None):
    """cached_put with dim-0 zero-padded to ``target_rows`` — the
    vocab-bucket upload of the compile plane (ISSUE 9): serving tables
    are uploaded at their shape-bucket size so vocabulary growth inside
    the bucket reuses both the resident device copy AND every compiled
    executable that reads it. Memoized on (array identity, rows,
    sharding); a smaller ``target_rows`` than the array has rows
    uploads unpadded (callers pass a covering bucket)."""
    import jax
    import numpy as np

    target = max(int(target_rows), arr.shape[0])
    key = (id(arr), target, sharding)
    with _lock:
        entry = _cache.get(key)
        if entry is not None and entry[0]() is arr:
            return entry[1]
    padded = arr if target == arr.shape[0] else np.concatenate(
        [arr, np.zeros((target - arr.shape[0],) + arr.shape[1:],
                       arr.dtype)])
    dev = jax.device_put(padded, sharding) if sharding is not None \
        else jax.device_put(padded)
    _record_upload(padded)
    try:
        ref = weakref.ref(arr, lambda r, k=key: _cache.pop(k, None))
    except TypeError:
        return dev
    with _lock:
        _cache[key] = (ref, dev)
    return dev


def cache_size() -> int:
    with _lock:
        return len(_cache)


def clear():
    with _lock:
        _cache.clear()
        _resident.clear()


# ---------------------------------------------------------------------------
# Versioned residency slots (fold ticks)
#
# A fold tick starts from the DEPLOYED factor tables and ends by publishing
# grown/updated tables; the next tick starts from exactly those. A named
# slot keeps the tick's final device arrays resident, keyed by the host
# arrays of the published model version — when the next tick presents the
# same host arrays, it reuses the device copies and uploads only the
# touched-row deltas (the ALX device-resident-shard discipline; ROADMAP
# open item). One live version per name; a slot dies with its key arrays
# (weakref callbacks), so an undeployed model never pins HBM.
# ---------------------------------------------------------------------------

_resident: Dict[str, Tuple[tuple, dict]] = {}   # name -> (key_refs, payload)


def get_resident(name: str, key_arrays) -> "dict | None":
    """The slot's payload iff it was stored against exactly these host
    arrays (identity match via weakrefs); None on any mismatch."""
    with _lock:
        entry = _resident.get(name)
    if entry is None:
        return None
    refs, payload = entry
    if len(refs) != len(key_arrays):
        return None
    if all(r() is a for r, a in zip(refs, key_arrays)):
        return payload
    return None


def put_resident(name: str, key_arrays, payload: dict):
    """Store device arrays for ``name``, valid while every array in
    ``key_arrays`` (the published model version's host tables) is alive
    and identical; replaces the slot's previous version."""
    # NOTE: no lock in the callback — gc may run it while this thread
    # already holds _lock (dict pop is GIL-atomic; same discipline as
    # cached_put's eviction callback)
    try:
        refs = tuple(weakref.ref(a, lambda r, k=name: _resident.pop(k, None))
                     for a in key_arrays)
    except TypeError:
        return  # not weakref-able: skip residency rather than leak HBM
    with _lock:
        _resident[name] = (refs, payload)


def drop_resident(name: str):
    with _lock:
        _resident.pop(name, None)


def resident_count() -> int:
    with _lock:
        return len(_resident)


def _payload_nbytes(obj) -> int:
    """Device bytes held by a residency payload: dicts/sequences are
    walked one level deep (fold payloads are flat dicts of device
    arrays / (array, gram) pairs); anything without ``nbytes`` counts
    zero."""
    if isinstance(obj, dict):
        return sum(_payload_nbytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_payload_nbytes(v) for v in obj)
    return int(getattr(obj, "nbytes", 0) or 0)


def resident_sizes() -> "Dict[str, int]":
    """name -> device bytes for every live residency slot — the sample
    source behind ``pio_hbm_table_bytes{table}`` (obs/costmon.py)."""
    with _lock:
        items = list(_resident.items())
    return {name: _payload_nbytes(payload)
            for name, (_refs, payload) in items}
