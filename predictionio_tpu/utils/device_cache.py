"""Host-array -> device-array cache.

Serving-path fix for SURVEY hard part #4 (serve-time latency from HBM):
model factor tables live in host numpy after deserialization; without a
cache every jitted predict call would re-transfer them host->device (hundreds
of ms for an ML-20M-sized table through a remote-chip tunnel). `cached_put`
uploads once per (array identity, sharding) and evicts when the host array
is garbage-collected.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import weakref
from typing import Any, Dict, Optional, Tuple

_lock = threading.Lock()
_cache: Dict[Tuple[int, Any], Tuple[Any, Any]] = {}

# ---------------------------------------------------------------------------
# Tenant attribution (ISSUE 15)
#
# A multi-tenant serving host packs many engines' factor tables into one
# device's HBM. Every upload that lands in this cache (and every residency
# slot) is tagged with the tenant active at put time, so the HBM budget
# manager (tenancy/budget.py) can read per-tenant resident bytes and evict
# one cold tenant's tables without touching another's. The scope is a
# contextvar: it follows the query/fold call stack across the serving
# lock, not threads created inside it.
# ---------------------------------------------------------------------------

# The scope itself moved to obs/tenantctx (ISSUE 17): the same
# contextvar now also drives device-time attribution, flight/trace/
# slowlog stamping and incident naming. These names stay re-exported —
# every PR 15 call site (and test) keeps working unchanged.
from predictionio_tpu.obs.tenantctx import (_tenant_var,   # noqa: F401
                                            current_tenant, tenant_scope)

# cache key -> tenant (entries whose upload ran under a tenant scope)
_tenant_keys: Dict[Any, str] = {}
# residency slot name -> tenant
_tenant_slots: Dict[str, str] = {}


def _tag_key(key):
    """Record the active tenant for a just-stored cache key. Caller
    holds ``_lock``."""
    t = _tenant_var.get()
    if t is not None:
        _tenant_keys[key] = t


def _evict_cache_key(key):
    """Weakref eviction callback body: lock-free (gc may run it while
    this thread already holds ``_lock``; dict pops are GIL-atomic)."""
    _cache.pop(key, None)
    _tenant_keys.pop(key, None)


def _sharding_key(sharding) -> Any:
    """Canonical cache-key component for a sharding: structurally
    distinct between no-sharding, replicated, and each sharded layout,
    and stable across equal-but-distinct NamedSharding objects. Keying
    on the raw object worked only as long as every caller passed the
    same layout for a given array — once replicated and model-sharded
    payloads of the SAME host array coexist (the sharded online
    plane), a layout must never be able to alias another's entry."""
    if sharding is None:
        return None
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if spec is None or mesh is None:
        return ("opaque", sharding)
    return ("named", id(mesh), tuple(spec))


class TableBudgetExceeded(RuntimeError):
    """A factor-table upload would exceed the enforced per-device
    table-byte budget (``PIO_TABLE_BUDGET_BYTES``)."""


def table_budget_bytes() -> Optional[int]:
    """The enforced per-device factor-table budget, or None (no
    enforcement — the default). The over-budget acceptance scenario
    sets this to prove a vocabulary genuinely does not fit one
    device: the replicated upload path refuses while the model-sharded
    path, paying only table/N per device, proceeds."""
    raw = os.environ.get("PIO_TABLE_BUDGET_BYTES", "").strip()
    if not raw:
        return None
    try:
        b = int(float(raw))
    except ValueError:
        return None
    return b if b > 0 else None


def _row_shards(sharding) -> int:
    """How many ways a sharding splits dim 0 (1 for None/replicated):
    the divisor turning table bytes into per-device bytes."""
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if not spec or mesh is None or not len(spec) or not spec[0]:
        return 1
    axes = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
    try:
        n = 1
        for ax in axes:
            n *= int(mesh.shape[ax])
        return max(n, 1)
    except Exception:
        return 1


def check_table_budget(per_device_bytes: int, table: str = "table"):
    """Raise :class:`TableBudgetExceeded` when ``per_device_bytes``
    breaks the enforced budget. No-op (zero cost beyond one getenv)
    when no budget is set."""
    budget = table_budget_bytes()
    if budget is not None and int(per_device_bytes) > budget:
        raise TableBudgetExceeded(
            f"{table}: {int(per_device_bytes)} bytes per device "
            f"exceeds the enforced table budget of {budget} bytes "
            f"(PIO_TABLE_BUDGET_BYTES); shard the table over the mesh "
            f"model axis (factor_sharding='model') or raise the budget")


def _record_upload(arr):
    """Host->device transfer accounting (obs.jaxmon): only cache MISSES
    move bytes, so counting here — not per call — is what makes the
    counter mean actual link traffic."""
    from predictionio_tpu.obs import jaxmon
    jaxmon.record_h2d(int(getattr(arr, "nbytes", 0) or 0))


def cached_put(arr, sharding=None):
    """device_put with identity-based memoization. `arr` must be a
    weakref-able host array (numpy ndarray)."""
    import jax

    key = (id(arr), _sharding_key(sharding))
    with _lock:
        entry = _cache.get(key)
        if entry is not None and entry[0]() is arr:
            return entry[1]
    dev = jax.device_put(arr, sharding) if sharding is not None \
        else jax.device_put(arr)
    _record_upload(arr)
    try:
        ref = weakref.ref(arr, lambda r, k=key: _evict_cache_key(k))
    except TypeError:
        return dev  # not weakref-able; skip caching
    with _lock:
        _cache[key] = (ref, dev)
        _tag_key(key)
    return dev


def cached_put_padded(arr, sharding, row_multiple: int):
    """cached_put for sharded uploads whose dim-0 must divide the axis
    size: pads rows with zeros before upload, memoized on
    (array identity, sharding, multiple) so per-query serve calls reuse
    the resident padded table."""
    import jax
    import numpy as np

    key = (id(arr), _sharding_key(sharding), "pad", row_multiple)
    with _lock:
        entry = _cache.get(key)
        if entry is not None and entry[0]() is arr:
            return entry[1]
    n = arr.shape[0]
    target = ((n + row_multiple - 1) // row_multiple) * row_multiple
    padded = arr if target == n else np.concatenate(
        [arr, np.zeros((target - n,) + arr.shape[1:], arr.dtype)])
    dev = jax.device_put(padded, sharding)
    _record_upload(padded)
    try:
        ref = weakref.ref(arr, lambda r, k=key: _evict_cache_key(k))
    except TypeError:
        return dev
    with _lock:
        _cache[key] = (ref, dev)
        _tag_key(key)
    return dev


def cached_put_rows(arr, target_rows: int, sharding=None):
    """cached_put with dim-0 zero-padded to ``target_rows`` — the
    vocab-bucket upload of the compile plane (ISSUE 9): serving tables
    are uploaded at their shape-bucket size so vocabulary growth inside
    the bucket reuses both the resident device copy AND every compiled
    executable that reads it. Memoized on (array identity, rows,
    sharding); a smaller ``target_rows`` than the array has rows
    uploads unpadded (callers pass a covering bucket)."""
    import jax
    import numpy as np

    target = max(int(target_rows), arr.shape[0])
    key = (id(arr), "rows", target, _sharding_key(sharding))
    with _lock:
        entry = _cache.get(key)
        if entry is not None and entry[0]() is arr:
            return entry[1]
    # the enforced per-device budget (over-budget acceptance): an
    # unsharded/replicated serving table costs its FULL padded bytes
    # on every device — exactly what a too-large vocabulary must not
    # be allowed to do silently
    row_bytes = int(np.prod(arr.shape[1:], dtype=np.int64)
                    * arr.dtype.itemsize) if arr.ndim > 1 \
        else arr.dtype.itemsize
    check_table_budget(target * row_bytes // _row_shards(sharding),
                       table="cached_put_rows")
    padded = arr if target == arr.shape[0] else np.concatenate(
        [arr, np.zeros((target - arr.shape[0],) + arr.shape[1:],
                       arr.dtype)])
    dev = jax.device_put(padded, sharding) if sharding is not None \
        else jax.device_put(padded)
    _record_upload(padded)
    try:
        ref = weakref.ref(arr, lambda r, k=key: _evict_cache_key(k))
    except TypeError:
        return dev
    with _lock:
        _cache[key] = (ref, dev)
        _tag_key(key)
    return dev


def cache_size() -> int:
    with _lock:
        return len(_cache)


def clear():
    with _lock:
        _cache.clear()
        _resident.clear()
        _tenant_keys.clear()
        _tenant_slots.clear()


# ---------------------------------------------------------------------------
# Versioned residency slots (fold ticks)
#
# A fold tick starts from the DEPLOYED factor tables and ends by publishing
# grown/updated tables; the next tick starts from exactly those. A named
# slot keeps the tick's final device arrays resident, keyed by the host
# arrays of the published model version — when the next tick presents the
# same host arrays, it reuses the device copies and uploads only the
# touched-row deltas (the ALX device-resident-shard discipline; ROADMAP
# open item). One live version per name; a slot dies with its key arrays
# (weakref callbacks), so an undeployed model never pins HBM.
# ---------------------------------------------------------------------------

_resident: Dict[str, Tuple[tuple, dict, Any]] = {}
# name -> (key_refs, payload, sharding_token)


def get_resident(name: str, key_arrays,
                 sharding: Any = None) -> "dict | None":
    """The slot's payload iff it was stored against exactly these host
    arrays (identity match via weakrefs) AND under the same sharding
    token; None on any mismatch. The token is what keeps a replicated
    payload from shadowing a sharded one (or vice versa) when both
    layouts of the same logical table coexist in one process — the
    latent aliasing the sharded online plane would otherwise hit on a
    ``factor_sharding`` config change."""
    with _lock:
        entry = _resident.get(name)
    if entry is None:
        return None
    refs, payload, token = entry
    if token != sharding or len(refs) != len(key_arrays):
        return None
    if all(r() is a for r, a in zip(refs, key_arrays)):
        return payload
    return None


def put_resident(name: str, key_arrays, payload: dict,
                 sharding: Any = None):
    """Store device arrays for ``name``, valid while every array in
    ``key_arrays`` (the published model version's host tables) is alive
    and identical; replaces the slot's previous version. ``sharding``
    is the layout token (e.g. ``"replicated"`` / ``"model:4"``) the
    matching :func:`get_resident` must present."""
    # NOTE: no lock in the callback — gc may run it while this thread
    # already holds _lock (dict pop is GIL-atomic; same discipline as
    # cached_put's eviction callback)
    try:
        refs = tuple(weakref.ref(a, lambda r, k=name: _evict_slot(k))
                     for a in key_arrays)
    except TypeError:
        return  # not weakref-able: skip residency rather than leak HBM
    with _lock:
        _resident[name] = (refs, payload, sharding)
        t = _tenant_var.get()
        if t is not None:
            _tenant_slots[name] = t


def _evict_slot(name: str):
    """Residency weakref callback body (lock-free, see put_resident)."""
    _resident.pop(name, None)
    _tenant_slots.pop(name, None)


def drop_resident(name: str):
    with _lock:
        _resident.pop(name, None)
        _tenant_slots.pop(name, None)


def resident_count() -> int:
    with _lock:
        return len(_resident)


def _device_nbytes(arr) -> int:
    """Bytes ONE device holds for ``arr``: a host/replicated array
    costs its full ``nbytes`` per device, while a dim-0-sharded device
    array costs only its largest per-device shard total — so the HBM
    gauge reads ~1/N per shard for model-sharded tables (the ALX
    scale-out claim, directly observable)."""
    shards = getattr(arr, "addressable_shards", None)
    if shards is None:
        return int(getattr(arr, "nbytes", 0) or 0)
    per: Dict[Any, int] = {}
    try:
        for sh in shards:
            d = sh.device
            per[d] = per.get(d, 0) + int(
                getattr(sh.data, "nbytes", 0) or 0)
    except Exception:
        return int(getattr(arr, "nbytes", 0) or 0)
    return max(per.values(), default=0)


def _payload_nbytes(obj) -> int:
    """Per-device bytes held by a residency payload: dicts/sequences
    are walked one level deep (fold payloads are flat dicts of device
    arrays / (array, gram) pairs); anything without ``nbytes`` counts
    zero."""
    if isinstance(obj, dict):
        return sum(_payload_nbytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_payload_nbytes(v) for v in obj)
    return _device_nbytes(obj)


def resident_sizes() -> "Dict[str, int]":
    """name -> per-device bytes for every live residency slot — the
    sample source behind ``pio_hbm_table_bytes{table}``
    (obs/costmon.py)."""
    with _lock:
        items = list(_resident.items())
    return {name: _payload_nbytes(payload)
            for name, (_refs, payload, _tok) in items}


def _payload_arrays(obj):
    """Flatten a residency payload into its array-like leaves (the
    same one-level walk as :func:`_payload_nbytes`)."""
    if isinstance(obj, dict):
        for v in obj.values():
            yield from _payload_arrays(v)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            yield from _payload_arrays(v)
    elif obj is not None and getattr(obj, "nbytes", 0):
        yield obj


def tenant_device_arrays() -> "Dict[str, list]":
    """tenant -> live device arrays this cache/residency layer holds
    for it (cache entries + residency payload leaves). The budget
    manager sums these identity-DEDUPED together with each slot's own
    handles — a fold tick attaches the same device arrays to its
    ShardedTables AND its residency payload, and counting them twice
    would double the gauge and thrash eviction."""
    with _lock:
        keys = [(k, t) for k, t in _tenant_keys.items() if k in _cache]
        devs = [(t, _cache[k][1]) for k, t in keys]
        slots = [(n, t) for n, t in _tenant_slots.items()
                 if n in _resident]
        payloads = [(t, _resident[n][1]) for n, t in slots]
    out: Dict[str, list] = {}
    for t, dev in devs:
        out.setdefault(t, []).append(dev)
    for t, payload in payloads:
        out.setdefault(t, []).extend(_payload_arrays(payload))
    return out


def tenant_sizes() -> "Dict[str, int]":
    """tenant -> per-device resident bytes across this cache AND the
    residency slots, measured from the live device arrays (not from
    put-time estimates), identity-deduped — the raw half of the
    sample source behind ``pio_engine_hbm_bytes{tenant}``
    (tenancy/budget.py adds each slot's ShardedTable handles). Tenants
    with nothing resident simply have no entry."""
    out: Dict[str, int] = {}
    for t, arrs in tenant_device_arrays().items():
        seen = set()
        total = 0
        for a in arrs:
            if id(a) in seen:
                continue
            seen.add(id(a))
            total += _device_nbytes(a)
        out[t] = total
    return out


def evict_tenant(tenant: str) -> Tuple[int, int]:
    """Drop every cache entry and residency slot attributed to
    ``tenant``; the device arrays are freed once no in-flight dispatch
    holds them (JAX arrays are refcounted — an enqueued window's
    closure keeps its inputs alive, so eviction never corrupts a
    dispatched computation; it only stops pinning HBM for the NEXT
    one). Returns (entries_dropped, per_device_bytes_freed). The host
    mirrors — the model objects' numpy tables — are untouched: the next
    hit re-uploads through the budget-checked ``cached_put*`` /
    ``ShardedTable.device`` paths."""
    tenant = str(tenant)
    with _lock:
        doomed_keys = [k for k, t in _tenant_keys.items() if t == tenant]
        doomed_slots = [n for n, t in _tenant_slots.items() if t == tenant]
        freed = 0
        dropped = 0
        seen = set()   # identity-dedup: a residency payload may hold
        #                the same device arrays a cache entry does
        for k in doomed_keys:
            entry = _cache.pop(k, None)
            _tenant_keys.pop(k, None)
            if entry is not None:
                dropped += 1
                if id(entry[1]) not in seen:
                    seen.add(id(entry[1]))
                    freed += _device_nbytes(entry[1])
        for n in doomed_slots:
            entry = _resident.pop(n, None)
            _tenant_slots.pop(n, None)
            if entry is not None:
                dropped += 1
                for a in _payload_arrays(entry[1]):
                    if id(a) not in seen:
                        seen.add(id(a))
                        freed += _device_nbytes(a)
    return dropped, freed
