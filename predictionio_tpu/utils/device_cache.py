"""Host-array -> device-array cache.

Serving-path fix for SURVEY hard part #4 (serve-time latency from HBM):
model factor tables live in host numpy after deserialization; without a
cache every jitted predict call would re-transfer them host->device (hundreds
of ms for an ML-20M-sized table through a remote-chip tunnel). `cached_put`
uploads once per (array identity, sharding) and evicts when the host array
is garbage-collected.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Dict, Tuple

_lock = threading.Lock()
_cache: Dict[Tuple[int, Any], Tuple[Any, Any]] = {}


def _record_upload(arr):
    """Host->device transfer accounting (obs.jaxmon): only cache MISSES
    move bytes, so counting here — not per call — is what makes the
    counter mean actual link traffic."""
    from predictionio_tpu.obs import jaxmon
    jaxmon.record_h2d(int(getattr(arr, "nbytes", 0) or 0))


def cached_put(arr, sharding=None):
    """device_put with identity-based memoization. `arr` must be a
    weakref-able host array (numpy ndarray)."""
    import jax

    key = (id(arr), sharding)
    with _lock:
        entry = _cache.get(key)
        if entry is not None and entry[0]() is arr:
            return entry[1]
    dev = jax.device_put(arr, sharding) if sharding is not None \
        else jax.device_put(arr)
    _record_upload(arr)
    try:
        ref = weakref.ref(arr, lambda r, k=key: _cache.pop(k, None))
    except TypeError:
        return dev  # not weakref-able; skip caching
    with _lock:
        _cache[key] = (ref, dev)
    return dev


def cached_put_padded(arr, sharding, row_multiple: int):
    """cached_put for sharded uploads whose dim-0 must divide the axis
    size: pads rows with zeros before upload, memoized on
    (array identity, sharding, multiple) so per-query serve calls reuse
    the resident padded table."""
    import jax
    import numpy as np

    key = (id(arr), sharding, row_multiple)
    with _lock:
        entry = _cache.get(key)
        if entry is not None and entry[0]() is arr:
            return entry[1]
    n = arr.shape[0]
    target = ((n + row_multiple - 1) // row_multiple) * row_multiple
    padded = arr if target == n else np.concatenate(
        [arr, np.zeros((target - n,) + arr.shape[1:], arr.dtype)])
    dev = jax.device_put(padded, sharding)
    _record_upload(padded)
    try:
        ref = weakref.ref(arr, lambda r, k=key: _cache.pop(k, None))
    except TypeError:
        return dev
    with _lock:
        _cache[key] = (ref, dev)
    return dev


def cache_size() -> int:
    with _lock:
        return len(_cache)


def clear():
    with _lock:
        _cache.clear()
