"""Host-array -> device-array cache.

Serving-path fix for SURVEY hard part #4 (serve-time latency from HBM):
model factor tables live in host numpy after deserialization; without a
cache every jitted predict call would re-transfer them host->device (hundreds
of ms for an ML-20M-sized table through a remote-chip tunnel). `cached_put`
uploads once per (array identity, sharding) and evicts when the host array
is garbage-collected.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Dict, Tuple

_lock = threading.Lock()
_cache: Dict[Tuple[int, Any], Tuple[Any, Any]] = {}


def cached_put(arr, sharding=None):
    """device_put with identity-based memoization. `arr` must be a
    weakref-able host array (numpy ndarray)."""
    import jax

    key = (id(arr), sharding)
    with _lock:
        entry = _cache.get(key)
        if entry is not None and entry[0]() is arr:
            return entry[1]
    dev = jax.device_put(arr, sharding) if sharding is not None \
        else jax.device_put(arr)
    try:
        ref = weakref.ref(arr, lambda r, k=key: _cache.pop(k, None))
    except TypeError:
        return dev  # not weakref-able; skip caching
    with _lock:
        _cache[key] = (ref, dev)
    return dev


def cache_size() -> int:
    with _lock:
        return len(_cache)


def clear():
    with _lock:
        _cache.clear()
