"""Tiny threaded HTTP server + router on the stdlib.

Plays the role of the reference's Spray/Akka HTTP layer (reference:
data/src/main/scala/io/prediction/data/api/EventServer.scala,
core/src/main/scala/io/prediction/workflow/CreateServer.scala) without
external dependencies: a ThreadingHTTPServer dispatching to route handlers.
Request-level concurrency comes from the thread pool; device work stays
serialized behind the algorithm's own jit calls (XLA queues per-device).
"""

from __future__ import annotations

import gzip
import json
import logging
import re
import threading
import urllib.parse
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


class Headers(dict):
    """Case-insensitive header mapping (RFC 9110 §5.1: field names are
    case-insensitive; a client sending ``authorization:`` must match a
    handler's ``.get("Authorization")``). Keys are stored lower-cased
    and every access path folds its probe key, so mutation and copying
    preserve the invariant."""

    def __init__(self, items=()):
        if hasattr(items, "items"):
            items = items.items()
        super().__init__((k.lower(), v) for k, v in items)

    def get(self, key, default=None):
        return super().get(key.lower(), default)

    def __getitem__(self, key):
        return super().__getitem__(key.lower())

    def __contains__(self, key):
        return super().__contains__(key.lower())

    def __setitem__(self, key, value):
        super().__setitem__(key.lower(), value)

    def __delitem__(self, key):
        super().__delitem__(key.lower())

    def pop(self, key, *default):
        return super().pop(key.lower(), *default)

    def setdefault(self, key, default=None):
        return super().setdefault(key.lower(), default)

    def update(self, items=(), **kw):
        if hasattr(items, "items"):
            items = items.items()
        for k, v in items:
            self[k] = v
        for k, v in kw.items():
            self[k] = v

    def copy(self):
        return Headers(self)


@dataclass
class Request:
    method: str
    path: str
    params: Dict[str, str]
    headers: Dict[str, str]
    body: bytes
    path_args: Tuple[str, ...] = ()

    def json(self) -> Any:
        if not self.body:
            return None
        return json.loads(self.body.decode("utf-8"))

    def form(self) -> Dict[str, str]:
        parsed = urllib.parse.parse_qs(self.body.decode("utf-8"),
                                       keep_blank_values=True)
        return {k: v[0] for k, v in parsed.items()}


@dataclass
class Response:
    status: int = 200
    body: Any = None           # dict/list -> JSON; str -> as-is
    content_type: str = "application/json; charset=UTF-8"
    # extra response headers (Retry-After on sheds, model-staleness on
    # degraded serving); None avoids a dict per ordinary response
    headers: Optional[Dict[str, str]] = None

    def payload(self) -> bytes:
        if self.body is None:
            return b""
        if isinstance(self.body, (bytes, bytearray)):
            return bytes(self.body)
        if isinstance(self.body, str):
            return self.body.encode("utf-8")
        return json.dumps(self.body).encode("utf-8")


Handler = Callable[[Request], Response]


def fetch_json(url: str, timeout: float = 3.0) -> Any:
    """GET a JSON endpoint, mapping any failure to {"error": str} —
    the polling pattern shared by `pio status --telemetry` and the
    dashboard's /telemetry view (an unreachable server is a row in the
    report, not an exception)."""
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())
    except Exception as e:
        return {"error": str(e)}


def fetch_text(url: str, timeout: float = 3.0) -> Optional[str]:
    """GET a text endpoint (a /metrics scrape); None on any failure or
    non-200 — the fleet federation treats that as a down member row,
    not an exception (obs/fleet.py)."""
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            if resp.status != 200:
                return None
            return resp.read().decode("utf-8", "replace")
    except Exception:
        return None


def _accepts_gzip(value: str) -> bool:
    """True when an Accept-Encoding value allows gzip — token match, not
    substring (``gzip;q=0`` is an explicit refusal)."""
    for part in value.split(","):
        bits = part.strip().split(";")
        if bits[0].strip().lower() != "gzip":
            continue
        for b in bits[1:]:
            b = b.strip().lower()
            if b.startswith("q="):
                try:
                    if float(b[2:]) == 0.0:
                        return False
                except ValueError:
                    pass
        return True
    return False


class Router:
    """Method+path-regex routing. Patterns use <name> wildcards that match
    one path segment and arrive as positional path_args."""

    def __init__(self):
        self.routes: List[Tuple[str, re.Pattern, Handler]] = []

    def add(self, method: str, pattern: str, handler: Handler):
        regex = re.compile(
            "^" + re.sub(r"<[^>]+>", r"([^/]+)", pattern) + "$")
        self.routes.append((method.upper(), regex, handler))

    def dispatch(self, req: Request) -> Response:
        matched_path = False
        for method, regex, handler in self.routes:
            m = regex.match(req.path)
            if m:
                matched_path = True
                if method == req.method:
                    req.path_args = m.groups()
                    return handler(req)
        if matched_path:
            return Response(405, {"message": "method not allowed"})
        return Response(404, {"message": "not found"})


class HttpServer:
    def __init__(self, router: Router, host: str = "0.0.0.0",
                 port: int = 8000):
        self.router = router
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # invoked with this server once the socket is bound (port
        # resolved) but BEFORE serve_forever — the only window where a
        # foreground server can publish its resolved port (the fleet
        # member registration, ISSUE 13). Must not raise.
        self.on_bound: Optional[Callable[["HttpServer"], None]] = None
        # latched by stop(): a stop that lands BEFORE the socket exists
        # (e.g. SIGTERM during the bind-retry window) must still win —
        # start() checks it after binding and tears down immediately
        # instead of serving as a zombie
        self._stop_requested = False
        # True once the current start() reached serving; lets stop()
        # tell "idempotent cleanup after a completed lifecycle" (no-op)
        # apart from "stop racing a bind in progress" (latch)
        self._has_served = False

    def _make_handler(self):
        router = self.router

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # one buffered write + TCP_NODELAY: without these, the
            # header/body write split interacts with Nagle + delayed ACK
            # for ~40-200 ms per response
            wbufsize = 1 << 16
            disable_nagle_algorithm = True

            def _handle(self):
                parsed = urllib.parse.urlsplit(self.path)
                # keep_blank_values: `targetEntityType=` (empty string)
                # is meaningful — the event API maps it to "target
                # absent" — and must not be silently dropped
                params = {k: v[0] for k, v in
                          urllib.parse.parse_qs(
                              parsed.query,
                              keep_blank_values=True).items()}
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                req = Request(method=self.command, path=parsed.path,
                              params=params,
                              headers=Headers(self.headers.items()),
                              body=body)
                try:
                    resp = router.dispatch(req)
                except ValueError as e:
                    resp = Response(400, {"message": str(e)})
                except KeyError as e:
                    # missing required field in a JSON body
                    resp = Response(400, {"message":
                                          f"missing field {e}"})
                except Exception as e:
                    # exceptions that know their HTTP status (e.g. mesh
                    # coordinator poisoned -> 503, a shed query, an
                    # open circuit breaker) pass it through; a
                    # retry_after_s attribute becomes the Retry-After
                    # header so well-behaved clients back off for the
                    # server-known recovery window
                    status = getattr(e, "http_status", None)
                    if status:
                        logger.error("handler error (%d): %s", status, e)
                        resp = Response(int(status), {"message": str(e)})
                        ra = getattr(e, "retry_after_s", None)
                        if ra is not None:
                            resp.headers = {
                                "Retry-After":
                                    str(max(1, int(float(ra) + 0.5)))}
                    else:
                        logger.exception("handler error")
                        resp = Response(500, {"message": str(e)})
                payload = resp.payload()
                self.send_response(resp.status)
                self.send_header("Content-Type", resp.content_type)
                for hk, hv in (resp.headers or {}).items():
                    self.send_header(hk, hv)
                # transparent gzip for clients that ask: bulk JSON (the
                # columnar training reads) compresses ~10x, which is the
                # difference on a thin link; tiny responses skip the
                # CPU cost. Header names are case-insensitive — use the
                # Message object, not the plain dict.
                accept = self.headers.get("Accept-Encoding") or ""
                if (_accepts_gzip(accept) and len(payload) >= 1024):
                    payload = gzip.compress(payload, compresslevel=1)
                    self.send_header("Content-Encoding", "gzip")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            do_GET = do_POST = do_DELETE = do_PUT = _handle

            def log_message(self, fmt, *args):
                logger.debug("%s %s", self.address_string(), fmt % args)

        return _Handler

    def start(self, background: bool = True, bind_retries: int = 3,
              retry_delay: float = 1.0):
        # bind retry x3 mirrors the reference MasterActor
        # (CreateServer.scala:363-373)
        import time as _time
        self._has_served = False   # new lifecycle attempt begins
        last_err = None
        for attempt in range(bind_retries):
            try:
                self._httpd = ThreadingHTTPServer((self.host, self.port),
                                                  self._make_handler())
                break
            except OSError as e:
                last_err = e
                logger.warning("bind %s:%d failed (%s), retry %d/%d",
                               self.host, self.port, e, attempt + 1,
                               bind_retries)
                _time.sleep(retry_delay)
        else:
            raise last_err
        self.port = self._httpd.server_address[1]  # resolve port 0
        if self._stop_requested:   # stop() raced the bind — honor it
            self._httpd.server_close()
            self._httpd = None
            self._stop_requested = False  # consumed; start() works again
            return
        self._has_served = True
        if self.on_bound is not None:
            try:
                self.on_bound(self)
            except Exception:
                logger.exception("on_bound hook failed")
        if background:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True)
            self._thread.start()
        else:
            self._httpd.serve_forever()
        return self

    def stop(self):
        if self._httpd is None and self._has_served:
            # idempotent cleanup after a completed lifecycle (a second
            # stop(), a try/finally sweep): nothing to do, and latching
            # here would make the NEXT start() bind-then-die
            return
        self._stop_requested = True
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            # the stop acted on a live server, so the latch is consumed:
            # an HttpServer is restartable (round-4 advisor); the latch
            # persists only when stop() fired before/at bind time, where
            # the pending start() must still honor it
            self._stop_requested = False
