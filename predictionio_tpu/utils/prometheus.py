"""Minimal Prometheus text-exposition rendering (no dependencies).

Beyond-parity observability: the reference exposes counters only as
JSON/HTML status pages (CreateServer.scala:418-420, Stats.scala:40-79);
modern deployments scrape. Both HTTP servers serve ``GET /metrics`` in
the v0.0.4 text format rendered here — since ISSUE 2 the sample lists
come from ``obs.metrics.MetricsRegistry.collect()``, never hand-built.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Optional, Sequence, Tuple, Union

# (labels, value) — or (name-suffix, labels, value) for histogram
# component samples (_bucket/_sum/_count ride under one family name),
# or (name-suffix, labels, value, exemplar) where exemplar is
# {"labels": {...}, "value": v, "ts": t} rendered as the OpenMetrics
# `# {trace_id="..."} v t` suffix (histogram _bucket lines only)
Sample = Union[Tuple[Optional[Mapping[str, str]], float],
               Tuple[str, Optional[Mapping[str, str]], float],
               Tuple[str, Optional[Mapping[str, str]], float, Mapping]]

# the exposition format version this module renders; callers use it as
# the HTTP Content-Type so header and body can never disagree
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# exemplar-bearing rendering (ISSUE 11): the classic 0.0.4 text parser
# rejects anything after the value that is not a timestamp, so exemplar
# suffixes are only emitted when the caller asked for the OpenMetrics
# exposition (Accept negotiation or ?exemplars=1) — served under this
# content type and terminated with `# EOF`
OPENMETRICS_CONTENT_TYPE = ("application/openmetrics-text; "
                            "version=1.0.0; charset=utf-8")


def _escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    # HELP lines escape backslash and newline only (no quote context)
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def render_metrics(metrics: Iterable[Tuple[str, str, str,
                                           Sequence[Sample]]],
                   exemplars: bool = False) -> str:
    """metrics: (name, type, help, samples); samples are
    (labels-or-None, value), (suffix, labels-or-None, value), or the
    4-tuple form carrying an exemplar. Returns the exposition text.

    ``exemplars=False`` (the default — every classic-format scrape)
    DROPS exemplar suffixes: the 0.0.4 parser rejects them and one
    suffix would fail the whole scrape. ``exemplars=True`` renders
    them on ``_bucket`` lines and terminates the body with the
    OpenMetrics ``# EOF`` marker; serve it under
    :data:`OPENMETRICS_CONTENT_TYPE`."""
    out = []
    for name, mtype, help_, samples in metrics:
        out.append(f"# HELP {name} {_escape_help(help_)}")
        out.append(f"# TYPE {name} {mtype}")
        for sample in samples:
            exemplar = None
            if len(sample) == 4:
                suffix, labels, value, exemplar = sample
            elif len(sample) == 3:
                suffix, labels, value = sample
            else:
                labels, value = sample
                suffix = ""
            lab = ""
            if labels:
                inner = ",".join(f'{k}="{_escape(v)}"'
                                 for k, v in labels.items())
                lab = "{" + inner + "}"
            line = f"{name}{suffix}{lab} {value}"
            if exemplars and exemplar and suffix == "_bucket":
                # OpenMetrics exemplar suffix: the same label-escaping
                # rules as sample labels, exemplar value, then its
                # unix timestamp. Deliberately restricted to _bucket
                # lines — exemplars on _sum/_count are not legal.
                ex_inner = ",".join(
                    f'{k}="{_escape(v)}"'
                    for k, v in (exemplar.get("labels") or {}).items())
                line += (" # {" + ex_inner + "} "
                         + f"{exemplar['value']}")
                ts = exemplar.get("ts")
                if ts is not None:
                    line += f" {round(float(ts), 3)}"
            out.append(line)
    tail = "\n# EOF\n" if exemplars else "\n"
    return "\n".join(out) + tail


def wants_exemplars(req) -> bool:
    """Shared /metrics switch: the exemplar-bearing exposition is
    STRICTLY ``?exemplars=1`` opt-in. Deliberately NOT Accept-header
    negotiated: stock Prometheus advertises openmetrics-text in every
    default Accept header, and this registry's counter families are
    registered with ``_total`` already in the family name — valid in
    the classic format, rejected by a strict OpenMetrics parser
    (which wants ``<family>_total`` samples under a suffix-free
    family) — so honoring the header would hand the default scraper a
    body it may refuse whole. Operators and tooling that want the
    trace-id exemplars ask for them explicitly."""
    params = getattr(req, "params", None) or {}
    return str(params.get("exemplars", "")).lower() in (
        "1", "true", "yes")
