"""Minimal Prometheus text-exposition rendering (no dependencies).

Beyond-parity observability: the reference exposes counters only as
JSON/HTML status pages (CreateServer.scala:418-420, Stats.scala:40-79);
modern deployments scrape. Both HTTP servers serve ``GET /metrics`` in
the v0.0.4 text format rendered here — since ISSUE 2 the sample lists
come from ``obs.metrics.MetricsRegistry.collect()``, never hand-built.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Optional, Sequence, Tuple, Union

# (labels, value) — or (name-suffix, labels, value) for histogram
# component samples (_bucket/_sum/_count ride under one family name)
Sample = Union[Tuple[Optional[Mapping[str, str]], float],
               Tuple[str, Optional[Mapping[str, str]], float]]

# the exposition format version this module renders; callers use it as
# the HTTP Content-Type so header and body can never disagree
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    # HELP lines escape backslash and newline only (no quote context)
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def render_metrics(metrics: Iterable[Tuple[str, str, str,
                                           Sequence[Sample]]]) -> str:
    """metrics: (name, type, help, samples); samples are
    (labels-or-None, value) or (suffix, labels-or-None, value).
    Returns the exposition text."""
    out = []
    for name, mtype, help_, samples in metrics:
        out.append(f"# HELP {name} {_escape_help(help_)}")
        out.append(f"# TYPE {name} {mtype}")
        for sample in samples:
            if len(sample) == 3:
                suffix, labels, value = sample
            else:
                labels, value = sample
                suffix = ""
            lab = ""
            if labels:
                inner = ",".join(f'{k}="{_escape(v)}"'
                                 for k, v in labels.items())
                lab = "{" + inner + "}"
            out.append(f"{name}{suffix}{lab} {value}")
    return "\n".join(out) + "\n"
