"""Minimal Prometheus text-exposition rendering (no dependencies).

Beyond-parity observability: the reference exposes counters only as
JSON/HTML status pages (CreateServer.scala:418-420, Stats.scala:40-79);
modern deployments scrape. Both HTTP servers serve ``GET /metrics`` in
the v0.0.4 text format rendered here.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Optional, Sequence, Tuple

Sample = Tuple[Optional[Mapping[str, str]], float]

# the exposition format version this module renders; callers use it as
# the HTTP Content-Type so header and body can never disagree
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def render_metrics(metrics: Iterable[Tuple[str, str, str,
                                           Sequence[Sample]]]) -> str:
    """metrics: (name, type, help, samples); samples are
    (labels-or-None, value). Returns the exposition text."""
    out = []
    for name, mtype, help_, samples in metrics:
        out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            lab = ""
            if labels:
                inner = ",".join(f'{k}="{_escape(v)}"'
                                 for k, v in sorted(labels.items()))
                lab = "{" + inner + "}"
            out.append(f"{name}{lab} {value}")
    return "\n".join(out) + "\n"
