"""Canary serving + post-swap watchdog for hot model swaps.

A version published by the fold loop has passed the numerical sentinels
and the pre-swap gates — but serving is the only oracle for serving
behavior. When canarying is enabled (``ServerConfig.canary_fraction``
> 0), ``EngineServer.swap_models`` stages the new model set as a
*candidate* instead of swapping it in: the incumbent keeps answering
``1 - fraction`` of traffic, the candidate answers the rest (responses
tagged ``X-PIO-Canary``), and this controller keeps per-arm outcome
stats (errors, non-finite scores, latency).

The watchdog decision runs opportunistically on the query path:

- any candidate response carrying non-finite scores beyond
  ``nan_tolerance`` rolls back immediately;
- once the candidate has ``min_requests`` samples, an error rate above
  ``max_error_ratio`` x the incumbent's (plus an absolute floor) rolls
  back;
- at the end of ``window_s`` with enough samples, a p50 latency above
  ``max_latency_ratio`` x the incumbent's rolls back, otherwise the
  candidate is promoted (and the server pins it last-known-good).

Rollback is in-memory and instant — the incumbent model set never left
the server — and counted in ``pio_guard_rollbacks_total{reason}``; the
registry-pinned last-known-good + ``pio rollback`` cover the durable
(restart/redeploy) path.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

INCUMBENT = "incumbent"
CANDIDATE = "candidate"


@dataclass(frozen=True)
class CanaryConfig:
    fraction: float = 0.0        # candidate traffic share; 0 disables
    window_s: float = 30.0       # watchdog decision window
    min_requests: int = 20       # candidate samples needed to judge
    max_error_ratio: float = 2.0  # vs incumbent error rate
    error_floor: float = 0.02    # absolute extra error rate tolerated
    max_latency_ratio: float = 3.0  # candidate p50 vs incumbent p50
    nan_tolerance: int = 0       # candidate responses with non-finite
    #                              scores tolerated before rollback


class _ArmStats:
    __slots__ = ("requests", "errors", "nonfinite", "latencies")

    def __init__(self):
        self.requests = 0
        self.errors = 0
        self.nonfinite = 0
        self.latencies = collections.deque(maxlen=512)

    def error_rate(self) -> float:
        return self.errors / self.requests if self.requests else 0.0

    def p50(self) -> Optional[float]:
        if not self.latencies:
            return None
        return float(np.median(self.latencies))

    def snapshot(self) -> dict:
        return {"requests": self.requests, "errors": self.errors,
                "nonFiniteScores": self.nonfinite,
                "p50LatencySec": self.p50()}


class CanaryController:
    """Thread-safe canary state machine for one engine server. All
    public methods take the internal lock only — callers may hold their
    own server lock around them, never the reverse."""

    def __init__(self, config: CanaryConfig, registry=None,
                 clock: Callable[[], float] = time.time):
        self.config = config
        self.clock = clock
        self._lock = threading.Lock()
        self._active = False
        self._candidate_models: Optional[List[Any]] = None
        self._candidate_version: Optional[str] = None
        self._candidate_events = 0
        self._started_at = 0.0
        self._seq = 0
        self._arms = {INCUMBENT: _ArmStats(), CANDIDATE: _ArmStats()}
        self.superseded = 0
        self.last_decision: Optional[dict] = None
        if registry is None:
            from predictionio_tpu.obs import get_registry
            registry = get_registry()
        self._c_requests = registry.counter(
            "pio_guard_canary_requests_total",
            "Queries served during canary windows, by arm",
            labelnames=("arm",))
        self._c_rollbacks = registry.counter(
            "pio_guard_rollbacks_total",
            "Automatic canary rollbacks by breach reason",
            labelnames=("reason",))
        self._c_promotions = registry.counter(
            "pio_guard_promotions_total",
            "Canary candidates promoted to full traffic")

    @property
    def enabled(self) -> bool:
        return self.config.fraction > 0.0

    @property
    def active(self) -> bool:
        with self._lock:
            return self._active

    # -- lifecycle ----------------------------------------------------------
    def stage(self, models: Sequence[Any], version: Optional[str],
              fold_in_events: int = 0) -> bool:
        """Begin (or replace) a canary for ``models``. Returns False when
        canarying is disabled — the caller should swap directly."""
        if not self.enabled:
            return False
        with self._lock:
            if self._active:
                # a newer publish supersedes an undecided candidate; the
                # incumbent stays authoritative either way
                self.superseded += 1
                logger.warning(
                    "canary candidate %s superseded before a decision",
                    self._candidate_version)
            self._active = True
            self._candidate_models = list(models)
            self._candidate_version = version
            self._candidate_events = int(fold_in_events)
            self._started_at = self.clock()
            self._seq = 0
            self._arms = {INCUMBENT: _ArmStats(), CANDIDATE: _ArmStats()}
        logger.info("canary staged: version %s at %.0f%% of traffic",
                    version, self.config.fraction * 100)
        return True

    def abandon(self, reason: str):
        """Discard an undecided candidate without a verdict (a full
        /reload replaced the pipeline underneath it)."""
        with self._lock:
            if not self._active:
                return
            self._active = False
            self._candidate_models = None
            self.superseded += 1
        logger.warning("canary abandoned: %s", reason)

    # -- query-path hooks ---------------------------------------------------
    def route(self) -> Optional[tuple]:
        """(models, version) when THIS request should serve from the
        candidate, else None. Deterministic Bresenham split: candidate
        requests are spread evenly through the stream at exactly
        ``fraction`` of traffic (no random sampling — a canary test
        reproduces, and a burst can never land entirely on the
        candidate)."""
        with self._lock:
            if not self._active:
                return None
            slot = self._seq
            self._seq += 1
            f = self.config.fraction
            if int((slot + 1) * f) > int(slot * f):
                return self._candidate_models, self._candidate_version
            return None

    def record(self, arm: str, error: bool = False, nonfinite: int = 0,
               latency_s: Optional[float] = None, n: int = 1):
        with self._lock:
            if not self._active:
                return
            st = self._arms[arm]
            st.requests += n
            if error:
                st.errors += n
            if nonfinite:
                st.nonfinite += nonfinite
            if latency_s is not None:
                st.latencies.extend([latency_s] * n)
        self._c_requests.labels(arm=arm).inc(n)

    # -- the watchdog -------------------------------------------------------
    def _breach(self) -> Optional[str]:
        """Caller holds the lock. Breach reason or None."""
        cfg = self.config
        cand = self._arms[CANDIDATE]
        inc = self._arms[INCUMBENT]
        if cand.nonfinite > cfg.nan_tolerance:
            return "nan_scores"
        if cand.requests >= cfg.min_requests:
            allowed = (inc.error_rate() * cfg.max_error_ratio
                       + cfg.error_floor)
            if cand.error_rate() > allowed:
                return "error_rate"
        return None

    def take_decision(self) -> Optional[dict]:
        """Evaluate the watchdog; on promote/rollback, atomically clear
        the canary and return the decision dict (the caller applies the
        model change). None while the window is still open."""
        with self._lock:
            if not self._active:
                return None
            reason = self._breach()
            verdict = None
            cand = self._arms[CANDIDATE]
            inc = self._arms[INCUMBENT]
            if reason is not None:
                verdict = ("rollback", reason)
            elif (self.clock() - self._started_at) >= self.config.window_s:
                if cand.requests >= self.config.min_requests:
                    c50, i50 = cand.p50(), inc.p50()
                    if c50 is not None and i50 is not None and i50 > 0 \
                            and c50 > self.config.max_latency_ratio * i50:
                        verdict = ("rollback", "latency")
                    else:
                        verdict = ("promote", "window_clean")
                # else: not enough candidate traffic to judge — the
                # window stays open (an idle candidate serves almost
                # nothing, so waiting is safe)
            if verdict is None:
                return None
            kind, why = verdict
            decision = {
                "decision": kind, "reason": why,
                "candidateVersion": self._candidate_version,
                "models": self._candidate_models,
                "foldInEvents": self._candidate_events,
                "windowSec": round(self.clock() - self._started_at, 3),
                "arms": {a: s.snapshot() for a, s in self._arms.items()},
            }
            self._active = False
            self._candidate_models = None
            self.last_decision = {k: v for k, v in decision.items()
                                  if k != "models"}
        if kind == "promote":
            self._c_promotions.inc()
            logger.info("canary PROMOTED: %s (%s)",
                        decision["candidateVersion"], why)
        else:
            self._c_rollbacks.labels(reason=why).inc()
            logger.error(
                "canary ROLLBACK of %s: %s — incumbent keeps serving",
                decision["candidateVersion"], why)
        # diagnostics plane (ISSUE 6): the verdict is a flight record;
        # a rollback additionally freezes a postmortem bundle (flight
        # tail + traces + registry scrape + provider states) the
        # operator replays via `pio incidents show`
        try:
            from predictionio_tpu.obs.flight import FLIGHT
            FLIGHT.record("canary_" + kind,
                          model_version=decision["candidateVersion"],
                          reason=why,
                          windowSec=decision["windowSec"],
                          arms=decision["arms"])
            if kind == "rollback":
                from predictionio_tpu.obs.incidents import INCIDENTS
                INCIDENTS.capture(
                    "canary_rollback",
                    f"canary rollback of "
                    f"{decision['candidateVersion']} ({why})",
                    context={k: v for k, v in decision.items()
                             if k != "models"})
        except Exception:
            logger.debug("canary forensics failed", exc_info=True)
        return decision

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            out = {
                "enabled": self.enabled,
                "active": self._active,
                "fraction": self.config.fraction,
                "superseded": self.superseded,
                "lastDecision": self.last_decision,
            }
            if self._active:
                out.update({
                    "candidateVersion": self._candidate_version,
                    "ageSec": round(self.clock() - self._started_at, 3),
                    "arms": {a: s.snapshot()
                             for a, s in self._arms.items()},
                })
            return out


def count_nonfinite(obj, depth: int = 0) -> int:
    """Non-finite floats anywhere in a (bounded-depth) JSON-shaped
    prediction — the per-response NaN-score detector."""
    import math
    if isinstance(obj, float):
        return 0 if math.isfinite(obj) else 1
    if depth >= 6:
        return 0
    if isinstance(obj, dict):
        return sum(count_nonfinite(v, depth + 1) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(count_nonfinite(v, depth + 1) for v in obj)
    return 0
