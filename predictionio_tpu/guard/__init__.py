"""Model-guard layer: numerical sentinels, pre-swap quality gates,
canary serving and automatic rollback (ISSUE 5 tentpole).

PR 3 made the system survive *infrastructure* faults; this package
defends against *model* faults — a fold tick fed poisoned events, a
NaN/Inf blow-up in an ALS sweep, or a degenerate factor table must
never be hot-swapped into live serving unchecked:

- ``sentinels`` — cheap on-device finite/norm-explosion checks inside
  the ALS train sweeps and ``fold_in``, with a checkpointed
  last-good-sweep rollback (a poisoned tick aborts — restoring deltas
  via the PR 1 machinery — instead of minting NaN factors).
- ``gates``     — pre-swap quality gates on the registry/scheduler
  publish path: finiteness, factor-norm and score-distribution drift
  bounds vs the live model, and a golden-query replay set whose
  results must stay within an overlap threshold.
- ``canary``    — canary serving + post-swap watchdog in the engine
  server: a new version serves a configurable traffic fraction first;
  error-rate/NaN-score/latency breaches vs the incumbent trigger an
  automatic rollback to the registry-pinned last-known-good version.

Every decision emits on the PR 2 telemetry layer (``pio_guard_*``
counters, gate verdicts in the ``fold_tick`` trace, ``X-PIO-Canary``
response tagging). ``PIO_GUARD=off`` is the operator kill switch for
sentinels + gates (canary is per-server config).
"""

from predictionio_tpu.guard.sentinels import (  # noqa: F401
    NumericalFault, SweepSentinel, guard_enabled, table_stats)
from predictionio_tpu.guard.gates import (  # noqa: F401
    GateConfig, GateRejected, QualityGatekeeper)
from predictionio_tpu.guard.canary import (  # noqa: F401
    CanaryConfig, CanaryController)

__all__ = [
    "NumericalFault", "SweepSentinel", "guard_enabled", "table_stats",
    "GateConfig", "GateRejected", "QualityGatekeeper",
    "CanaryConfig", "CanaryController",
]
