"""Numerical sentinels: on-device finite/norm checks for factor sweeps.

Large-scale training systems treat non-finite values and loss spikes as
first-class recoverable faults rather than silent corruption (PAPERS.md
fault-tolerant-training surveys); here the unit of recovery is one ALS
sweep. A sentinel check is one tiny jitted reduction over the rows a
sweep just solved — an all-finite flag and the max squared row norm —
fetched as two scalars, so the cost per check is O(touched rows) device
work plus one host sync, not an O(model) host round trip.

Breach policy is the caller's:

- ``fold_in_coo`` checks each side after its solve. With at least one
  clean full sweep checkpointed it rolls the device tables back to that
  sweep and publishes the last-good state; with none it raises
  ``NumericalFault`` so the tick aborts and the scheduler's existing
  delta-restore machinery (PR 1) requeues the events.
- ``als_train`` checkpoints the factor tables each iteration (an HBM
  copy, never a host fetch) and on breach returns the last clean
  iteration's model instead of NaN factors; a first-iteration breach
  raises.

``PIO_GUARD=off`` (or ``0``) disables every sentinel and gate — the
operator kill switch when the guard layer itself misbehaves.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


class NumericalFault(ArithmeticError):
    """A sweep produced non-finite or norm-exploded factor rows."""

    def __init__(self, site: str, detail: str):
        super().__init__(f"numerical fault in {site}: {detail}")
        self.site = site
        self.detail = detail


def guard_enabled() -> bool:
    """The PIO_GUARD kill switch: sentinels + gates are on unless the
    environment says ``off``/``0``."""
    return os.environ.get("PIO_GUARD", "").strip().lower() \
        not in ("off", "0", "false")


_jits: dict = {}


def _jitted(name, impl):
    fn = _jits.get(name)
    if fn is None:
        import jax
        fn = jax.jit(impl)
        _jits[name] = fn
    return fn


def _table_stats_impl(table):
    import jax.numpy as jnp
    finite = jnp.all(jnp.isfinite(table))
    sq = jnp.sum(table.astype(jnp.float32) ** 2, axis=-1)
    return jnp.stack([finite.astype(jnp.float32),
                      jnp.max(sq, initial=0.0)])


def _rows_stats_impl(table, idx):
    import jax.numpy as jnp
    rows = table[idx]
    finite = jnp.all(jnp.isfinite(rows))
    sq = jnp.sum(rows.astype(jnp.float32) ** 2, axis=-1)
    return jnp.stack([finite.astype(jnp.float32),
                      jnp.max(sq, initial=0.0)])


def _copy_impl(table):
    import jax.numpy as jnp
    return jnp.copy(table)


def device_copy(table):
    """An independent HBM copy — the checkpoint buffer survives a later
    donated sweep consuming the original."""
    return _jitted("copy", _copy_impl)(table)


def table_stats(table) -> Tuple[bool, float]:
    """(all finite, max row L2 norm) of a device (or host) table."""
    vals = np.asarray(_jitted("table_stats", _table_stats_impl)(table))
    return bool(vals[0] > 0.5), float(np.sqrt(max(vals[1], 0.0)))


def _pad_pow2(idx: np.ndarray) -> np.ndarray:
    """Pad the checked-row index vector to a power-of-two length
    (repeating the first index — duplicates change neither the finite
    flag nor the max) so the jitted stats kernel compiles once per size
    class instead of once per touched-set size."""
    n = int(idx.size)
    m = 1 << max(n - 1, 0).bit_length()
    if m == n:
        return idx
    out = np.empty(m, dtype=np.int32)
    out[:n] = idx
    out[n:] = idx[0]
    return out


def rows_stats(table, idx: np.ndarray) -> Tuple[bool, float]:
    """(all finite, max row L2 norm) over ``table[idx]`` — the
    O(touched) per-side sentinel read."""
    if idx.size == 0:
        return True, 0.0
    padded = _pad_pow2(np.asarray(idx, dtype=np.int32))
    vals = np.asarray(
        _jitted("rows_stats", _rows_stats_impl)(table, padded))
    return bool(vals[0] > 0.5), float(np.sqrt(max(vals[1], 0.0)))


def host_max_norm(*tables: np.ndarray) -> float:
    """Max row L2 norm across host factor tables — the baseline the
    explosion bound scales from."""
    mx = 0.0
    for t in tables:
        if t is None or t.size == 0:
            continue
        with np.errstate(over="ignore", invalid="ignore"):
            n = float(np.sqrt(np.max(np.einsum("ij,ij->i", t, t))))
        if np.isfinite(n):
            mx = max(mx, n)
    return mx


def _breach_counter():
    from predictionio_tpu.obs import get_registry
    return get_registry().counter(
        "pio_guard_sentinel_breaches_total",
        "Numerical sentinel breaches (non-finite or norm-exploded "
        "factor rows) by site",
        labelnames=("site",))


def _breach_forensics(site: str, detail: str):
    """Diagnostics plane (ISSUE 6): a breach is both a flight record
    and an incident bundle — the postmortem evidence an operator
    reconstructs the poisoned tick from. Never raises."""
    try:
        from predictionio_tpu.obs.flight import FLIGHT
        from predictionio_tpu.obs.incidents import INCIDENTS
        FLIGHT.record("sentinel_breach", site=site, detail=detail)
        INCIDENTS.capture("sentinel_breach",
                          f"numerical fault in {site}",
                          context={"site": site, "detail": detail})
    except Exception:
        logger.debug("breach forensics failed", exc_info=True)


class SweepSentinel:
    """Per-sweep breach detector: rows must be finite and their norms
    must stay under ``max(norm_floor, norm_ratio * baseline)`` where
    the baseline is the incumbent model's max row norm (a legitimate
    fold moves rows a little; an explosion moves them orders of
    magnitude)."""

    def __init__(self, site: str, baseline_norm: float,
                 norm_ratio: float = 1e3, norm_floor: float = 1e4):
        self.site = site
        self.bound = max(norm_floor, norm_ratio * baseline_norm)
        self.breaches = 0
        # largest norm seen by a PASSING check: callers fold it into the
        # next tick's baseline so the baseline never needs another
        # O(model) rescan (untouched rows keep their old, already-
        # covered norms; touched rows were all observed here)
        self.observed_max = baseline_norm

    def check_rows(self, table, idx: np.ndarray, what: str
                   ) -> Optional[NumericalFault]:
        """Inspect the just-solved rows; returns the fault (also counted
        in ``pio_guard_sentinel_breaches_total``) or None. The CALLER
        decides whether to roll back or raise."""
        if not guard_enabled():
            return None
        finite, max_norm = rows_stats(table, idx)
        if finite and max_norm <= self.bound:
            self.observed_max = max(self.observed_max, max_norm)
            return None
        self.breaches += 1
        _breach_counter().labels(site=self.site).inc()
        detail = (f"{what}: finite={finite} max_row_norm={max_norm:.4g} "
                  f"bound={self.bound:.4g}")
        logger.error("sentinel breach in %s — %s", self.site, detail)
        _breach_forensics(self.site, detail)
        return NumericalFault(self.site, detail)

    def check_table(self, table, what: str) -> Optional[NumericalFault]:
        """Whole-table variant (train sweeps, where every row moved)."""
        if not guard_enabled():
            return None
        finite, max_norm = table_stats(table)
        if finite and max_norm <= self.bound:
            return None
        self.breaches += 1
        _breach_counter().labels(site=self.site).inc()
        detail = (f"{what}: finite={finite} max_row_norm={max_norm:.4g} "
                  f"bound={self.bound:.4g}")
        logger.error("sentinel breach in %s — %s", self.site, detail)
        _breach_forensics(self.site, detail)
        return NumericalFault(self.site, detail)
