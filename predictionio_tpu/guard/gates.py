"""Pre-swap model quality gates: no publish without passing validation.

The DASE deploy loop the reference assumes has a human between train
and deploy; our online fold loop has none, so the machine runs the
checklist instead. ``QualityGatekeeper.evaluate`` compares a candidate
model set against the live one and returns a structured verdict report;
the scheduler refuses to publish (``GateRejected``) on any failure and
the registry can run the finiteness gate as a last line before
persisting a version.

Gates (each verdict is ``pass``/``fail``/``skip`` with detail, counted
in ``pio_guard_gate_verdicts_total{gate,verdict}``):

- ``finite``        — every factor table in the candidate is finite.
- ``norm_drift``    — candidate max row norms within a ratio bound of
                      the live model's (per table name).
- ``score_drift``   — the score distribution over a fixed sampled
                      user x item probe grid must not shift more than
                      ``max_score_shift`` live-standard-deviations or
                      widen more than ``max_score_spread_ratio`` x.
- ``golden_queries``— a replay set of real queries answered by both
                      models; each answer's top-k item overlap must
                      stay >= ``golden_min_overlap``. Queries come from
                      config, or are auto-derived from the model's user
                      vocabulary for user-keyed templates.

Models are duck-typed: anything exposing ``.als`` (recommendation), a
raw ``ALSModel``, or a dataclass carrying 2-D float factor tables
(similarproduct) is gateable; unrecognized models skip the factor gates
rather than fail them.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.guard.sentinels import guard_enabled

logger = logging.getLogger(__name__)


class GateRejected(RuntimeError):
    """A candidate model failed a pre-swap quality gate."""

    def __init__(self, report: dict):
        failed = [g["gate"] for g in report.get("gates", ())
                  if g.get("verdict") == "fail"]
        super().__init__(
            "model publish rejected by quality gate(s): "
            + (", ".join(failed) or "unknown"))
        self.report = report


@dataclass(frozen=True)
class GateConfig:
    """Gate knobs (docs/operations.md "Guarded deploys")."""
    enabled: bool = True
    require_finite: bool = True
    max_norm_ratio: float = 10.0       # candidate vs live max row norm
    norm_floor: float = 1e3            # absolute norm slack near zero
    # score-distribution probe: sampled users x items, fixed seed
    sample_entities: int = 128
    max_score_shift: float = 3.0       # |mean shift| in live std units
    max_score_spread_ratio: float = 10.0
    # std floor as a fraction of the live mean magnitude: a live model
    # with near-constant probe scores must not fail every candidate on
    # a microscopic absolute shift
    score_std_floor_frac: float = 0.05
    # golden-query replay
    golden_queries: Tuple[dict, ...] = ()
    golden_min_overlap: float = 0.5    # retained fraction of live top-k
    golden_num: int = 10               # k for auto-derived queries
    auto_golden: int = 8               # users sampled when no explicit set
    seed: int = 0


def _factor_tables(model) -> Dict[str, np.ndarray]:
    """The 2-D float factor tables a model carries, by attribute name.
    Unknown shapes return {} (factor gates skip, never guess)."""
    from predictionio_tpu.ops.als import ALSModel
    if isinstance(model, ALSModel):
        return {"user_factors": model.user_factors,
                "item_factors": model.item_factors}
    als = getattr(model, "als", None)
    if isinstance(als, ALSModel):
        return {"user_factors": als.user_factors,
                "item_factors": als.item_factors}
    out: Dict[str, np.ndarray] = {}
    for k, v in getattr(model, "__dict__", {}).items():
        if isinstance(v, np.ndarray) and v.ndim == 2 \
                and np.issubdtype(v.dtype, np.floating):
            out[k] = v
        elif _is_sharded(v):
            out[k] = v
    return out


def _score_pair(tables: Dict[str, np.ndarray]
                ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(user-like, item-like) table pair for the score probe."""
    u = tables.get("user_factors")
    v = tables.get("item_factors")
    if v is None:
        v = tables.get("item_factors_raw")
    if u is None or v is None or u.shape[1] != v.shape[1]:
        return None
    return u, v


def _is_sharded(t) -> bool:
    from predictionio_tpu.parallel.sharded_table import is_sharded
    return is_sharded(t)


def _all_finite(t) -> bool:
    """Finiteness over either layout: per-shard host-mirror scans for
    a ShardedTable (no device involved — the gates must not force a
    cross-shard gather), plain numpy otherwise."""
    if _is_sharded(t):
        return t.all_finite()
    return bool(np.isfinite(t).all())


def _probe_rows(t, idx) -> np.ndarray:
    """Sampled rows for the score-distribution probe: host shard
    mirrors for sharded tables, fancy indexing for numpy — the gates
    run the same statistics over both layouts (no silent gate bypass
    for sharded models)."""
    from predictionio_tpu.parallel.sharded_table import table_rows
    return table_rows(t, idx)


def _max_row_norm(t) -> float:
    if _is_sharded(t):
        return t.max_row_norm()
    if t.size == 0:
        return 0.0
    with np.errstate(over="ignore", invalid="ignore"):
        n = np.sqrt(np.max(np.einsum("ij,ij->i", t, t)))
    return float(n)


def _max_row_norm_cached(model, name: str, t: np.ndarray) -> float:
    """Per-table max row norm memoized ON the model object: this tick's
    candidate is the next tick's live model, so in steady state the
    norm-drift gate scans only the candidate side once — not both
    models' full tables every tick."""
    memo = getattr(model, "_pio_guard_norms", None)
    if memo is None:
        memo = {}
        try:
            object.__setattr__(model, "_pio_guard_norms", memo)
        except (AttributeError, TypeError):
            memo = None
    if memo is not None and name in memo:
        return memo[name]
    v = _max_row_norm(t)
    if memo is not None:
        memo[name] = v
    return v


def _result_items(result) -> Optional[List[str]]:
    """Ranked item ids out of a predict result (ItemScoreResult or its
    wire dict); None when the shape is unrecognized."""
    scores = getattr(result, "item_scores", None)
    if scores is not None:
        return [s.item for s in scores]
    if isinstance(result, dict) and "itemScores" in result:
        return [s.get("item") for s in result["itemScores"]]
    return None


def _result_scores(result) -> List[float]:
    scores = getattr(result, "item_scores", None)
    if scores is not None:
        return [float(s.score) for s in scores]
    if isinstance(result, dict) and "itemScores" in result:
        return [float(s.get("score", 0.0)) for s in result["itemScores"]]
    return []


class QualityGatekeeper:
    """Runs every configured gate for each (candidate, live) model pair
    and aggregates a report: ``{"passed": bool, "gates": [...]}``."""

    def __init__(self, config: Optional[GateConfig] = None, registry=None):
        self.config = config or GateConfig()
        if registry is None:
            from predictionio_tpu.obs import get_registry
            registry = get_registry()
        self._c_verdicts = registry.counter(
            "pio_guard_gate_verdicts_total",
            "Pre-swap quality-gate verdicts by gate and verdict",
            labelnames=("gate", "verdict"))

    # -- individual gates ---------------------------------------------------
    def _gate_finite(self, cand_tables: Dict[str, np.ndarray]) -> dict:
        bad = [name for name, t in cand_tables.items()
               if t.size and not _all_finite(t)]
        if not cand_tables:
            return {"gate": "finite", "verdict": "skip",
                    "detail": "no factor tables"}
        if bad:
            return {"gate": "finite", "verdict": "fail",
                    "detail": f"non-finite values in {', '.join(bad)}"}
        return {"gate": "finite", "verdict": "pass",
                "detail": f"{len(cand_tables)} table(s) finite"}

    def _gate_norm_drift(self, cand, live, cand_tables,
                         live_tables) -> dict:
        cfg = self.config
        shared = [n for n in cand_tables if n in live_tables]
        if not shared:
            return {"gate": "norm_drift", "verdict": "skip",
                    "detail": "no comparable tables"}
        worst = None
        for name in shared:
            cn = _max_row_norm_cached(cand, name, cand_tables[name])
            ln = _max_row_norm_cached(live, name, live_tables[name])
            bound = max(cfg.norm_floor, cfg.max_norm_ratio * ln)
            if not np.isfinite(cn) or cn > bound:
                worst = (name, cn, bound)
                break
        if worst is not None:
            name, cn, bound = worst
            return {"gate": "norm_drift", "verdict": "fail",
                    "detail": f"{name} max row norm {cn:.4g} exceeds "
                              f"bound {bound:.4g}"}
        return {"gate": "norm_drift", "verdict": "pass",
                "detail": f"{len(shared)} table(s) within "
                          f"{cfg.max_norm_ratio:g}x"}

    def _gate_score_drift(self, cand_tables, live_tables) -> dict:
        cfg = self.config
        cand = _score_pair(cand_tables)
        live = _score_pair(live_tables)
        if cand is None or live is None:
            return {"gate": "score_drift", "verdict": "skip",
                    "detail": "no (user, item) factor pair"}
        cu, cv = cand
        lu, lv = live
        nu = min(cu.shape[0], lu.shape[0])
        ni = min(cv.shape[0], lv.shape[0])
        if nu == 0 or ni == 0:
            return {"gate": "score_drift", "verdict": "skip",
                    "detail": "empty shared vocabulary"}
        rng = np.random.default_rng(cfg.seed)
        iu = rng.choice(nu, size=min(cfg.sample_entities, nu),
                        replace=False)
        iv = rng.choice(ni, size=min(cfg.sample_entities, ni),
                        replace=False)
        with np.errstate(over="ignore", invalid="ignore"):
            s_live = _probe_rows(lu, iu) @ _probe_rows(lv, iv).T
            s_cand = _probe_rows(cu, iu) @ _probe_rows(cv, iv).T
        if not np.isfinite(s_cand).all():
            return {"gate": "score_drift", "verdict": "fail",
                    "detail": "candidate probe scores non-finite"}
        live_mean = float(np.mean(s_live))
        live_std = max(float(np.std(s_live)),
                       cfg.score_std_floor_frac * (abs(live_mean) + 1.0),
                       1e-6)
        shift = abs(float(np.mean(s_cand)) - live_mean)
        spread = float(np.std(s_cand))
        if shift > cfg.max_score_shift * live_std:
            return {"gate": "score_drift", "verdict": "fail",
                    "detail": f"mean score shifted {shift:.4g} "
                              f"(> {cfg.max_score_shift:g} x live std "
                              f"{live_std:.4g})"}
        if spread > cfg.max_score_spread_ratio * live_std:
            return {"gate": "score_drift", "verdict": "fail",
                    "detail": f"score spread {spread:.4g} widened past "
                              f"{cfg.max_score_spread_ratio:g} x live "
                              f"std {live_std:.4g}"}
        return {"gate": "score_drift", "verdict": "pass",
                "detail": f"shift {shift:.4g} / spread {spread:.4g} "
                          f"within bounds"}

    def _golden_query_set(self, live_model, algo) -> List[dict]:
        cfg = self.config
        if cfg.golden_queries:
            return list(cfg.golden_queries)
        # auto-derivation for user-keyed templates: a deterministic
        # sample of known users replays as {"user": id, "num": k}
        user_ix = getattr(live_model, "user_ix", None)
        qc = getattr(algo, "query_class", None)
        if user_ix is None or len(user_ix) == 0 or qc is None \
                or "user" not in getattr(qc, "__dataclass_fields__", {}):
            return []
        rng = np.random.default_rng(cfg.seed)
        n = min(cfg.auto_golden, len(user_ix))
        picks = rng.choice(len(user_ix), size=n, replace=False)
        return [{"user": user_ix.id_of(int(ix)), "num": cfg.golden_num}
                for ix in picks]

    def _gate_golden(self, candidate, live, algo) -> dict:
        cfg = self.config
        if algo is None or getattr(algo, "query_class", None) is None:
            return {"gate": "golden_queries", "verdict": "skip",
                    "detail": "no query-capable algorithm"}
        queries = self._golden_query_set(live, algo)
        if not queries:
            return {"gate": "golden_queries", "verdict": "skip",
                    "detail": "no golden queries (configure "
                              "gate_config.golden_queries)"}
        qc = algo.query_class
        worst = 1.0
        compared = 0
        try:
            qs = [qc.from_dict(qd) for qd in queries]
            live_results = self._replay(algo, live, qs)
            cand_results = self._replay(algo, candidate, qs)
        except Exception as e:
            return {"gate": "golden_queries", "verdict": "fail",
                    "detail": f"golden replay raised: {e}"}
        for qd, live_r, cand_r in zip(queries, live_results,
                                      cand_results):
            if any(not np.isfinite(s) for s in _result_scores(cand_r)):
                return {"gate": "golden_queries", "verdict": "fail",
                        "detail": f"non-finite score for {qd!r}"}
            live_items = _result_items(live_r)
            cand_items = _result_items(cand_r)
            if not live_items or cand_items is None:
                continue  # cold-start/unanswerable on the live model
            compared += 1
            overlap = len(set(live_items) & set(cand_items)) \
                / max(len(live_items), 1)
            worst = min(worst, overlap)
            if overlap < cfg.golden_min_overlap:
                return {"gate": "golden_queries", "verdict": "fail",
                        "detail": f"{qd!r}: top-k overlap {overlap:.2f} "
                                  f"< {cfg.golden_min_overlap:g}"}
        if compared == 0:
            return {"gate": "golden_queries", "verdict": "skip",
                    "detail": "no comparable golden answers"}
        return {"gate": "golden_queries", "verdict": "pass",
                "detail": f"{compared} quer(ies), worst overlap "
                          f"{worst:.2f}"}

    @staticmethod
    def _replay(algo, model, qs) -> List[Any]:
        """Answer every golden query against one model — one
        ``batch_predict`` device call when the algorithm has it (the
        per-query jit-dispatch overhead dominated the gate's cost),
        else a predict loop. Runs under the ``gates_probe`` compile
        label (obs/costmon) so a probe-induced recompile is charged to
        the gates, not to serving.

        Compile plane (ISSUE 9): batch_predict dispatches through the
        AOT registry's shape buckets, and the deploy/swap warm set
        covers the golden-replay batch bucket — so in steady state the
        gate probe runs zero XLA compiles (its bucket was compiled
        before the first tick's gate ever ran)."""
        from predictionio_tpu.obs import costmon
        with costmon.executable(costmon.GATES_PROBE):
            bp = getattr(algo, "batch_predict", None)
            if bp is not None:
                by_ix = dict(bp(model, list(enumerate(qs))))
                return [by_ix.get(i) for i in range(len(qs))]
            return [algo.predict(model, q) for q in qs]

    # -- aggregation --------------------------------------------------------
    def _count(self, gates: Sequence[dict]):
        for g in gates:
            self._c_verdicts.labels(gate=g["gate"],
                                    verdict=g["verdict"]).inc()

    def evaluate(self, candidates: Sequence[Any], live: Sequence[Any],
                 algorithms: Optional[Sequence[Any]] = None) -> dict:
        """Gate every candidate model against its live counterpart.
        Returns ``{"passed", "gates"}``; disabled (config or PIO_GUARD)
        reports pass with a single skip entry."""
        if not self.config.enabled or not guard_enabled():
            return {"passed": True,
                    "gates": [{"gate": "all", "verdict": "skip",
                               "detail": "gates disabled"}]}
        gates: List[dict] = []
        algorithms = list(algorithms or [None] * len(candidates))
        for i, (cand, live_m) in enumerate(zip(candidates, live)):
            if cand is live_m:
                continue  # not refreshed this publish: nothing to gate
            algo = algorithms[i] if i < len(algorithms) else None
            ct = _factor_tables(cand)
            lt = _factor_tables(live_m)
            if self.config.require_finite:
                gates.append(self._gate_finite(ct))
            if gates and gates[-1].get("verdict") == "fail":
                # non-finite tables poison every downstream comparison;
                # report the root cause alone
                break
            gates.append(self._gate_norm_drift(cand, live_m, ct, lt))
            gates.append(self._gate_score_drift(ct, lt))
            gates.append(self._gate_golden(cand, live_m, algo))
        self._count(gates)
        return {"passed": all(g["verdict"] != "fail" for g in gates),
                "gates": gates}

    def check_publishable(self, models: Sequence[Any]):
        """The registry's last line: refuse to persist non-finite factor
        tables even when no live model is available to compare against.
        Raises ``GateRejected``."""
        if not self.config.enabled or not guard_enabled() \
                or not self.config.require_finite:
            return
        gates = [self._gate_finite(_factor_tables(m)) for m in models]
        gates = [g for g in gates if g["verdict"] != "skip"]
        self._count(gates)
        if any(g["verdict"] == "fail" for g in gates):
            raise GateRejected({"passed": False, "gates": gates})
