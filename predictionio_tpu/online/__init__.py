"""Online model updates: the event->model loop without full retrains.

Three pieces (ISSUE 1 tentpole; ALX arxiv 2112.02194 fold-in shape,
DrJAX arxiv 2403.07128 streaming-aggregation motivation):

  - ``fold_in``    — batched one-sided normal-equation solves for only the
                     user/item rows touched by fresh events, reusing the
                     bucketed batched solvers of ``ops/solve.py`` (explicit
                     ALS-WR and implicit Hu-Koren paths).
  - ``scheduler``  — a delta-training loop that tails the event store,
                     accumulates per-entity deltas with the
                     ``data/aggregator.py`` monoid machinery, triggers
                     fold-in on staleness/count thresholds, and escalates
                     to a full retrain when drift exceeds a bound.
  - ``registry``   — a model-version registry layered on
                     ``core/persistence.py`` so folded models publish as
                     new COMPLETED engine instances the existing
                     ``/reload`` hot-swap path picks up atomically.
"""

from predictionio_tpu.online.fold_in import (FoldInConfig, FoldInStats,
                                             fold_in_coo, solve_rows)
from predictionio_tpu.online.registry import (ModelVersionRegistry,
                                              ROLLEDBACK_STATUS)
from predictionio_tpu.online.scheduler import (DeltaTrainingScheduler,
                                               EntityDelta, SchedulerConfig,
                                               attach_scheduler)

__all__ = [
    "FoldInConfig", "FoldInStats", "fold_in_coo", "solve_rows",
    "ModelVersionRegistry", "ROLLEDBACK_STATUS",
    "DeltaTrainingScheduler", "EntityDelta", "SchedulerConfig",
    "attach_scheduler",
]
