"""Model-version registry: publish folded models as first-class versions.

Layered on the existing persistence stack (``core/persistence.py`` decides
automatic-pickle vs manifest vs retrain per algorithm; ``Models``/
``EngineInstances`` DAOs store the blobs and the lineage): each publish
clones the serving engine instance into a NEW row tagged as an online
update, serializes the updated models through the same
``make_serializable_models`` path a training run uses, and marks it
COMPLETED — which makes the EXISTING hot-swap machinery
(``get_latest_completed`` + ``/reload``) pick it up with no new wire
protocol. Version history is ordinary engine-instance history: every
fold-in survives restarts, `pio status` shows it, and rolling back is
"deploy the previous instance id".
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
from typing import Any, List, Optional, Sequence

from predictionio_tpu.data.storage.base import EngineInstance, Model
from predictionio_tpu.data.storage.registry import Storage

logger = logging.getLogger(__name__)

# batch tag marking instances produced by the online path (vs `pio train`)
ONLINE_BATCH_TAG = "online-fold-in"


class ModelVersionRegistry:
    """Versioned model publish/list/rollback over the metadata DAOs."""

    def __init__(self, instances=None, models=None):
        self._instances = instances
        self._models = models

    @property
    def instances(self):
        return self._instances or Storage.get_meta_data_engine_instances()

    @property
    def models(self):
        return self._models or Storage.get_model_data_models()

    def publish(self, engine, engine_params, base_instance: EngineInstance,
                models: Sequence[Any], meta: Optional[dict] = None) -> str:
        """Persist ``models`` as a new COMPLETED version derived from
        ``base_instance``. Returns the new instance id.

        The models go through the engine's standard serialization pipeline
        (PersistentModel manifests included), so a folded mesh-sharded
        model checkpoints exactly like a trained one."""
        now = _dt.datetime.now(_dt.timezone.utc)
        lineage = dict(meta or {})
        lineage["baseInstance"] = base_instance.id
        instance = base_instance.with_(
            id="", status="INIT", start_time=now, end_time=now,
            batch=f"{ONLINE_BATCH_TAG}:{json.dumps(lineage, sort_keys=True)}")
        instance_id = self.instances.insert(instance)
        instance = self.instances.get(instance_id)
        try:
            from predictionio_tpu.core.engine import TrainResult
            result = TrainResult(
                models=list(models),
                algorithms=engine.make_algorithms(engine_params))
            serializable = engine.make_serializable_models(
                result, instance_id, engine_params)
            blob = engine.serialize_models(serializable)
            self.models.insert(Model(instance_id, blob))
        except Exception:
            # mirror run_train's failure bookkeeping: never leave an
            # INIT row behind (the scheduler retries every tick, and an
            # orphan per retry would pollute instance history forever)
            self.instances.update(instance.with_(
                status="ABORTED",
                end_time=_dt.datetime.now(_dt.timezone.utc)))
            raise
        self.instances.update(instance.with_(
            status="COMPLETED",
            end_time=_dt.datetime.now(_dt.timezone.utc)))
        logger.info("Published online model version %s (base %s)",
                    instance_id, base_instance.id)
        return instance_id

    def versions(self, engine_id: str, engine_version: str,
                 engine_variant: str) -> List[EngineInstance]:
        """COMPLETED instances for one engine, newest first — training runs
        and online versions interleaved in publish order."""
        return self.instances.get_completed(engine_id, engine_version,
                                            engine_variant)

    def online_versions(self, engine_id: str, engine_version: str,
                        engine_variant: str) -> List[EngineInstance]:
        return [i for i in self.versions(engine_id, engine_version,
                                         engine_variant)
                if i.batch.startswith(ONLINE_BATCH_TAG)]
