"""Model-version registry: publish folded models as first-class versions.

Layered on the existing persistence stack (``core/persistence.py`` decides
automatic-pickle vs manifest vs retrain per algorithm; ``Models``/
``EngineInstances`` DAOs store the blobs and the lineage): each publish
clones the serving engine instance into a NEW row tagged as an online
update, serializes the updated models through the same
``make_serializable_models`` path a training run uses, and marks it
COMPLETED — which makes the EXISTING hot-swap machinery
(``get_latest_completed`` + ``/reload``) pick it up with no new wire
protocol. Version history is ordinary engine-instance history: every
fold-in survives restarts, `pio status` shows it, and rolling back is
"deploy the previous instance id".
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import re
from typing import Any, Dict, List, Optional, Sequence

from predictionio_tpu.data.storage.base import EngineInstance, Model
from predictionio_tpu.data.storage.registry import Storage

logger = logging.getLogger(__name__)

# batch tag marking instances produced by the online path (vs `pio train`)
ONLINE_BATCH_TAG = "online-fold-in"

# status stamped on versions demoted by `pio rollback` — no longer
# COMPLETED, so get_latest_completed (deploy, /reload) skips them
ROLLEDBACK_STATUS = "ROLLEDBACK"


class ModelVersionRegistry:
    """Versioned model publish/list/rollback over the metadata DAOs.

    ``gatekeeper`` (guard/gates.QualityGatekeeper) is the publish path's
    last line of defense: when set, ``publish`` refuses to persist
    models whose factor tables fail the finiteness gate — a registry
    used by several writers stays clean even if one of them skipped the
    scheduler-side gates.

    The last-known-good pin is a crash-atomic JSON sidecar under
    ``<PIO_FS_BASEDIR>/guard/`` (the registry's metadata DAOs have no
    KV surface): the canary watchdog pins each PROMOTED version, and
    ``pio rollback`` / ``rollback_to`` demote everything newer back to
    it after a bad deploy.
    """

    def __init__(self, instances=None, models=None, gatekeeper=None):
        self._instances = instances
        self._models = models
        self.gatekeeper = gatekeeper

    @property
    def instances(self):
        return self._instances or Storage.get_meta_data_engine_instances()

    @property
    def models(self):
        return self._models or Storage.get_model_data_models()

    def publish(self, engine, engine_params, base_instance: EngineInstance,
                models: Sequence[Any], meta: Optional[dict] = None) -> str:
        """Persist ``models`` as a new COMPLETED version derived from
        ``base_instance``. Returns the new instance id.

        The models go through the engine's standard serialization pipeline
        (PersistentModel manifests included), so a folded mesh-sharded
        model checkpoints exactly like a trained one."""
        if self.gatekeeper is not None:
            # raises guard.gates.GateRejected BEFORE any row exists —
            # a non-finite model never even gets an ABORTED instance
            self.gatekeeper.check_publishable(models)
        now = _dt.datetime.now(_dt.timezone.utc)
        lineage = dict(meta or {})
        lineage["baseInstance"] = base_instance.id
        # sharded online plane (ISSUE 12): a version whose models carry
        # model-sharded factor tables records the layout in its lineage
        # tag — the blob holds per-shard host slices (ShardedTable
        # serialization), so `pio status` and a restarted follower can
        # tell the layouts apart without deserializing models
        try:
            from predictionio_tpu.parallel.sharded_table import \
                sharding_meta
            info = sharding_meta(models)
            if info is not None:
                lineage.setdefault("sharding", info)
        except Exception:
            logger.debug("sharding lineage detection failed",
                         exc_info=True)
        instance = base_instance.with_(
            id="", status="INIT", start_time=now, end_time=now,
            batch=f"{ONLINE_BATCH_TAG}:{json.dumps(lineage, sort_keys=True)}")
        instance_id = self.instances.insert(instance)
        instance = self.instances.get(instance_id)
        try:
            from predictionio_tpu.core.engine import TrainResult
            result = TrainResult(
                models=list(models),
                algorithms=engine.make_algorithms(engine_params))
            serializable = engine.make_serializable_models(
                result, instance_id, engine_params)
            blob = engine.serialize_models(serializable)
            self.models.insert(Model(instance_id, blob))
        except Exception:
            # mirror run_train's failure bookkeeping: never leave an
            # INIT row behind (the scheduler retries every tick, and an
            # orphan per retry would pollute instance history forever)
            self.instances.update(instance.with_(
                status="ABORTED",
                end_time=_dt.datetime.now(_dt.timezone.utc)))
            raise
        self.instances.update(instance.with_(
            status="COMPLETED",
            end_time=_dt.datetime.now(_dt.timezone.utc)))
        logger.info("Published online model version %s (base %s)",
                    instance_id, base_instance.id)
        return instance_id

    def versions(self, engine_id: str, engine_version: str,
                 engine_variant: str) -> List[EngineInstance]:
        """COMPLETED instances for one engine, newest first — training runs
        and online versions interleaved in publish order."""
        return self.instances.get_completed(engine_id, engine_version,
                                            engine_variant)

    def online_versions(self, engine_id: str, engine_version: str,
                        engine_variant: str) -> List[EngineInstance]:
        return [i for i in self.versions(engine_id, engine_version,
                                         engine_variant)
                if i.batch.startswith(ONLINE_BATCH_TAG)]

    # -- last-known-good pin + rollback (ISSUE 5) ---------------------------
    @staticmethod
    def _pin_path(engine_id: str, engine_version: str,
                  engine_variant: str) -> str:
        from predictionio_tpu.data.storage.registry import base_dir
        key = re.sub(r"[^A-Za-z0-9._-]", "_",
                     f"{engine_id}_{engine_version}_{engine_variant}")
        return os.path.join(base_dir(), "guard", f"last_good_{key}.json")

    def pin_last_good(self, engine_id: str, engine_version: str,
                      engine_variant: str, instance_id: str):
        """Record ``instance_id`` as the last-known-good version for
        this engine (crash-atomic: temp + os.replace). Called by the
        canary watchdog on promotion and usable by operators directly."""
        path = self._pin_path(engine_id, engine_version, engine_variant)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"instanceId": instance_id,
                       "pinnedAt": _dt.datetime.now(
                           _dt.timezone.utc).isoformat()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        logger.info("pinned last-known-good %s for %s %s %s",
                    instance_id, engine_id, engine_version,
                    engine_variant)

    def last_good(self, engine_id: str, engine_version: str,
                  engine_variant: str) -> Optional[str]:
        try:
            with open(self._pin_path(engine_id, engine_version,
                                     engine_variant)) as f:
                return json.load(f).get("instanceId")
        except (FileNotFoundError, ValueError):
            return None

    def demote_version(self, instance_id: str) -> bool:
        """Mark one COMPLETED version ROLLEDBACK (the canary watchdog's
        verdict made durable: a restart or /reload must not resolve the
        rejected version via get_latest_completed). Returns False when
        the instance is unknown or not COMPLETED."""
        inst = self.instances.get(instance_id)
        if inst is None or inst.status != "COMPLETED":
            return False
        self.instances.update(inst.with_(
            status=ROLLEDBACK_STATUS,
            end_time=_dt.datetime.now(_dt.timezone.utc)))
        logger.warning("demoted version %s to %s", instance_id,
                       ROLLEDBACK_STATUS)
        return True

    def rollback_to(self, engine_id: str, engine_version: str,
                    engine_variant: str,
                    target_id: Optional[str] = None) -> Dict[str, Any]:
        """Demote every COMPLETED version newer than the target (the
        last-good pin by default; the previous COMPLETED version when
        no pin exists) to ``ROLLEDBACK`` so ``get_latest_completed`` —
        deploy, ``/reload`` — resolves the target again. Durable: a
        restarted server loads the rolled-back-to version. Returns
        ``{"target", "demoted"}``."""
        completed = self.versions(engine_id, engine_version,
                                  engine_variant)
        if not completed:
            raise ValueError(
                f"no COMPLETED versions for engine {engine_id} "
                f"{engine_version} {engine_variant}")
        target = target_id or self.last_good(engine_id, engine_version,
                                             engine_variant)
        if target is None:
            if len(completed) < 2:
                raise ValueError(
                    "no last-good pin and only one COMPLETED version — "
                    "nothing to roll back to")
            target = completed[1].id   # newest-first: the previous one
        ids = [i.id for i in completed]
        if target not in ids:
            raise ValueError(
                f"rollback target {target} is not a COMPLETED version "
                f"of engine {engine_id} {engine_version} "
                f"{engine_variant}")
        demoted = []
        now = _dt.datetime.now(_dt.timezone.utc)
        for inst in completed:
            if inst.id == target:
                break
            self.instances.update(inst.with_(status=ROLLEDBACK_STATUS,
                                             end_time=now))
            demoted.append(inst.id)
        self.pin_last_good(engine_id, engine_version, engine_variant,
                           target)
        logger.warning("rolled back to %s (demoted: %s)", target,
                       ", ".join(demoted) or "nothing")
        # diagnostics plane (ISSUE 6): an operator rollback is a
        # lifecycle transition AND an incident worth a bundle — the
        # durable counterpart of the canary watchdog's capture
        try:
            from predictionio_tpu.obs.flight import FLIGHT
            from predictionio_tpu.obs.incidents import INCIDENTS
            FLIGHT.record("registry_rollback", model_version=target,
                          demoted=demoted)
            INCIDENTS.capture(
                "registry_rollback",
                f"rolled back to {target} "
                f"({len(demoted)} version(s) demoted)",
                context={"target": target, "demoted": demoted,
                         "engineId": engine_id})
        except Exception:
            logger.debug("rollback forensics failed", exc_info=True)
        return {"target": target, "demoted": demoted}
