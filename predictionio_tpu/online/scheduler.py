"""Delta-training scheduler: tail the event store, fold in, hot-swap.

The background loop that closes the event->model gap (ISSUE 1 tentpole
piece 2). Each tick:

  1. TAIL — read events newer than the cursor through the ``LEvents``
     store (``EventStore.find`` with an event-time ``start_time`` cursor;
     channel-scoped when the engine's data source names a channel) and
     fold them into per-entity delta state with the same monoid machinery
     the property aggregator uses (``EntityDelta.merge`` is duck-type
     compatible with ``data/aggregator.merge_aggregations``, so partition
     merges reuse that code path verbatim).
  2. TRIGGER — when the accumulated delta count or the oldest delta's
     staleness crosses its threshold (or ``tick(force=True)``), run a
     fold-in: re-read the training data through the engine's own data
     source, and ask each algorithm that supports online updates
     (``algo.fold_in``) for a model with only the touched rows re-solved.
  3. DRIFT GATE — folded rows are exact GIVEN the frozen counterpart
     rows, so repeated fold-ins drift from the retrain fixed point. The
     post-fold training loss is compared against the anchor loss (the
     loss right after the last full train / first fold); when the ratio
     exceeds ``drift_ratio`` the scheduler stops folding and escalates
     through ``on_retrain``.
  4. PUBLISH — swap the attached in-process server atomically
     (zero dropped queries; the server counts swaps and fold-ins for
     ``/stats.json`` and ``/metrics``) and/or publish a new model version
     through the registry + POST ``/reload`` to a remote deployment.

Cursor semantics: the cursor is the max event time seen, inclusive-start
on re-read with an id set de-duplicating the boundary instant — events
back-dated BEFORE an already-advanced cursor are not observed until the
next full retrain (the same visibility rule a batch ``pio train`` run at
the cursor instant would have had).

Cost model: the touched rows' solves need their COMPLETE histories, and
item columns can span the corpus — but nothing outside the touched
entities. When the data source supports entity-filtered reads
(``read_training_touched``, backed by the storage layer's
``find_columnar_by_entities`` pushdown) and the touched set is small
(``filtered_read_max_entities``), a tick reads O(touched histories)
instead of running the full columnar scan (~22 s at ML-20M for a tick
touching a handful of users); larger touched sets, or data sources
without the hook, fall back to the full scan. The choice — and the rows
it read — is recorded in the ``fold_tick`` trace, the fold report
(``readPath``/``readRows``) and ``pio_fold_read_rows_total{path=...}``.
"""

from __future__ import annotations

import contextlib
import datetime as _dt
import logging
import threading
import time as _time
import urllib.request
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

from predictionio_tpu.data.aggregator import merge_aggregations
from predictionio_tpu.data.event import Event, utcnow
from predictionio_tpu.data.store import LEventStore
from predictionio_tpu.guard.gates import (GateConfig, GateRejected,
                                          QualityGatekeeper)
from predictionio_tpu.obs import TRACER, get_registry, jaxmon

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class EntityDelta:
    """Mergeable per-entity delta state — the rating-event analog of the
    aggregator's ``EventOp`` (same monoid laws: commutative, associative,
    time-keyed), consumable by ``merge_aggregations``."""
    count: int = 0
    first_t: Optional[_dt.datetime] = None
    last_t: Optional[_dt.datetime] = None

    @staticmethod
    def from_event(e: Event) -> "EntityDelta":
        return EntityDelta(count=1, first_t=e.event_time,
                           last_t=e.event_time)

    def merge(self, other: "EntityDelta") -> "EntityDelta":
        def opt(a, b, f):
            if a is None:
                return b
            if b is None:
                return a
            return f(a, b)
        return EntityDelta(
            count=self.count + other.count,
            first_t=opt(self.first_t, other.first_t, min),
            last_t=opt(self.last_t, other.last_t, max))


@dataclass(frozen=True)
class SchedulerConfig:
    app_name: str
    channel_name: Optional[str] = None
    # None = the engine data source's event_names (plus $set, which marks
    # property-only freshness the next retrain picks up)
    event_names: Optional[Sequence[str]] = None
    max_deltas: int = 256          # fold in after this many fresh events
    max_staleness_s: float = 30.0  # ... or once the oldest delta is this old
    drift_ratio: float = 1.5       # post-fold loss / anchor loss escalation
    poll_interval_s: float = 2.0   # background loop cadence
    tail_batch_limit: int = 50_000  # max events consumed per tick
    # entity-filtered tail reads (the O(touched) cutover): when the data
    # source exposes read_training_touched and the touched entity count
    # is at most filtered_read_max_entities, the fold reads only the
    # touched histories; otherwise the full scan runs. The threshold is
    # the cost-model knob: past a few thousand entities the per-id
    # pushdown probes approach the cost of one sequential scan.
    filtered_reads: bool = True
    filtered_read_max_entities: int = 1024
    # supervision (ISSUE 3): consecutive tick failures back off
    # exponentially (poll_interval * 2^k, capped), and after
    # max_tick_failures the scheduler stops folding and escalates to a
    # full retrain through on_retrain — a wedged fold loop must not
    # retry on the same cadence forever while the model quietly ages
    max_tick_failures: int = 5
    failure_backoff_cap_s: float = 60.0
    # breaker over the event-store tail read: a down store makes ticks
    # skip the read (no thread pile-up on a dead backend) until the
    # half-open probe sees it recover
    tail_breaker_failures: int = 3
    tail_breaker_reset_s: float = 10.0
    # pre-swap quality gates (ISSUE 5, guard/gates.py): every fold's
    # candidate models must pass finiteness, norm/score-drift and
    # golden-query gates against the LIVE models before a publish is
    # attempted; a rejection restores the deltas and counts toward the
    # retrain escalation (the same data will fold the same way again)
    gates: bool = True
    gate_config: GateConfig = GateConfig()


class FoldTickGate:
    """Per-host fold-tick fairness (ISSUE 18 satellite).

    Several attached schedulers contend for ONE device: without a
    gate, tick admission is FIFO thread wakeup — a chatty tenant whose
    poll interval happens to phase-align with the device going idle
    can starve a quieter tenant's folds indefinitely. Every scheduler
    a :class:`~predictionio_tpu.tenancy.host.ServingHost` attaches
    shares the host's gate; ``turn(tenant)`` admits exactly one tick
    at a time, and among waiters the grant goes to the tenant whose
    LAST grant is oldest (never-granted first, then arrival order) —
    round-robin by staleness, so every tenant's fold lag is bounded by
    (tenants × tick time) rather than by luck.

    The queue is observable: ``pio_fold_tick_wait_seconds{tenant}``
    records how long each tenant's tick waited for its turn — the
    direct "is the device over-subscribed for folding" signal.
    """

    def __init__(self, registry=None):
        reg = registry or get_registry()
        self._h_wait = reg.histogram(
            "pio_fold_tick_wait_seconds",
            "Time a tenant's fold tick waited for its turn at the "
            "shared per-host tick gate",
            labelnames=("tenant",))
        self._cond = threading.Condition()
        self._busy: Optional[str] = None
        self._seq = 0
        self._waiters: List[tuple] = []
        self._last_grant: Dict[str, float] = {}
        # per-tenant histogram children resolved once (gate calls run
        # on scheduler control threads, but there is no reason to
        # re-resolve labels every tick either)
        self._children: Dict[str, Any] = {}

    def _child(self, tenant: str):
        c = self._children.get(tenant)
        if c is None:
            if len(self._children) >= 4096:
                self._children.clear()
            c = self._children[tenant] = self._h_wait.labels(
                tenant=tenant)
        return c

    def _pick(self) -> Optional[tuple]:
        """The waiter whose tenant has gone longest without a grant
        (never-granted first; arrival order breaks ties)."""
        if not self._waiters:
            return None
        return min(self._waiters, key=lambda w: (
            self._last_grant.get(w[0], float("-inf")), w[1]))

    @contextlib.contextmanager
    def turn(self, tenant: str):
        tenant = tenant or ""
        t0 = _time.monotonic()
        with self._cond:
            me = (tenant, self._seq)
            self._seq += 1
            self._waiters.append(me)
            while self._busy is not None or self._pick() != me:
                self._cond.wait(timeout=1.0)
            self._waiters.remove(me)
            self._busy = tenant
        self._child(tenant).observe(_time.monotonic() - t0)
        try:
            yield
        finally:
            with self._cond:
                self._busy = None
                if len(self._last_grant) >= 4096:
                    self._last_grant.clear()
                self._last_grant[tenant] = _time.monotonic()
                self._cond.notify_all()

    def stats(self) -> dict:
        with self._cond:
            return {"busy": self._busy,
                    "waiting": [w[0] for w in sorted(
                        self._waiters, key=lambda w: w[1])]}


class DeltaTrainingScheduler:
    """One scheduler follows one deployed engine.

    ``server``: an in-process ``EngineServer`` to hot-swap (tests,
    single-process deployments). ``registry`` + ``reload_url``: publish
    each folded version through the model-version registry and poke a
    REMOTE deployment's ``/reload`` (the `pio update --follow` path).
    Either, both, or neither (dry runs) may be given.
    """

    def __init__(self, engine, engine_params, instance,
                 algorithms: Sequence[Any], models: Sequence[Any],
                 config: SchedulerConfig,
                 server=None, registry=None, reload_url: Optional[str] = None,
                 on_retrain: Optional[Callable[[dict], None]] = None,
                 event_store=None, cursor: Optional[_dt.datetime] = None,
                 tenant: Optional[str] = None, tick_gate=None):
        # multi-tenant serving (ISSUE 15): when this scheduler follows
        # one tenant slot of a ServingHost, its fold ticks' device
        # uploads and residency slots run under the tenant's
        # device_cache attribution scope — so the HBM budget manager
        # can evict THIS tenant's fold-resident tables by name
        self.tenant = str(tenant) if tenant is not None else None
        # shared per-host fold-tick fairness gate (ISSUE 18): when
        # several schedulers contend for one device, background ticks
        # take turns through it instead of racing FIFO thread wakeup
        self._tick_gate: Optional[FoldTickGate] = tick_gate
        self.engine = engine
        self.engine_params = engine_params
        self.instance = instance
        self.algorithms = list(algorithms)
        self.models = list(models)
        self.config = config
        self.server = server
        self.registry = registry
        self.reload_url = reload_url
        self.on_retrain = on_retrain
        self.events = event_store or LEventStore
        # cursor: events at/after this instant are "fresh". Default: a
        # training instance's start (everything before it is inside the
        # model); an ONLINE version instead carries the tail cursor its
        # fold read up to in its lineage tag — the publish-time
        # start_time would skip events that landed between the fold's
        # data read and the publish.
        self._cursor: Optional[_dt.datetime] = (
            cursor if cursor is not None
            else self._instance_cursor(instance))
        self._seen_at_cursor: Set[str] = set()
        # attach-time boundary dedup (ISSUE 11 triage): event times are
        # stored at millisecond precision, so events that landed in the
        # SAME millisecond the cursor anchor was stamped in sit exactly
        # AT the cursor instant — and the tail's inclusive-start read
        # would re-count them as fresh on every (re)attach, although
        # they are already inside the model this scheduler resumes from
        # (training reads its corpus after start_time is stamped; a
        # lineage cursor is the max event time the fold consumed).
        # Seed the boundary-dedup set the running tail already
        # maintains with the ids currently at the cursor instant. A
        # failed pre-read degrades to the old behavior: those events
        # double-count once.
        # Trade, chosen deliberately: an event whose (client-supplied)
        # event_time lands in the anchor's exact millisecond AND that
        # was ingested in the gap between the corpus/fold read and
        # this attach gets marked seen without having been folded. The
        # alternative re-folds EVERY genuine boundary event on EVERY
        # attach (the bug this fixes). The skipped event stays in the
        # store — the next entity touch or any retrain (drift
        # escalation, `pio train`) reads it — whereas the old behavior
        # corrupted fold accounting on every restart unconditionally.
        if self._cursor is not None:
            try:
                self._seen_at_cursor = {
                    e.event_id for e in self.events.find(
                        app_name=config.app_name,
                        channel_name=config.channel_name,
                        start_time=self._cursor,
                        until_time=self._cursor
                        + _dt.timedelta(milliseconds=1),
                        event_names=self._event_names())
                    if e.event_id is not None}
            except Exception:
                logger.debug(
                    "cursor-boundary pre-read failed; boundary events "
                    "may double-count once", exc_info=True)
        self._user_deltas: Dict[str, EntityDelta] = {}
        self._item_deltas: Dict[str, EntityDelta] = {}
        self._pending_events = 0   # fresh events since last fold (1/event)
        # ingest-trace ids of the pending events (resolved at tail time
        # via the tracer's event map): the fold tick's trace links them
        # so /traces.json ties an ingested event to the fold that
        # absorbed it (ISSUE 2 end-to-end causality)
        self._pending_trace_ids: Set[str] = set()
        # process-wide fold instruments (get-or-create: schedulers in
        # one process share the families, and both HTTP servers expose
        # them through the registry parent chain). Every family
        # carries a ``tenant`` label (ISSUE 17 cost attribution; ""
        # for an untenanted scheduler) — the child for THIS
        # scheduler's tenant is resolved once here, so the tick path
        # observes exactly as before, and a host's per-tenant SLO
        # engines read only their own tenant's series out of the
        # shared families.
        if self.tenant is not None:
            from predictionio_tpu.obs.tenantctx import register_tenant
            register_tenant(self.tenant)
        self._metric_tenant = self.tenant or ""
        reg = get_registry()
        self._h_tick = reg.histogram(
            "pio_fold_tick_seconds",
            "Wall time of a scheduler tick that ran a fold-in "
            "(tail read + touched-row solves + publish + swap)",
            labelnames=("tenant",)).labels(tenant=self._metric_tenant)
        self._c_fold_events = reg.counter(
            "pio_fold_events_total",
            "Fresh events absorbed by completed fold-ins",
            labelnames=("tenant",)).labels(tenant=self._metric_tenant)
        self._c_fold_h2d = reg.counter(
            "pio_fold_upload_bytes_total",
            "Host->device bytes uploaded by fold-in solves (the "
            "per-tick upload cost; ROADMAP open item)",
            labelnames=("tenant",)).labels(tenant=self._metric_tenant)
        self._c_tick_failures = reg.counter(
            "pio_fold_tick_failures_total",
            "Scheduler ticks that raised (tail read, solve, or publish "
            "failure); consecutive failures back off exponentially",
            labelnames=("tenant",)).labels(tenant=self._metric_tenant)
        self._c_fold_read_rows = reg.counter(
            "pio_fold_read_rows_total",
            "Training-data rows read by fold ticks, by read path "
            "(entity_filtered = O(touched) pushdown, full_scan = the "
            "whole corpus)", labelnames=("path", "tenant"))
        self._c_gate_rejects = reg.counter(
            "pio_guard_gate_rejects_total",
            "Fold publishes refused by the pre-swap quality gates "
            "(the live model kept serving)",
            labelnames=("tenant",)).labels(tenant=self._metric_tenant)
        self.gatekeeper = (QualityGatekeeper(config.gate_config, reg)
                           if config.gates else None)
        self.gate_rejects = 0
        # breaker over the event-store tail read (ISSUE 3)
        from predictionio_tpu.resilience import CircuitBreaker
        self._tail_breaker = CircuitBreaker(
            "scheduler_tail",
            failure_threshold=config.tail_breaker_failures,
            reset_timeout_s=config.tail_breaker_reset_s)
        self.consecutive_failures = 0
        self.last_error: Optional[str] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counters (mirrored onto the attached server's /stats.json)
        self.fold_in_count = 0
        self.events_folded = 0
        self.retrain_requested = False
        self.anchor_loss: Optional[float] = None
        self.last_loss: Optional[float] = None
        self.last_report: Optional[dict] = None
        # incident forensics (ISSUE 6): bundles capture the fold
        # lineage (cursor, counts, breaker state) at incident time
        from predictionio_tpu.obs.incidents import get_incidents
        get_incidents().register_provider("scheduler", self.stats)

    @staticmethod
    def _instance_cursor(instance) -> Optional[_dt.datetime]:
        """Resume point for a (re)attached scheduler: the lineage cursor
        of an online version, else the instance's training start."""
        from predictionio_tpu.data.event import parse_event_time
        from predictionio_tpu.online.registry import ONLINE_BATCH_TAG
        batch = getattr(instance, "batch", "") or ""
        if batch.startswith(ONLINE_BATCH_TAG + ":"):
            try:
                import json as _json
                lineage = _json.loads(batch[len(ONLINE_BATCH_TAG) + 1:])
                if lineage.get("cursor"):
                    return parse_event_time(lineage["cursor"])
            except (ValueError, KeyError):
                logger.warning("unparseable online lineage tag %r", batch)
        return getattr(instance, "start_time", None)

    # -- event-store tail ---------------------------------------------------
    def _event_names(self) -> Optional[List[str]]:
        if self.config.event_names is not None:
            return list(self.config.event_names)
        _, ds_params = self.engine_params.data_source_params
        names = getattr(ds_params, "event_names", None)
        if names is None:
            return None
        # $set rides along: a property-only update (new item metadata)
        # counts as freshness so the next fold re-derives filter metadata
        out = list(names)
        if "$set" not in out:
            out.append("$set")
        return out

    def poll_events(self) -> int:
        """Advance the tail: fold fresh events into the delta state.
        Returns the number of NEW events observed (each event counts
        once, however many entities it touches)."""
        cfg = self.config
        fresh = 0
        # breaker-gated tail: while the event store is down, ticks skip
        # the read entirely (CircuitOpenError propagates — the loop's
        # supervision waits for the probe window); after the reset
        # timeout one probe read is admitted and a success closes the
        # breaker. The iterator stays LAZY (a full 50k-event tick never
        # materializes twice); delta state commits only after the loop
        # completes, so a mid-iteration read failure is side-effect-free.
        self._tail_breaker.allow()
        new_users: Dict[str, EntityDelta] = {}
        new_items: Dict[str, EntityDelta] = {}
        new_trace_ids: Set[str] = set()
        miss_ids: List[str] = []
        max_t = self._cursor
        boundary: Set[str] = set()
        # only STORE work (find + iterator pulls) is attributed to the
        # breaker; a poisoned event that raises during delta processing
        # must land in the supervision loop's counted/escalating branch
        # (the breaker staying closed is what routes it there), not
        # masquerade as a store outage
        try:
            it = iter(self.events.find(
                app_name=cfg.app_name, channel_name=cfg.channel_name,
                start_time=self._cursor, event_names=self._event_names(),
                limit=cfg.tail_batch_limit))
        except Exception:
            self._tail_breaker.record_failure()
            raise
        while True:
            try:
                e = next(it)
            except StopIteration:
                break
            except Exception:
                self._tail_breaker.record_failure()
                raise
            try:
                if e.event_id is not None \
                        and e.event_id in self._seen_at_cursor:
                    continue  # boundary-instant re-read
                fresh += 1
                if e.event_id is not None:
                    tid = TRACER.trace_id_for_event(e.event_id)
                    if tid:
                        new_trace_ids.add(tid)
                    elif len(miss_ids) < 256:
                        # minted in another process (ISSUE 13): batch-
                        # resolved against fleet peers after the read
                        miss_ids.append(e.event_id)
                d = EntityDelta.from_event(e)
                # route by entity TYPE: a rate/buy/view event's subject
                # is a user and its target an item; a $set on an item
                # is an item-side delta even though it arrives in
                # entity_id
                if e.entity_id:
                    side = (new_items if e.entity_type == "item"
                            else new_users)
                    prev = side.get(e.entity_id)
                    side[e.entity_id] = d if prev is None \
                        else prev.merge(d)
                if e.target_entity_id and e.target_entity_type != "user":
                    prev = new_items.get(e.target_entity_id)
                    new_items[e.target_entity_id] = (
                        d if prev is None else prev.merge(d))
                if max_t is None or e.event_time > max_t:
                    max_t = e.event_time
                    boundary = {e.event_id} if e.event_id else set()
                elif e.event_time == max_t and e.event_id:
                    boundary.add(e.event_id)
            except Exception:
                # delta PROCESSING failed, but the store was answering:
                # close out the breaker interaction with the verdict
                # the read evidence supports (this also releases a
                # half-open probe slot allow() may hold — without it
                # the breaker would be stuck half-open forever), then
                # let the supervision loop's counted branch own the
                # failure (breaker closed routes it there).
                self._tail_breaker.record_success()
                raise
        self._tail_breaker.record_success()
        if miss_ids:
            # cross-process ingest traces (ISSUE 13): resolve the local
            # misses against fleet peers' event maps. Fail-soft and
            # peers-only — co-located servers share this process's
            # tracer, so a local miss means another pid or no trace at
            # all (directly-inserted training rows).
            try:
                from predictionio_tpu.obs import fleet
                new_trace_ids.update(
                    fleet.resolve_event_traces(miss_ids).values())
            except Exception:
                logger.debug("fleet event-trace resolution failed",
                             exc_info=True)
        with self._lock:
            # partition merge through the aggregator's monoid machinery
            self._user_deltas = merge_aggregations(
                [self._user_deltas, new_users])
            self._item_deltas = merge_aggregations(
                [self._item_deltas, new_items])
            self._pending_events += fresh
            # bounded: link fidelity degrades gracefully under a flood
            # (Trace.MAX_LINKS caps the fold trace's side anyway)
            room = 256 - len(self._pending_trace_ids)
            if room > 0:
                for tid in new_trace_ids:
                    self._pending_trace_ids.add(tid)
                    room -= 1
                    if room <= 0:
                        break
            if max_t is not None and (self._cursor is None
                                      or max_t > self._cursor):
                self._cursor = max_t
                self._seen_at_cursor = boundary
            elif max_t is not None:
                self._seen_at_cursor |= boundary
        return fresh

    # -- trigger logic ------------------------------------------------------
    def pending_deltas(self) -> int:
        """Fresh EVENTS accumulated since the last fold (each event
        counts once — max_deltas means events, as documented)."""
        with self._lock:
            return self._pending_events

    def should_fold(self, now: Optional[_dt.datetime] = None) -> bool:
        cfg = self.config
        with self._lock:
            if self._pending_events == 0:
                return False
            if self._pending_events >= cfg.max_deltas:
                return True
            firsts = [d.first_t for d in list(self._user_deltas.values())
                      + list(self._item_deltas.values())
                      if d.first_t is not None]
            if not firsts:
                return False
            now = now or utcnow()
            return (now - min(firsts)).total_seconds() >= cfg.max_staleness_s

    # -- the fold-in step ---------------------------------------------------
    def _read_training_data(self):
        """Full-scan read through the engine's own data source (the
        fallback path; kept zero-arg so tests and subclasses can stub
        it)."""
        data_source = self.engine.make_data_source(self.engine_params)
        return data_source.read_training()

    @staticmethod
    def _td_rows(td) -> Optional[int]:
        """Row count of a template's training payload (ratings for the
        recommendation shape, view + like events for similarproduct);
        None when the shape is unknown."""
        total = None
        for attr in ("ratings", "view_events", "like_events"):
            rows = getattr(td, attr, None)
            if rows is None:
                continue
            try:
                n = int(len(rows))
            except TypeError:
                continue
            total = n if total is None else total + n
        return total

    def _read_training(self, touched_users, touched_items):
        """The cost-model cutover: entity-filtered read when the data
        source supports it and the touched set is small, else the full
        scan. Returns ``(td, info)`` where info carries readPath/
        readRows for the trace, report and metrics."""
        cfg = self.config
        n_touched = len(touched_users) + len(touched_items)
        if cfg.filtered_reads and 0 < n_touched \
                <= cfg.filtered_read_max_entities:
            data_source = self.engine.make_data_source(self.engine_params)
            reader = getattr(data_source, "read_training_touched", None)
            if reader is not None:
                td = reader(touched_users, touched_items)
                return td, {"readPath": "entity_filtered",
                            "readRows": self._td_rows(td)}
        td = self._read_training_data()
        return td, {"readPath": "full_scan",
                    "readRows": self._td_rows(td)}

    def fold_in(self) -> dict:
        """Run one fold-in over the accumulated deltas and publish."""
        with self._lock:
            user_deltas = self._user_deltas
            item_deltas = self._item_deltas
            n_events = self._pending_events
            trace_ids = self._pending_trace_ids
            self._user_deltas = {}
            self._item_deltas = {}
            self._pending_events = 0
            self._pending_trace_ids = set()
        touched_users = list(user_deltas.keys())
        touched_items = list(item_deltas.keys())
        # two-way causality links: the fold trace names the ingest
        # traces it absorbs, and each ingest trace gains a link to the
        # fold (so either end of /traces.json walks to the other)
        tick_trace = TRACER.current_trace()
        if tick_trace is not None:
            for tid in trace_ids:
                tick_trace.link(tid)
                TRACER.link_completed(tid, tick_trace.trace_id)
        # this thread's uploads only: a concurrent serving cache miss
        # or /reload on another thread must not inflate the fold's cost
        h2d_before = jaxmon.thread_h2d_total()
        try:
            with TRACER.span("tail_data_read") as sp:
                td, read_info = self._read_training(touched_users,
                                                    touched_items)
                if sp is not None:
                    sp.attrs.update(read_info)
            new_models: List[Any] = []
            reports: List[dict] = []
            folded_any = False
            # the fold must replay the Preparator's data policy (dedup
            # mode, exclusion lists) even though it cannot run prepare()
            # itself (prepare rebuilds vocabularies, shuffling the
            # deployed dense indices)
            _, prep_params = self.engine_params.preparator_params
            for algo, model in zip(self.algorithms, self.models):
                fold = getattr(algo, "fold_in", None)
                if fold is None:
                    new_models.append(model)  # not online-capable: keep
                    continue
                with TRACER.span("fold_solve",
                                 touchedUsers=len(touched_users),
                                 touchedItems=len(touched_items)):
                    new_model, report = fold(
                        model, td, touched_users, touched_items,
                        preparator_params=prep_params)
                new_models.append(new_model)
                reports.append(report)
                folded_any = True
        except Exception:
            # transient failure (storage hiccup, solve error): restore
            # the popped deltas so the NEXT tick retries these events
            # instead of silently dropping them until a full retrain
            self._restore_deltas(user_deltas, item_deltas, n_events,
                                 trace_ids)
            raise
        report = {
            "foldIn": self.fold_in_count + 1,
            "touchedUsers": len(touched_users),
            "touchedItems": len(touched_items),
            "events": n_events,
            "algorithms": reports,
            # per-tick upload cost through instrumented paths — the
            # ROADMAP open item as a first-class number
            "h2dBytes": jaxmon.h2d_delta(h2d_before),
            # which read path the cost model chose, and what it cost
            **read_info,
        }
        # sharded online plane (ISSUE 12): the tick's table layout
        # rides the report + trace so MULTICHIP artifacts and
        # /traces.json can separate sharded from replicated ticks
        sharding = next((r.get("sharding") for r in reports
                         if r.get("sharding")), None)
        if sharding is not None:
            report["sharding"] = sharding
            TRACER.annotate(sharding=sharding)
        TRACER.annotate(h2dBytes=report["h2dBytes"])
        if read_info.get("readRows") is not None:
            self._c_fold_read_rows.labels(
                path=read_info["readPath"],
                tenant=self._metric_tenant).inc(read_info["readRows"])
        if not folded_any:
            logger.warning("no algorithm supports fold_in; deltas dropped")
            self.last_report = report
            return report
        if all(nm is old for nm, old in zip(new_models, self.models)):
            # degenerate tick (ISSUE 5 satellite): every online
            # algorithm no-opped (empty touched set after filtering,
            # all-zero ratings) — nothing to gate or publish, and the
            # consumed events are spent (refolding them would no-op
            # identically, so they are NOT restored)
            report["degenerate"] = True
            TRACER.annotate(degenerate=True)
            logger.info("fold tick was a clean no-op (%d event(s) "
                        "contributed nothing solvable)", n_events)
            self.last_report = report
            return report
        # pre-swap quality gates (ISSUE 5): the candidate set must pass
        # against the LIVE models before any publish is attempted
        guard_wall_s = sum(r.get("guardWallS") or 0.0 for r in reports)
        if self.gatekeeper is not None:
            g0 = _time.perf_counter()
            with TRACER.span("guard_gates") as sp:
                gate_report = self.gatekeeper.evaluate(
                    new_models, self.models, self.algorithms)
                if sp is not None:
                    sp.attrs["passed"] = gate_report["passed"]
                    sp.attrs["verdicts"] = {
                        g["gate"]: g["verdict"]
                        for g in gate_report["gates"]}
            guard_wall_s += _time.perf_counter() - g0
            report["gateReport"] = gate_report
        # the robustness tax, first-class: sentinel + gate wall per tick
        # (bench.py banks it as guard_overhead_ms)
        report["guardOverheadMs"] = round(guard_wall_s * 1000, 3)
        if self.gatekeeper is not None:
            TRACER.annotate(gatesPassed=gate_report["passed"])
            # flight record (ISSUE 6): every gate verdict is a
            # lifecycle transition — the pass that precedes a publish
            # as much as the reject that blocks one
            from predictionio_tpu.obs.flight import FLIGHT
            FLIGHT.record(
                "gate_verdict",
                model_version=getattr(self.instance, "id", None),
                passed=gate_report["passed"],
                verdicts={g["gate"]: g["verdict"]
                          for g in gate_report["gates"]},
                events=n_events)
            if not gate_report["passed"]:
                # the events are restored for the record, but the same
                # data folds the same way — the supervision loop's
                # escalation to a full retrain is the real exit
                self._restore_deltas(user_deltas, item_deltas, n_events,
                                     trace_ids)
                self._c_gate_rejects.inc()
                self.gate_rejects += 1
                if self.server is not None:
                    self.server.note_publish_failure()
                self.last_report = report
                # incident bundle (ISSUE 6): a refused publish is a
                # postmortem-worthy event — freeze the gate report,
                # the tick's trace and the fold lineage now
                from predictionio_tpu.obs.incidents import INCIDENTS
                tick = TRACER.current_trace()
                INCIDENTS.capture(
                    "gate_rejected",
                    "fold publish refused by quality gate(s): "
                    + ", ".join(g["gate"] for g in gate_report["gates"]
                                if g["verdict"] == "fail"),
                    context={"gateReport": gate_report,
                             "events": n_events,
                             "baseInstance": getattr(self.instance,
                                                     "id", None)},
                    trace_ids=(tick.trace_id,) if tick else ())
                raise GateRejected(gate_report)
        # drift gate: anchor = the first post-fold loss after (re)deploy
        losses = [r["loss"] for r in reports if r.get("loss") is not None]
        loss = max(losses) if losses else None
        report["loss"] = loss
        if loss is not None:
            self.last_loss = loss
            if self.anchor_loss is None:
                self.anchor_loss = loss
            elif loss > self.config.drift_ratio * self.anchor_loss:
                self.retrain_requested = True
                report["retrainRequested"] = True
                logger.warning(
                    "fold-in drift: loss %.5f > %.2f x anchor %.5f — "
                    "escalating to full retrain", loss,
                    self.config.drift_ratio, self.anchor_loss)
        report["anchorLoss"] = self.anchor_loss
        if report.get("retrainRequested") and self.on_retrain is not None:
            self.on_retrain(report)
        try:
            self._publish(new_models, report,
                          touched_entities={"user": touched_users,
                                            "item": touched_items})
        except Exception:
            # a publish failure (registry insert, in-process swap) means
            # the SERVED model never advanced: restore the deltas so the
            # next tick re-solves and re-publishes, and count nothing as
            # folded — /stats.json must not claim events the serving
            # path never absorbed. The re-solve is deterministic over
            # the re-read data, so the retry is idempotent. The attached
            # server keeps answering from the stale model and says so
            # (X-PIO-Model-Staleness-Ms) until a publish lands.
            self._restore_deltas(user_deltas, item_deltas, n_events,
                                 trace_ids)
            if self.server is not None:
                self.server.note_publish_failure()
            raise
        self.models = new_models
        self.fold_in_count += 1
        self.events_folded += n_events
        self._c_fold_events.inc(n_events)
        self._c_fold_h2d.inc(report["h2dBytes"])
        self.last_report = report
        return report

    def _restore_deltas(self, user_deltas, item_deltas, n_events: int,
                        trace_ids: Optional[Set[str]] = None):
        with self._lock:
            self._user_deltas = merge_aggregations(
                [user_deltas, self._user_deltas])
            self._item_deltas = merge_aggregations(
                [item_deltas, self._item_deltas])
            self._pending_events += n_events
            if trace_ids:
                self._pending_trace_ids |= trace_ids

    def _publish(self, models: Sequence[Any], report: dict,
                 touched_entities: Optional[dict] = None):
        """``touched_entities`` ({"user": ids, "item": ids}): the exact
        rows this fold tick re-solved — forwarded to the attached
        server's hot-swap so its result cache invalidates per entity
        instead of clearing (ISSUE 14); a cross-process /reload has no
        such lineage and clears the remote cache wholesale."""
        version = None
        if self.registry is not None:
            with self._lock:
                cursor = self._cursor
            meta = {"foldIn": report["foldIn"],
                    "events": report["events"]}
            if cursor is not None:
                # recorded so a RESTARTED follower resumes tailing from
                # the folded data's horizon, not from the publish
                # instant (events landing in the read->publish window
                # would otherwise be skipped forever). Conservative: a
                # boundary re-read refolds, which is idempotent.
                meta["cursor"] = cursor.isoformat()
            with TRACER.span("registry_publish"):
                version = self.registry.publish(
                    self.engine, self.engine_params, self.instance,
                    models, meta=meta)
            TRACER.annotate(version=version)
            report["publishedVersion"] = version
        from predictionio_tpu.obs.flight import FLIGHT
        FLIGHT.record("fold_publish", model_version=version,
                      events=report["events"],
                      foldIn=report["foldIn"],
                      readPath=report.get("readPath"))
        if self.server is not None:
            with TRACER.span("hot_swap", version=version or ""):
                self.server.swap_models(
                    models, version=version,
                    fold_in_events=report["events"],
                    touched_entities=touched_entities)
        if self.reload_url is not None:
            with TRACER.span("reload", url=self.reload_url):
                try:
                    # cross-process publish hop (ISSUE 13): the engine
                    # server adopts this fold tick's trace id, so its
                    # hot_swap flight record and load spans join the
                    # fleet-stitched story
                    from predictionio_tpu.obs.trace import \
                        trace_context_headers
                    req = urllib.request.Request(
                        self.reload_url, method="POST", data=b"",
                        headers=trace_context_headers())
                    urllib.request.urlopen(req, timeout=30).read()
                    report["reloaded"] = True
                except Exception as e:
                    report["reloaded"] = False
                    logger.error("POST %s failed: %s", self.reload_url, e)

    # -- tick / loop --------------------------------------------------------
    def tick(self, force: bool = False) -> Optional[dict]:
        """One scheduler step: tail, then fold if a threshold fired (or
        ``force``). Returns the fold-in report, or None if no fold ran.

        Each tick that observes fresh events or runs a fold records a
        ``fold_tick`` trace (tail read -> touched-row solves ->
        registry publish -> hot swap), linked to the ingest traces of
        the events it absorbed; idle ticks are discarded so the poll
        loop doesn't flood the trace ring."""
        if self.tenant is not None:
            from predictionio_tpu.utils.device_cache import tenant_scope
            with tenant_scope(self.tenant):
                return self._tick_inner(force)
        return self._tick_inner(force)

    def _tick_inner(self, force: bool = False) -> Optional[dict]:
        t0 = _time.perf_counter()
        with TRACER.trace("fold_tick") as tr:
            with TRACER.span("tail_read") as sp:
                fresh = self.poll_events()
                if sp is not None:
                    sp.attrs["freshEvents"] = fresh
            tr.discard = fresh == 0   # kept only if a fold runs below
            if self.retrain_requested and not force:
                return None  # drifted: wait for the full retrain
            if force or self.should_fold():
                if self.pending_deltas() == 0:
                    return None
                tr.discard = False
                report = self.fold_in()
                self._h_tick.observe(_time.perf_counter() - t0)
                tr.root.attrs["events"] = report["events"]
                return report
            return None

    def start(self) -> "DeltaTrainingScheduler":
        if self._thread is not None:
            return self
        self._stop.clear()
        # fleet member record (ISSUE 13): a following scheduler is a
        # fleet citizen — no HTTP port, but its liveness governs flight
        # GC and shows up in `pio fleet status` / incident bundles
        from predictionio_tpu.obs import fleet
        self._fleet_id = fleet.register_member("scheduler")

        def loop():
            # supervised ticks (ISSUE 3): consecutive failures back off
            # exponentially (a down event store is probed at the breaker
            # cadence, not hammered at poll cadence), and a persistently
            # failing fold loop escalates to a full retrain instead of
            # retrying on the same cadence forever
            from predictionio_tpu.resilience import CircuitOpenError
            cfg = self.config
            delay = cfg.poll_interval_s
            while True:
                if self._stop.wait(delay):
                    return
                try:
                    if self._tick_gate is not None:
                        with self._tick_gate.turn(self.tenant or ""):
                            self.tick()
                    else:
                        self.tick()
                    self.consecutive_failures = 0
                    self.last_error = None
                    delay = cfg.poll_interval_s
                except CircuitOpenError as e:
                    # the tail breaker fast-failing is the INTENDED
                    # degradation while the store is down — wait for
                    # the probe window; it must not count toward the
                    # retrain escalation (a retrain needs the store
                    # too, and a recovered store should resume folding)
                    self.last_error = str(e)
                    delay = min(max(e.retry_after_s,
                                    cfg.poll_interval_s),
                                cfg.failure_backoff_cap_s)
                    logger.warning(
                        "scheduler tail breaker open; next probe in "
                        "%.1fs", delay)
                except Exception as e:
                    self.last_error = str(e)
                    self._c_tick_failures.inc()
                    if self._tail_breaker.state != "closed":
                        # the failure tripped (or re-tripped, on a
                        # failed half-open probe) the tail breaker: the
                        # breaker owns store-read outages — wait for
                        # its probe cadence, and like the fast-fail
                        # path above do NOT count toward the retrain
                        # escalation. Everything else (solve, publish,
                        # poisoned-event processing) leaves the breaker
                        # closed — poll_events attributes only store
                        # work to it — so those failures always land in
                        # the counted, escalating branch below.
                        delay = max(cfg.poll_interval_s,
                                    min(cfg.tail_breaker_reset_s,
                                        cfg.failure_backoff_cap_s))
                        logger.warning(
                            "scheduler tail read failed and the "
                            "breaker is %s; next attempt in %.1fs",
                            self._tail_breaker.state, delay)
                        continue
                    self.consecutive_failures += 1
                    delay = min(
                        cfg.poll_interval_s
                        * (2 ** self.consecutive_failures),
                        cfg.failure_backoff_cap_s)
                    logger.exception(
                        "scheduler tick failed (%d consecutive)",
                        self.consecutive_failures)
                    if (self.consecutive_failures
                            >= cfg.max_tick_failures
                            and not self.retrain_requested):
                        self.retrain_requested = True
                        report = {
                            "retrainRequested": True,
                            "reason": "consecutive_tick_failures",
                            "failures": self.consecutive_failures,
                            "lastError": self.last_error,
                        }
                        logger.error(
                            "scheduler: %d consecutive tick failures — "
                            "escalating to full retrain",
                            self.consecutive_failures)
                        from predictionio_tpu.obs.flight import FLIGHT
                        FLIGHT.record(
                            "retrain_escalation",
                            failures=self.consecutive_failures,
                            lastError=self.last_error)
                        if self.on_retrain is not None:
                            try:
                                self.on_retrain(report)
                            except Exception:
                                logger.exception("on_retrain failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="pio-delta-scheduler")
        self._thread.start()
        return self

    def stop(self):
        from predictionio_tpu.obs import fleet
        fleet.deregister_member(getattr(self, "_fleet_id", None))
        self._fleet_id = None
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- canary feedback (ISSUE 5) ------------------------------------------
    def note_canary_decision(self, decision: dict):
        """The attached server's canary watchdog decided. On promote,
        pin the version as last-known-good in the registry (the durable
        rollback target). On rollback, the fold lineage has produced a
        bad-serving model the gates could not see: re-anchor on what is
        actually serving and escalate to a full retrain."""
        if decision.get("decision") == "promote":
            version = decision.get("candidateVersion")
            if self.registry is not None and version:
                try:
                    inst = self.instance
                    self.registry.pin_last_good(
                        inst.engine_id, inst.engine_version,
                        inst.engine_variant, version)
                except Exception:
                    logger.exception("last-good pin failed")
            return
        if decision.get("decision") == "rollback":
            if self.server is not None:
                self.models = list(self.server.models)
            self.retrain_requested = True
            version = decision.get("candidateVersion")
            if self.registry is not None and version:
                # make the verdict durable: the rejected version must
                # not stay newest-COMPLETED, or the next /reload or
                # restart would deploy it to 100% of traffic
                try:
                    self.registry.demote_version(version)
                except Exception:
                    logger.exception("demoting %s failed", version)
            logger.error(
                "canary rollback of %s (%s): scheduler re-anchored on "
                "the serving models and escalated to a full retrain",
                decision.get("candidateVersion"),
                decision.get("reason"))

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            pending = self._pending_events
        return {
            "foldIns": self.fold_in_count,
            "eventsFolded": self.events_folded,
            "pendingEvents": pending,
            "cursor": self._cursor.isoformat() if self._cursor else None,
            "anchorLoss": self.anchor_loss,
            "lastLoss": self.last_loss,
            "retrainRequested": self.retrain_requested,
            "consecutiveFailures": self.consecutive_failures,
            "lastError": self.last_error,
            "tailBreaker": self._tail_breaker.state,
            "gateRejects": self.gate_rejects,
        }


def attach_scheduler(server, config: SchedulerConfig,
                     registry=None, **kw) -> DeltaTrainingScheduler:
    """Build a scheduler bound to a LOADED in-process EngineServer: the
    engine, params, instance and live model set all come from the server,
    and every fold-in hot-swaps it atomically."""
    if not server.algorithms:
        raise RuntimeError("server has no engine loaded; call load() first")
    sched = DeltaTrainingScheduler(
        engine=server.engine, engine_params=server.engine_params,
        instance=server.engine_instance, algorithms=server.algorithms,
        models=server.models, config=config, server=server,
        registry=registry, **kw)
    # canary feedback loop (ISSUE 5): watchdog promotions pin the
    # last-known-good version; rollbacks re-anchor the fold lineage and
    # escalate to a full retrain
    server.on_canary_decision = sched.note_canary_decision
    return sched
