"""Fold-in kernels: solve only the touched factor rows of a deployed model.

The ALX observation (PAPERS.md, arxiv 2112.02194): the per-user least-
squares step of ALS — solve (V_S^T C V_S + lam*n*I) x = V_S^T C r with the
counterpart table FIXED — is exactly the bucketed batched-solve shape the
training sweep already runs, so absorbing fresh events costs one
mini-sweep over the touched entities instead of a full retrain. This
module reuses the whole training stack for that mini-sweep: the
ragged->fixed bucketing of ``ops/ratings.build_solve_plan``, the stacked
device upload of ``ops/als._upload_plan``, the single-dispatch scan sweep
``ops/als._solve_sweep`` and its backend-resolved solvers
(``ops/solve.spd_solve`` — LAPACK cholesky on CPU, the VMEM-resident CG
Pallas kernel on TPU).

Math parity with the training sweep is by construction — both paths call
the identical ``_solve_batch`` kernel:

  explicit  — ALS-WR: x = argmin sum_S (r - x.v)^2 + lam * n |x|^2
              (per-entity regularizer lam * n ratings, MLlib 1.3).
  implicit  — Hu-Koren: (G + V_S^T (C_S - I) V_S + lam*n*I) x = V_S^T C_S p
              with G = V^T V over the FULL counterpart table.

Device residency (the ALX keep-shards-on-device discipline): a tick
uploads the grown U/V tables at most once — the solve plans upload once
per side, both solve sides and every sweep read the tables where they
already live, and solved rows scatter on-device between sides. The
implicit Gram is carried alongside its table and updated by the rank-k
correction G += sum(v_new v_new^T - v_old v_old^T) over the scattered
rows (recomputed from the table on upload and every
``_GRAM_REFRESH_EVERY`` incremental ticks, bounding float drift).
With a ``resident_key``, the tick's final device tables stay resident
in ``utils/device_cache`` keyed by the published model's host arrays,
so the NEXT tick uploads only its touched-row solve plans — per-tick
``pio_fold_upload_bytes_total`` is O(touched), not O(model).

Exactness caveat: a folded row is the exact least-squares solution GIVEN
the current counterpart factors; counterpart rows not in the touched set
keep their deployed values, so the folded model is one Gauss-Seidel
half-step from the retrain fixed point, not the fixed point itself. The
scheduler's drift bound (fold-in loss vs anchor loss) decides when that
gap has grown enough to warrant a real retrain.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.guard.sentinels import (SweepSentinel, guard_enabled,
                                              host_max_norm)
from predictionio_tpu.ops.als import (ALSConfig, _gram, _gram_eig,
                                      ALSModel, _run_side, _upload_plan,
                                      default_compute_dtype,
                                      resolve_sweep_chunk)
from predictionio_tpu.ops.ratings import RatingsCOO, build_solve_plan
from predictionio_tpu.ops.solve import resolve_solver
from predictionio_tpu.parallel.mesh import MeshContext, current_mesh, \
    host_fetch
from predictionio_tpu.utils import device_cache


@dataclass(frozen=True)
class FoldInConfig:
    """Hyperparameters of the touched-row solves. Defaults mirror
    ``ops/als.ALSConfig`` so a fold-in against a model trained with
    default params reproduces the training math exactly."""
    lam: float = 0.01
    implicit_prefs: bool = False
    alpha: float = 1.0
    lambda_scaling: str = "nratings"   # 'nratings' (ALS-WR) | 'constant'
    solver: str = "auto"               # ops/solve.spd_solve methods
    compute_dtype: Optional[str] = None  # None = bf16 on TPU, f32 on CPU
    work_budget: int = 1 << 20
    sweep_chunk: int = 0
    # pow2 segment-length ladder (train defaults to 1.125): the fold
    # tick's K classes must be a SMALL, quickly-saturated set so
    # consecutive ticks re-dispatch compiled programs instead of
    # minting near-duplicate shapes (ISSUE 9 zero-recompile contract);
    # the extra padded gather work is noise at touched-row scale
    bucket_ratio: float = 2.0
    dual_solve: str = "auto"
    solver_iters: Optional[int] = None
    dual_iters_cap: Optional[int] = None
    # one sweep = user side then item side. 2 sweeps let a brand-new
    # (user, item) PAIR bootstrap: the first user-side solve sees only
    # zero rows for a brand-new item, so its solution is refined once the
    # item side has produced a real row.
    sweeps: int = 1
    # sharded online plane (ISSUE 12): 'model' keeps the factor
    # tables device-resident under a NamedSharding over the mesh model
    # axis for the whole tick — solves gather touched counterpart rows
    # cross-shard (GSPMD collectives over ICI), solved rows scatter
    # back to their owning shard on-device, and the publish patches
    # only the touched rows into the per-shard host mirrors. The
    # layout is normally inferred from the model's tables
    # (ShardedTable -> 'model'); the config field records intent and
    # lets parity harnesses force a layout.
    factor_sharding: str = "replicated"
    # numerical sentinels (ISSUE 5): after each side's solve, the
    # touched rows are checked on-device for finiteness and norm
    # explosion (> max(floor, ratio * incumbent max row norm)). A breach
    # rolls back to the last clean sweep's checkpointed device tables,
    # or — with no clean sweep — aborts the tick with NumericalFault so
    # the scheduler's delta-restore machinery requeues the events.
    # PIO_GUARD=off disables at runtime.
    sentinel: bool = True
    sentinel_norm_ratio: float = 1e3
    sentinel_norm_floor: float = 1e4


@dataclass
class FoldInStats:
    """What one fold-in call touched (exported by the serving counters)."""
    n_user_rows: int = 0
    n_item_rows: int = 0
    n_new_users: int = 0
    n_new_items: int = 0
    nnz_user_side: int = 0
    nnz_item_side: int = 0
    sweeps: int = 0
    wall_s: float = 0.0
    # ISSUE 12: the tick ran the model-sharded layout (tables resident
    # under a model-axis NamedSharding; publish patched host mirrors)
    sharded: bool = False
    # True when the tick reused device-resident tables from the previous
    # tick (no full-table upload happened)
    resident_hit: bool = False
    # ISSUE 5 guard outcomes: the tick was a clean no-op (nothing
    # solvable — empty touched set or all-zero ratings), or a sentinel
    # breach rolled the tables back to the last clean sweep
    degenerate: bool = False
    sentinel_rollback: bool = False
    # wall seconds spent in sentinel work (baseline norm + per-side row
    # checks, including the device sync each check forces — an upper
    # bound on the tax). Feeds bench.py's guard_overhead_ms.
    guard_wall_s: float = 0.0


#: incremental Gram updates tolerated before a full recompute from the
#: table — bounds accumulated float32 error across long tick chains
_GRAM_REFRESH_EVERY = 64


def _degenerate_counter():
    from predictionio_tpu.obs import get_registry
    return get_registry().counter(
        "pio_guard_fold_degenerate_total",
        "Fold ticks that no-opped cleanly (empty touched set after "
        "filtering, or all-zero ratings) instead of building an empty "
        "solve plan")


def _als_config(cfg: FoldInConfig, rank: int, solver: str) -> ALSConfig:
    return ALSConfig(
        rank=rank, iterations=1, lam=cfg.lam,
        implicit_prefs=cfg.implicit_prefs, alpha=cfg.alpha,
        lambda_scaling=cfg.lambda_scaling, solver=solver,
        compute_dtype=cfg.compute_dtype or default_compute_dtype(),
        work_budget=cfg.work_budget, sweep_chunk=cfg.sweep_chunk,
        bucket_ratio=cfg.bucket_ratio, dual_solve=cfg.dual_solve,
        solver_iters=cfg.solver_iters, dual_iters_cap=cfg.dual_iters_cap)


# -- small jitted helpers (resolved from the compile plane) -----------------
#
# ISSUE 9: the fold tick resolves its jitted helpers from the AOT
# registry's shared-jit surface instead of a module-local cache — one
# process-wide jit per key, visible in `pio status --telemetry` /
# /stats.json, and the idiom the JAX003/JAX005 lint rules recognize.

def _jitted(name: str, impl):
    from predictionio_tpu.compile.aot import shared_jit
    return shared_jit("fold." + name, impl)


#: scatter-target sentinel for bucket padding: far out of range for any
#: factor table, so `.at[dst].set(mode="drop")` discards the entry (a
#: negative pad would WRAP under jax indexing and corrupt a real row)
_DROP = np.int32(2**31 - 1)


def _scatter_impl(table, solved, src, dst):
    # padded dst entries carry _DROP (out of bounds) -> dropped
    return table.at[dst].set(solved[src], mode="drop")


def _scatter_gram_impl(table, gram, solved, src, dst):
    import jax.numpy as jnp
    n = table.shape[0]
    valid = dst < n                       # bucket padding -> False
    rows = jnp.where(valid[:, None], solved[src], 0.0)
    old = jnp.where(valid[:, None], table[jnp.minimum(dst, n - 1)], 0.0)
    return (table.at[dst].set(rows, mode="drop"),
            gram + rows.T @ rows - old.T @ old)


def _eigh_impl(G):
    import jax.numpy as jnp
    return jnp.linalg.eigh(G)


def _solver_gram(G, dual_auto: bool):
    """The solver-facing gram the sweep kernels expect: (G, w, q) when
    the eig-SMW dual route applies, else G alone. The eigendecomposition
    is rank x rank — recomputing it per solve from the carried G costs
    nothing next to re-deriving G from the full table."""
    if G is None:
        return None
    if dual_auto:
        w, q = _jitted("eigh", _eigh_impl)(G)
        return (G, w, q)
    return G


def _grown_dev(table, n_new: int):
    """Zero-append rows ON DEVICE so vocabulary growth never round-trips
    the table through the host."""
    grow = n_new - int(table.shape[0])
    if grow <= 0:
        return table
    import jax.numpy as jnp
    return jnp.pad(table, ((0, grow), (0, 0)))


def _record_h2d(nbytes: int):
    from predictionio_tpu.obs import jaxmon
    jaxmon.record_h2d(int(nbytes))


# -- sharded-layout helpers (ISSUE 12) --------------------------------------

def _take_rows_impl(table, idx):
    return table[idx]


def _sharded_jit(name: str, impl, mesh: MeshContext, out_shardings):
    """Per-mesh shared jit for the sharded tick's scatter/gather
    programs: the explicit ``out_shardings`` pin the updated table to
    its model-axis layout (GSPMD propagation alone may re-replicate a
    scatter output), and the AOT-adopt key includes the mesh so two
    meshes never share a latched sharding."""
    import jax
    from predictionio_tpu.compile.aot import get_aot
    key = (f"fold.{name}.sharded:{id(mesh.mesh)}:"
           f"{mesh.model_parallelism}")
    return get_aot().adopt(key, jax.jit(impl,
                                        out_shardings=out_shardings))


def _pad_pow2_idx(idx: np.ndarray) -> np.ndarray:
    """Pad a row-index vector to its compile-plane row bucket (floored
    at the touched-row floor so tiny ticks share ONE gather program —
    bare pow2 would mint classes 1/2/4/8 and recompile across steady
    ticks) by repeating its first entry (duplicate fetches are
    harmless)."""
    from predictionio_tpu.compile.buckets import bucket_rows
    n = int(idx.size)
    m = bucket_rows(n, floor=_TOUCHED_FLOOR)
    if m == n:
        return idx
    out = np.empty(m, dtype=np.int32)
    out[:n] = idx
    out[n:] = idx[0] if n else 0
    return out


def _fetch_rows(table_dev, idx: np.ndarray, mesh: MeshContext
                ) -> np.ndarray:
    """Device->host fetch of the touched rows only — the ONLY d2h a
    steady-state sharded tick pays (the publish patches these into the
    host shard mirrors; the table itself never crosses the link)."""
    from predictionio_tpu.obs import jaxmon
    if idx.size == 0:
        return np.zeros((0, table_dev.shape[1]), dtype=np.float32)
    padded = _pad_pow2_idx(np.asarray(idx, dtype=np.int32))
    take = _sharded_jit("take_rows", _take_rows_impl, mesh,
                        mesh.replicated())
    rows = np.asarray(host_fetch(take(table_dev, padded)),
                      dtype=np.float32)[:idx.size]
    jaxmon.record_d2h(rows.nbytes)
    return rows


def solve_rows(counter_factors: np.ndarray,
               owner_compact: np.ndarray,
               counter_idx: np.ndarray,
               values: np.ndarray,
               n_rows: int,
               cfg: FoldInConfig,
               mesh: Optional[MeshContext] = None) -> np.ndarray:
    """One-sided normal-equation solve for ``n_rows`` entities, host in /
    host out — the per-side-upload path (the counterpart table crosses
    the link on every call; ``fold_in_coo`` is the device-resident tick
    built from the same kernels). Kept as the reference implementation
    the parity tests compare against, and for ad-hoc callers.

    ``owner_compact`` [nnz] holds compacted 0..n_rows-1 owner ids,
    ``counter_idx``/``values`` the counterpart index and rating of each
    entry. Returns the solved [n_rows, rank] float32 rows; rows with no
    entries come back zero (callers keep the deployed row for those).
    """
    mesh = mesh or current_mesh()
    counter_factors = np.ascontiguousarray(counter_factors,
                                           dtype=np.float32)
    rank = counter_factors.shape[1]
    solver = resolve_solver(cfg.solver, mesh.n_devices)
    plan = build_solve_plan(
        np.asarray(owner_compact, dtype=np.int64),
        np.asarray(counter_idx, dtype=np.int32),
        np.asarray(values, dtype=np.float32),
        n_rows, work_budget=cfg.work_budget,
        batch_multiple=mesh.data_parallelism,
        bucket_ratio=cfg.bucket_ratio)
    if not plan.batches:
        return np.zeros((n_rows, rank), dtype=np.float32)
    chunk = resolve_sweep_chunk(cfg.sweep_chunk, mesh.n_devices)
    groups = _upload_plan(mesh, plan, chunk)
    # +1 dummy tail row: the scatter target for batch padding (rows = -1)
    out_dev = mesh.put_replicated(
        np.zeros((n_rows + 1, rank), dtype=np.float32))
    counter_dev = mesh.put_replicated(counter_factors)
    _record_h2d(counter_factors.nbytes)   # the per-side upload cost
    als_cfg = _als_config(cfg, rank, solver)
    gram = None
    if cfg.implicit_prefs:
        gram_of = _gram_eig if cfg.dual_solve == "auto" else _gram
        gram = gram_of(counter_dev)
    from predictionio_tpu.obs import costmon
    with costmon.executable(costmon.FOLD_SIDE):
        solved = costmon.device_timed(
            costmon.FOLD_SIDE, _run_side, groups, out_dev, counter_dev,
            als_cfg, gram)
    return np.asarray(host_fetch(solved)[:n_rows], dtype=np.float32)


def _grown_table(table: np.ndarray, n_new: int) -> np.ndarray:
    """Old rows keep their indices; appended rows start at zero (a zero
    factor row scores 0 everywhere — inert until its first solve)."""
    rank = table.shape[1]
    out = np.zeros((n_new, rank), dtype=np.float32)
    out[:table.shape[0]] = table
    return out


@dataclass
class _SidePrep:
    """One side's per-tick constants: the touched-row selection, solve
    plan and scatter targets are identical across sweeps (the satellite
    fix for the per-sweep np.isin recompute), so they are built — and
    their plan uploaded — exactly once per tick.

    Shape-bucketed (ISSUE 9): ``n_rows`` is the touched-row BUCKET (the
    solved-table height), ``src``/``dst`` are padded to their own pow2
    bucket with ``_DROP`` targets, and the plan's same-shape batch
    groups are padded to pow2 counts — so consecutive ticks whose
    touched sets differ in size (within a bucket) re-dispatch the
    exact programs of the previous tick: zero recompiles."""
    groups: tuple          # device-resident stacked plan groups
    src: np.ndarray        # rows of the solved [bucket+1] table to take
    dst: np.ndarray        # rows of the full table those land on
    dst_real: np.ndarray   # unpadded dst (sentinel checks, stats)
    n_rows: int            # touched-row bucket (solved height minus pad)
    nnz: int


#: touched-row / scatter-length bucket floor: small ticks share one
#: program class without inflating the solve beyond a few dozen rows
_TOUCHED_FLOOR = 16


def _pad_batch_rows(b, target: int):
    """Pad one batch's entity dim to ``target`` rows with the kernel's
    established padding convention (rows = -1 scatters to the dummy
    tail, mask = 0 solves the pure-regularizer system to x = 0)."""
    from predictionio_tpu.ops.ratings import SolveBatch
    B, K = b.shape
    if target <= B:
        return b
    pad = target - B
    return SolveBatch(
        rows=np.concatenate([b.rows,
                             np.full(pad, -1, dtype=b.rows.dtype)]),
        idx=np.vstack([b.idx, np.zeros((pad, K), dtype=b.idx.dtype)]),
        val=np.vstack([b.val, np.zeros((pad, K), dtype=b.val.dtype)]),
        mask=np.vstack([b.mask, np.zeros((pad, K), dtype=b.mask.dtype)]))


def _pad_plan_batches(plan, batch_multiple: int = 1):
    """Shape-stabilize a fold solve plan: pad every batch's entity dim
    B to its pow2 bucket (floored so tiny ticks share one class), then
    pad every same-shape batch GROUP to a pow2 count with fully inert
    batches — so ticks whose touched-count histograms differ (within
    buckets) re-dispatch byte-identical program shapes: zero
    recompiles. Fold-tick only — a train pays this (< 2x, trivially
    solved) padding nowhere."""
    from predictionio_tpu.compile.buckets import bucket_batch
    from predictionio_tpu.ops.ratings import SolveBatch, SolvePlan
    by_shape = {}
    dp = max(int(batch_multiple), 1)
    for b in plan.batches:
        target = max(bucket_batch(b.shape[0], floor=_TOUCHED_FLOOR), dp)
        # the stacked upload shards the entity dim over the mesh data
        # axis: the padded B must stay a MULTIPLE of it (a pow2 bucket
        # alone breaks non-pow2 axes, e.g. dp=3)
        target = ((target + dp - 1) // dp) * dp
        b = _pad_batch_rows(b, target)
        by_shape.setdefault(b.shape, []).append(b)
    out = []
    for shape in sorted(by_shape):
        bs = by_shape[shape]
        out.extend(bs)
        target = bucket_batch(len(bs))
        if target > len(bs):
            B, K = shape
            inert = SolveBatch(
                rows=np.full(B, -1, dtype=np.int32),
                idx=np.zeros((B, K), dtype=np.int32),
                val=np.zeros((B, K), dtype=np.float32),
                mask=np.zeros((B, K), dtype=np.float32))
            out.extend([inert] * (target - len(bs)))
    return SolvePlan(batches=out, n_entities=plan.n_entities,
                     nnz=plan.nnz)


def _prep_side(owner_idx: np.ndarray, counter_idx: np.ndarray,
               values: np.ndarray, touched: np.ndarray,
               cfg: FoldInConfig, mesh: MeshContext
               ) -> Optional[_SidePrep]:
    from predictionio_tpu.compile.buckets import bucket_rows
    if touched.size == 0:
        return None
    sel = np.isin(owner_idx, touched)
    nnz = int(np.count_nonzero(sel))
    if nnz == 0:
        return None
    compact = np.searchsorted(touched, owner_idx[sel])
    # touched-row bucket: the solved-table height (and so the sweep's
    # scatter-output shape) quantizes to pow2, so tick-to-tick touched
    # counts inside a bucket re-use every compiled program
    n_slot = bucket_rows(int(touched.size), floor=_TOUCHED_FLOOR)
    plan = build_solve_plan(
        np.asarray(compact, dtype=np.int64),
        np.asarray(counter_idx[sel], dtype=np.int32),
        np.asarray(values[sel], dtype=np.float32),
        n_slot, work_budget=cfg.work_budget,
        batch_multiple=mesh.data_parallelism,
        bucket_ratio=cfg.bucket_ratio)
    if not plan.batches:
        return None
    plan = _pad_plan_batches(plan, batch_multiple=mesh.data_parallelism)
    chunk = resolve_sweep_chunk(cfg.sweep_chunk, mesh.n_devices)
    groups = _upload_plan(mesh, plan, chunk)
    # only scatter rows that actually had data: a touched entity whose
    # entries all vanished (e.g. deleted events) keeps its deployed row
    # rather than being zeroed
    has_data = np.bincount(compact, minlength=touched.size) > 0
    src_real = np.nonzero(has_data)[0].astype(np.int32)
    dst_real = touched[has_data].astype(np.int32)
    # scatter-index bucket: padded entries point src at row 0 (any
    # valid row — their contribution is masked) and dst at _DROP (out
    # of bounds -> dropped by the scatter, excluded from the Gram)
    plen = bucket_rows(max(int(src_real.size), 1), floor=_TOUCHED_FLOOR)
    src = np.zeros(plen, dtype=np.int32)
    src[:src_real.size] = src_real
    dst = np.full(plen, _DROP, dtype=np.int32)
    dst[:dst_real.size] = dst_real
    return _SidePrep(groups=groups, src=src, dst=dst,
                     dst_real=dst_real, n_rows=n_slot, nnz=nnz)


def _solve_side(prep: _SidePrep, counter_dev, counter_gram, out_dev,
                out_gram, als_cfg: ALSConfig, cfg: FoldInConfig,
                mesh: MeshContext, rank: int, sharded: bool = False):
    """One side of one sweep, entirely on device: solve the touched rows
    against the resident counterpart table, scatter them into the
    resident owned table, and (implicit) apply the rank-k Gram
    correction for the rows that moved. Returns the updated
    (out_dev, out_gram).

    Sharded layout: the counterpart gathers and the scatter run
    against model-axis-sharded tables — GSPMD inserts the cross-shard
    row gathers (O(touched) rows over ICI, never a table gather), and
    the scatter's explicit ``out_shardings`` keeps the updated table
    on its owning shards (the ``.at[].set(mode="drop")`` OOB-sentinel
    padding convention is layout-independent)."""
    from predictionio_tpu.obs import costmon
    zeros = mesh.put_replicated(
        np.zeros((prep.n_rows + 1, rank), dtype=np.float32))
    with costmon.executable(costmon.FOLD_SIDE):
        # device-time attribution (ISSUE 11): the fold solve is the
        # other big device consumer next to serving — a sampled sync
        # here is what lets `pio_device_time_seconds_total` compare
        # fold_side against batch_predict honestly
        solved = costmon.device_timed(
            costmon.FOLD_SIDE, _run_side, prep.groups, zeros,
            counter_dev, als_cfg,
            _solver_gram(counter_gram, cfg.dual_solve == "auto"))
    if sharded:
        scatter = _sharded_jit("scatter", _scatter_impl, mesh,
                               mesh.model_sharded(2))
        scatter_gram = _sharded_jit(
            "scatter_gram", _scatter_gram_impl, mesh,
            (mesh.model_sharded(2), mesh.replicated()))
    else:
        scatter = _jitted("scatter", _scatter_impl)
        scatter_gram = _jitted("scatter_gram", _scatter_gram_impl)
    if out_gram is None:
        return scatter(out_dev, solved, prep.src, prep.dst), None
    return scatter_gram(out_dev, out_gram, solved, prep.src, prep.dst)


def fold_in_coo(als: ALSModel, coo: RatingsCOO,
                touched_users: Sequence[int],
                touched_items: Sequence[int],
                cfg: FoldInConfig,
                mesh: Optional[MeshContext] = None,
                resident_key: Optional[str] = None
                ) -> Tuple[ALSModel, FoldInStats]:
    """Fold fresh data into a trained model: re-solve only the touched
    user/item rows against ``coo`` (the CURRENT deduped dataset, whose
    touched rows/columns must be complete — the solve is least-squares
    over whatever it is given, so partial histories produce rows biased
    to the fresh slice).

    ``coo.n_users``/``coo.n_items`` may exceed the model's (grown
    vocabularies): new rows are appended zero-initialized and solved when
    touched, so existing dense indices — and the deployed factor rows
    behind them — never move.

    ``resident_key`` names a device-residency slot: when the passed
    model's host tables are the ones the previous tick published under
    the same key, the grown tables (and implicit Grams) are reused
    in-place on device and the tick uploads only its solve plans.
    """
    t0 = time.perf_counter()
    from predictionio_tpu.parallel.sharded_table import is_sharded, \
        layout_of
    sharded = is_sharded(als.user_factors)
    if sharded != is_sharded(als.item_factors):
        raise ValueError(
            "fold_in_coo needs both factor tables in the same layout; "
            f"got user={type(als.user_factors).__name__} "
            f"item={type(als.item_factors).__name__}")
    if mesh is None and sharded:
        # serve/fold threads must resolve the SAME mesh for a given
        # shard count (current_mesh is thread-local)
        from predictionio_tpu.parallel.mesh import model_mesh
        mesh = model_mesh(als.user_factors.n_shards)
    mesh = mesh or current_mesh()
    layout_token = layout_of(als.user_factors)
    rank = als.rank
    n_users = max(coo.n_users, als.n_users)
    n_items = max(coo.n_items, als.n_items)
    tu = np.unique(np.asarray(touched_users, dtype=np.int64))
    ti = np.unique(np.asarray(touched_items, dtype=np.int64))
    stats = FoldInStats(
        n_new_users=n_users - als.n_users,
        n_new_items=n_items - als.n_items)
    implicit = cfg.implicit_prefs

    # chaos opt-in (ISSUE 5): a `fold.ratings:corrupt=P` PIO_FAULTS
    # clause poisons this tick's data — the sentinel below must catch it
    from predictionio_tpu.resilience.faults import maybe_corrupt_array
    vals, vals_corrupted = maybe_corrupt_array("fold.ratings", coo.rating)
    if vals_corrupted:
        coo = RatingsCOO(coo.user_idx, coo.item_idx, vals,
                         coo.n_users, coo.n_items)

    # -- per-tick constants, hoisted out of the sweep loop ------------------
    solver = resolve_solver(cfg.solver, mesh.n_devices)
    als_cfg = _als_config(cfg, rank, solver)
    degenerate = (
        (tu.size == 0 and ti.size == 0)
        or coo.rating.size == 0
        # all-zero ratings: every solve would return x = 0 and ZERO the
        # deployed rows (explicit: zero targets; implicit: preference 0)
        or not np.any(coo.rating))
    prep_u = prep_i = None
    if not degenerate:
        prep_u = _prep_side(coo.user_idx, coo.item_idx, coo.rating, tu,
                            cfg, mesh)
        prep_i = _prep_side(coo.item_idx, coo.user_idx, coo.rating, ti,
                            cfg, mesh)
        degenerate = prep_u is None and prep_i is None
    if degenerate:
        # no-op tick (ISSUE 5 satellite): nothing solvable — return the
        # deployed model unchanged WITHOUT uploading tables or building
        # an empty solve plan, and make it countable
        _degenerate_counter().inc()
        stats.degenerate = True
        stats.wall_s = time.perf_counter() - t0
        return als, stats

    # -- tables onto the device (once per tick, or not at all) --------------
    # vocab shape-buckets (ISSUE 9): device tables live at pow2 row
    # buckets, so vocabulary growth INSIDE a bucket re-uses every traced
    # program (and, with residency, the device arrays themselves);
    # promotion to the next bucket is one predictable re-pad + compile
    from predictionio_tpu.compile.buckets import (bucket_rows,
                                                  bucket_rows_sharded)
    U_tab = V_tab = None
    if sharded:
        stats.sharded = True
        mp = mesh.model_parallelism
        U_tab, V_tab = als.user_factors, als.item_factors
        n_users_b = max(bucket_rows_sharded(n_users, mp),
                        U_tab.padded_rows)
        n_items_b = max(bucket_rows_sharded(n_items, mp),
                        V_tab.padded_rows)
        # bucket promotion: the one O(table) host reshuffle + upload,
        # paid per 2x vocabulary growth (steady-state ticks never
        # enter these branches)
        if n_users_b > U_tab.padded_rows:
            U_tab = U_tab.grown(als.n_users, n_users_b)
        if n_items_b > V_tab.padded_rows:
            V_tab = V_tab.grown(als.n_items, n_items_b)
    else:
        n_users_b = bucket_rows(n_users)
        n_items_b = bucket_rows(n_items)
    payload = device_cache.get_resident(
        resident_key, (als.user_factors, als.item_factors),
        sharding=layout_token) if resident_key else None
    if payload is not None and payload.get("mesh") is mesh \
            and payload.get("implicit") == implicit \
            and (not sharded
                 or (payload["U"].shape[0] == n_users_b
                     and payload["V"].shape[0] == n_items_b)):
        U_dev = payload["U"] if sharded \
            else _grown_dev(payload["U"], n_users_b)
        V_dev = payload["V"] if sharded \
            else _grown_dev(payload["V"], n_items_b)
        # appended zero rows contribute nothing to a Gram: carry it
        gram_u, gram_v = payload.get("GU"), payload.get("GV")
        incr = int(payload.get("incr", 0))
        stats.resident_hit = True
    elif sharded:
        # residency miss: the tables' own attached device handles are
        # the second-chance fast path (a just-trained or just-swapped
        # ShardedTable arrives with its arrays still resident); only a
        # genuinely cold table uploads — per-shard slices, budget-
        # checked at 1/N of the table
        U_dev = U_tab.device(mesh)
        V_dev = V_tab.device(mesh)
        gram_u = gram_v = None
        incr = 0
    else:
        U_host = _grown_table(als.user_factors, n_users_b)
        V_host = _grown_table(als.item_factors, n_items_b)
        # the enforced per-device budget (ISSUE 12): a replicated fold
        # costs each device the FULL table — refuse loudly instead of
        # silently overcommitting HBM (factor_sharding='model' is the
        # supported path past the budget)
        device_cache.check_table_budget(U_host.nbytes,
                                        table="fold user table")
        device_cache.check_table_budget(V_host.nbytes,
                                        table="fold item table")
        U_dev = mesh.put_replicated(U_host)
        V_dev = mesh.put_replicated(V_host)
        _record_h2d(U_host.nbytes + V_host.nbytes)
        gram_u = gram_v = None
        incr = 0
    if implicit and (gram_u is None or gram_v is None
                     or incr >= _GRAM_REFRESH_EVERY):
        gram_u = _gram(U_dev)
        gram_v = _gram(V_dev)
        incr = 0

    # -- sentinel (ISSUE 5): touched rows checked after each side -----------
    sentinel = None
    if cfg.sentinel and not solver.startswith("diag_") \
            and guard_enabled():
        g0 = time.perf_counter()
        # O(model) baseline scan only on the FIRST tick of a model
        # lineage: every published fold carries its norm forward (the
        # untouched rows' norms are covered by the previous baseline,
        # the touched rows by the checks that passed), so steady-state
        # ticks stay O(touched)
        baseline = getattr(als, "_pio_guard_norm", None)
        if baseline is None:
            if sharded:
                baseline = max(als.user_factors.max_row_norm(),
                               als.item_factors.max_row_norm())
            else:
                baseline = host_max_norm(als.user_factors,
                                         als.item_factors)
        sentinel = SweepSentinel(
            "fold_in", baseline,
            norm_ratio=cfg.sentinel_norm_ratio,
            norm_floor=cfg.sentinel_norm_floor)
        stats.guard_wall_s += time.perf_counter() - g0

    def _timed_check(table, idx, what):
        g0 = time.perf_counter()
        try:
            return sentinel.check_rows(table, idx, what)
        finally:
            stats.guard_wall_s += time.perf_counter() - g0

    sweeps = max(1, int(cfg.sweeps))
    ckpt = None        # device state after the last CLEAN sweep
    fault = None
    for _ in range(sweeps):
        if prep_u is not None:
            U_dev, gram_u = _solve_side(
                prep_u, V_dev, gram_v if implicit else None, U_dev,
                gram_u if implicit else None, als_cfg, cfg, mesh, rank,
                sharded=sharded)
            stats.n_user_rows += len(prep_u.dst_real)
            stats.nnz_user_side += prep_u.nnz
            if sentinel is not None:
                fault = _timed_check(U_dev, prep_u.dst_real,
                                     "user-side solve")
                if fault is not None:
                    break
        if prep_i is not None:
            V_dev, gram_v = _solve_side(
                prep_i, U_dev, gram_u if implicit else None, V_dev,
                gram_v if implicit else None, als_cfg, cfg, mesh, rank,
                sharded=sharded)
            stats.n_item_rows += len(prep_i.dst_real)
            stats.nnz_item_side += prep_i.nnz
            if sentinel is not None:
                fault = _timed_check(V_dev, prep_i.dst_real,
                                     "item-side solve")
                if fault is not None:
                    break
        stats.sweeps += 1
        # the scatter jits mint NEW arrays each sweep and nothing here
        # is donated, so a checkpoint is just references — the last-good
        # rollback costs no copy and no host round trip
        ckpt = (U_dev, V_dev, gram_u, gram_v)
    if fault is not None:
        if ckpt is None:
            # no clean sweep to fall back to: abort the tick; the
            # scheduler restores the popped deltas (PR 1) and the
            # supervision loop owns the retry/escalation policy
            raise fault
        U_dev, V_dev, gram_u, gram_v = ckpt
        stats.sentinel_rollback = True

    if sharded:
        # sharded publish (ISSUE 12): ONLY the touched rows cross the
        # device->host link; they are patched copy-on-write into the
        # per-shard host mirrors, and the tick's final device arrays
        # ride along as the resident fast path — the table as a whole
        # never moves, which is exactly what the over-budget scenario
        # asserts via pio_fold_upload_bytes_total
        idx_u = prep_u.dst_real if prep_u is not None \
            else np.zeros(0, dtype=np.int32)
        idx_v = prep_i.dst_real if prep_i is not None \
            else np.zeros(0, dtype=np.int32)
        rows_u = _fetch_rows(U_dev, idx_u, mesh)
        rows_v = _fetch_rows(V_dev, idx_v, mesh)
        # chaos opt-in: `fold.factors:corrupt=P` — poisons the patched
        # rows, so the host mirrors the gates probe see the corruption
        rows_u, cu = maybe_corrupt_array("fold.factors", rows_u)
        rows_v, cv = maybe_corrupt_array("fold.factors", rows_v)
        U_out = U_tab.with_rows(idx_u, rows_u, n_rows=n_users)
        V_out = V_tab.with_rows(idx_v, rows_v, n_rows=n_items)
        if not (cu or cv):
            U_out.attach_device(U_dev)
            V_out.attach_device(V_dev)
            if resident_key:
                device_cache.put_resident(
                    resident_key, (U_out, V_out),
                    {"U": U_dev, "V": V_dev, "GU": gram_u,
                     "GV": gram_v, "mesh": mesh, "implicit": implicit,
                     "incr": incr + 1},
                    sharding=layout_token)
        stats.wall_s = time.perf_counter() - t0
        out = ALSModel(user_factors=U_out, item_factors=V_out,
                       rank=rank)
        if sentinel is not None and not (cu or cv):
            out._pio_guard_norm = sentinel.observed_max
        return out, stats

    # slice the vocab-bucket padding back off: published models carry
    # exact-sized host tables (the padding is a device-residency shape
    # contract, not part of the model)
    U_host = np.asarray(host_fetch(U_dev)[:n_users], dtype=np.float32)
    V_host = np.asarray(host_fetch(V_dev)[:n_items], dtype=np.float32)
    # chaos opt-in: `fold.factors:corrupt=P` simulates a blow-up that
    # slipped past the sweep sentinel — the pre-swap gates' job
    U_host, cu = maybe_corrupt_array("fold.factors", U_host)
    V_host, cv = maybe_corrupt_array("fold.factors", V_host)
    if resident_key and not (cu or cv):
        # (a corrupted tick must not key the clean device tables under
        # the poisoned host arrays — skip residency so the next tick
        # re-uploads from whatever model is actually deployed)
        device_cache.put_resident(
            resident_key, (U_host, V_host),
            {"U": U_dev, "V": V_dev, "GU": gram_u, "GV": gram_v,
             "mesh": mesh, "implicit": implicit, "incr": incr + 1},
            sharding=layout_token)
    stats.wall_s = time.perf_counter() - t0
    out = ALSModel(user_factors=U_host, item_factors=V_host, rank=rank)
    if sentinel is not None and not (cu or cv):
        out._pio_guard_norm = sentinel.observed_max
    return out, stats
