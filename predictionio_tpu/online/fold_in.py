"""Fold-in kernels: solve only the touched factor rows of a deployed model.

The ALX observation (PAPERS.md, arxiv 2112.02194): the per-user least-
squares step of ALS — solve (V_S^T C V_S + lam*n*I) x = V_S^T C r with the
counterpart table FIXED — is exactly the bucketed batched-solve shape the
training sweep already runs, so absorbing fresh events costs one
mini-sweep over the touched entities instead of a full retrain. This
module reuses the whole training stack for that mini-sweep: the
ragged->fixed bucketing of ``ops/ratings.build_solve_plan``, the stacked
device upload of ``ops/als._upload_plan``, the single-dispatch scan sweep
``ops/als._solve_sweep`` and its backend-resolved solvers
(``ops/solve.spd_solve`` — LAPACK cholesky on CPU, the VMEM-resident CG
Pallas kernel on TPU).

Math parity with the training sweep is by construction — both paths call
the identical ``_solve_batch`` kernel:

  explicit  — ALS-WR: x = argmin sum_S (r - x.v)^2 + lam * n |x|^2
              (per-entity regularizer lam * n ratings, MLlib 1.3).
  implicit  — Hu-Koren: (G + V_S^T (C_S - I) V_S + lam*n*I) x = V_S^T C_S p
              with G = V^T V over the FULL counterpart table, computed once
              per one-sided solve (the eig-SMW dual route applies
              unchanged). Each side's solve within a sweep reads a
              counterpart table the PREVIOUS side just updated, so the
              Gram — and the counterpart upload — are per-solve costs by
              necessity, not caching misses; keeping the carried tables
              device-resident across sides is the noted future
              optimization for tunnel-latency deployments.

Exactness caveat: a folded row is the exact least-squares solution GIVEN
the current counterpart factors; counterpart rows not in the touched set
keep their deployed values, so the folded model is one Gauss-Seidel
half-step from the retrain fixed point, not the fixed point itself. The
scheduler's drift bound (fold-in loss vs anchor loss) decides when that
gap has grown enough to warrant a real retrain.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.ops.als import (ALSConfig, ALSModel, _gram, _gram_eig,
                                      _run_side, _upload_plan,
                                      default_compute_dtype,
                                      resolve_sweep_chunk)
from predictionio_tpu.ops.ratings import RatingsCOO, build_solve_plan
from predictionio_tpu.ops.solve import resolve_solver
from predictionio_tpu.parallel.mesh import MeshContext, current_mesh, \
    host_fetch


@dataclass(frozen=True)
class FoldInConfig:
    """Hyperparameters of the touched-row solves. Defaults mirror
    ``ops/als.ALSConfig`` so a fold-in against a model trained with
    default params reproduces the training math exactly."""
    lam: float = 0.01
    implicit_prefs: bool = False
    alpha: float = 1.0
    lambda_scaling: str = "nratings"   # 'nratings' (ALS-WR) | 'constant'
    solver: str = "auto"               # ops/solve.spd_solve methods
    compute_dtype: Optional[str] = None  # None = bf16 on TPU, f32 on CPU
    work_budget: int = 1 << 20
    sweep_chunk: int = 0
    bucket_ratio: float = 1.125
    dual_solve: str = "auto"
    solver_iters: Optional[int] = None
    dual_iters_cap: Optional[int] = None
    # one sweep = user side then item side. 2 sweeps let a brand-new
    # (user, item) PAIR bootstrap: the first user-side solve sees only
    # zero rows for a brand-new item, so its solution is refined once the
    # item side has produced a real row.
    sweeps: int = 1


@dataclass
class FoldInStats:
    """What one fold-in call touched (exported by the serving counters)."""
    n_user_rows: int = 0
    n_item_rows: int = 0
    n_new_users: int = 0
    n_new_items: int = 0
    nnz_user_side: int = 0
    nnz_item_side: int = 0
    sweeps: int = 0
    wall_s: float = 0.0


def _als_config(cfg: FoldInConfig, rank: int, solver: str) -> ALSConfig:
    return ALSConfig(
        rank=rank, iterations=1, lam=cfg.lam,
        implicit_prefs=cfg.implicit_prefs, alpha=cfg.alpha,
        lambda_scaling=cfg.lambda_scaling, solver=solver,
        compute_dtype=cfg.compute_dtype or default_compute_dtype(),
        work_budget=cfg.work_budget, sweep_chunk=cfg.sweep_chunk,
        bucket_ratio=cfg.bucket_ratio, dual_solve=cfg.dual_solve,
        solver_iters=cfg.solver_iters, dual_iters_cap=cfg.dual_iters_cap)


def solve_rows(counter_factors: np.ndarray,
               owner_compact: np.ndarray,
               counter_idx: np.ndarray,
               values: np.ndarray,
               n_rows: int,
               cfg: FoldInConfig,
               mesh: Optional[MeshContext] = None) -> np.ndarray:
    """One-sided normal-equation solve for ``n_rows`` entities.

    ``owner_compact`` [nnz] holds compacted 0..n_rows-1 owner ids,
    ``counter_idx``/``values`` the counterpart index and rating of each
    entry. Returns the solved [n_rows, rank] float32 rows; rows with no
    entries come back zero (callers keep the deployed row for those).

    The whole call is the training half-sweep in miniature: bucketed
    plan -> stacked upload -> one scan-sweep dispatch -> host fetch.
    """
    mesh = mesh or current_mesh()
    counter_factors = np.ascontiguousarray(counter_factors,
                                           dtype=np.float32)
    rank = counter_factors.shape[1]
    solver = resolve_solver(cfg.solver, mesh.n_devices)
    plan = build_solve_plan(
        np.asarray(owner_compact, dtype=np.int64),
        np.asarray(counter_idx, dtype=np.int32),
        np.asarray(values, dtype=np.float32),
        n_rows, work_budget=cfg.work_budget,
        batch_multiple=mesh.data_parallelism,
        bucket_ratio=cfg.bucket_ratio)
    if not plan.batches:
        return np.zeros((n_rows, rank), dtype=np.float32)
    chunk = resolve_sweep_chunk(cfg.sweep_chunk, mesh.n_devices)
    groups = _upload_plan(mesh, plan, chunk)
    # +1 dummy tail row: the scatter target for batch padding (rows = -1)
    out_dev = mesh.put_replicated(
        np.zeros((n_rows + 1, rank), dtype=np.float32))
    counter_dev = mesh.put_replicated(counter_factors)
    als_cfg = _als_config(cfg, rank, solver)
    gram = None
    if cfg.implicit_prefs:
        gram_of = _gram_eig if cfg.dual_solve == "auto" else _gram
        gram = gram_of(counter_dev)
    solved = _run_side(groups, out_dev, counter_dev, als_cfg, gram)
    return np.asarray(host_fetch(solved)[:n_rows], dtype=np.float32)


def _grown_table(table: np.ndarray, n_new: int) -> np.ndarray:
    """Old rows keep their indices; appended rows start at zero (a zero
    factor row scores 0 everywhere — inert until its first solve)."""
    rank = table.shape[1]
    out = np.zeros((n_new, rank), dtype=np.float32)
    out[:table.shape[0]] = table
    return out


def _side(owner_idx: np.ndarray, counter_idx: np.ndarray,
          values: np.ndarray, touched: np.ndarray,
          counter_factors: np.ndarray, out_table: np.ndarray,
          cfg: FoldInConfig, mesh: Optional[MeshContext]) -> Tuple[int, int]:
    """Solve the ``touched`` rows of one side in place in ``out_table``.
    Returns (rows_solved, nnz_consumed)."""
    if touched.size == 0:
        return 0, 0
    sel = np.isin(owner_idx, touched)
    nnz = int(np.count_nonzero(sel))
    if nnz == 0:
        return 0, 0
    compact = np.searchsorted(touched, owner_idx[sel])
    solved = solve_rows(counter_factors, compact, counter_idx[sel],
                        values[sel], touched.size, cfg, mesh)
    # only scatter rows that actually had data: a touched entity whose
    # entries all vanished (e.g. deleted events) keeps its deployed row
    # rather than being zeroed
    has_data = np.bincount(compact, minlength=touched.size) > 0
    out_table[touched[has_data]] = solved[has_data]
    return int(np.count_nonzero(has_data)), nnz


def fold_in_coo(als: ALSModel, coo: RatingsCOO,
                touched_users: Sequence[int],
                touched_items: Sequence[int],
                cfg: FoldInConfig,
                mesh: Optional[MeshContext] = None
                ) -> Tuple[ALSModel, FoldInStats]:
    """Fold fresh data into a trained model: re-solve only the touched
    user/item rows against ``coo`` (the CURRENT deduped dataset, whose
    touched rows/columns must be complete — the solve is least-squares
    over whatever it is given, so partial histories produce rows biased
    to the fresh slice).

    ``coo.n_users``/``coo.n_items`` may exceed the model's (grown
    vocabularies): new rows are appended zero-initialized and solved when
    touched, so existing dense indices — and the deployed factor rows
    behind them — never move.
    """
    t0 = time.perf_counter()
    rank = als.rank
    n_users = max(coo.n_users, als.n_users)
    n_items = max(coo.n_items, als.n_items)
    U = _grown_table(als.user_factors, n_users)
    V = _grown_table(als.item_factors, n_items)
    tu = np.unique(np.asarray(touched_users, dtype=np.int64))
    ti = np.unique(np.asarray(touched_items, dtype=np.int64))
    stats = FoldInStats(
        n_new_users=n_users - als.n_users,
        n_new_items=n_items - als.n_items)
    sweeps = max(1, int(cfg.sweeps))
    for _ in range(sweeps):
        nu, zu = _side(coo.user_idx, coo.item_idx, coo.rating, tu, V, U,
                       cfg, mesh)
        ni, zi = _side(coo.item_idx, coo.user_idx, coo.rating, ti, U, V,
                       cfg, mesh)
        stats.n_user_rows += nu
        stats.n_item_rows += ni
        stats.nnz_user_side += zu
        stats.nnz_item_side += zi
        stats.sweeps += 1
    stats.wall_s = time.perf_counter() - t0
    return ALSModel(user_factors=U, item_factors=V, rank=rank), stats
