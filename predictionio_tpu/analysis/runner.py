"""Lint orchestration: parse -> rules -> baseline -> report.

``run_lint()`` is the one entry point the CLI, the tier-1 gate test and
the fixture suite all share; rule selection and root/baseline paths are
parameters so fixtures lint a directory of snippets with no baseline
while CI lints ``predictionio_tpu/`` under ``conf/lint_baseline.json``.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from predictionio_tpu.analysis import rules_cost, rules_jax, rules_locks
from predictionio_tpu.analysis.baseline import (BaselineEntry,
                                                apply_baseline,
                                                load_baseline)
from predictionio_tpu.analysis.core import (Finding, RepoModel,
                                            number_occurrences)

#: every rule's checker, in reporting order
CHECKERS: Sequence[Callable[[RepoModel], List[Finding]]] = (
    rules_locks.check_lock001,
    rules_locks.check_lock002,
    rules_locks.check_lock003,
    rules_jax.check_jax001,
    rules_jax.check_jax002,
    rules_jax.check_jax003,
    rules_jax.check_jax004,
    rules_jax.check_jax005,
    rules_jax.check_jax006,
    rules_cost.check_cost001,
    rules_cost.check_cost002,
    rules_cost.check_cost003,
)


def default_root() -> str:
    """The package directory itself — the analyzer's repo-run target."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_baseline_path() -> str:
    repo = os.path.dirname(default_root())
    return os.path.join(repo, "conf", "lint_baseline.json")


@dataclass
class LintReport:
    findings: List[Finding]           # all, pre-baseline
    new: List[Finding]
    suppressed: List[Finding]
    stale: List[str]
    files: int
    elapsed_s: float
    parse_errors: List = field(default_factory=list)
    baseline_path: Optional[str] = None

    @property
    def ok(self) -> bool:
        # stale entries fail too: the CI gates (tier-1, lint_smoke.sh)
        # reject them, so a local `pio lint` must agree — a fixed
        # finding's baseline entry has to be deleted, not left to rot
        return not self.new and not self.parse_errors \
            and not self.stale

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "files": self.files,
            "elapsedS": round(self.elapsed_s, 3),
            "findings": [f.to_dict() for f in self.new],
            "suppressed": len(self.suppressed),
            "staleBaselineEntries": sorted(self.stale),
            "parseErrors": [{"path": p, "error": e}
                            for p, e in self.parse_errors],
            "baseline": self.baseline_path,
        }

    def render(self) -> str:
        lines = []
        for f in sorted(self.new, key=lambda f: (f.path, f.line)):
            lines.append(f"{f.path}:{f.line}: {f.rule_id} "
                         f"[{f.symbol or '<module>'}] {f.message}")
        for p, e in self.parse_errors:
            lines.append(f"{p}: PARSE ERROR {e}")
        stale_part = ""
        if self.stale:
            plural = "y" if len(self.stale) == 1 else "ies"
            stale_part = f", {len(self.stale)} STALE baseline entr{plural}"
        lines.append(
            f"pio lint: {len(self.new)} new finding(s), "
            f"{len(self.suppressed)} suppressed by baseline{stale_part} "
            f"({self.files} files, {self.elapsed_s:.1f}s)")
        if self.stale:
            for fp in sorted(self.stale):
                lines.append(f"  stale (no longer fires — remove from "
                             f"baseline): {fp}")
        return "\n".join(lines)


def run_lint(root: Optional[str] = None,
             baseline_path: Optional[str] = None,
             base: Optional[str] = None,
             use_baseline: bool = True) -> LintReport:
    t0 = time.perf_counter()
    root = root or default_root()
    repo = RepoModel(root, base=base)
    findings: List[Finding] = []
    for check in CHECKERS:
        findings.extend(check(repo))
    number_occurrences(findings)
    entries: List[BaselineEntry] = []
    bpath = None
    if use_baseline:
        bpath = baseline_path or default_baseline_path()
        entries = load_baseline(bpath)
    new, suppressed, stale = apply_baseline(findings, entries)
    return LintReport(findings=findings, new=new, suppressed=suppressed,
                      stale=stale, files=len(repo.modules),
                      elapsed_s=time.perf_counter() - t0,
                      parse_errors=repo.parse_errors,
                      baseline_path=bpath)


def main(argv: Optional[List[str]] = None) -> int:
    """``pio lint`` entry point (tools/cli.py delegates here)."""
    import argparse
    p = argparse.ArgumentParser(
        prog="pio lint",
        description="Static concurrency + JAX hot-path analyzer. "
                    "Exit 0 = zero findings outside the baseline.")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout (CI mode)")
    p.add_argument("--root", default=None,
                   help="directory to analyze (default: the "
                        "predictionio_tpu package)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: conf/lint_baseline"
                        ".json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, suppressing nothing")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to the current finding "
                        "set (new entries get a TODO justification "
                        "you must edit before committing)")
    args = p.parse_args(argv)

    report = run_lint(root=args.root, baseline_path=args.baseline,
                      use_baseline=not args.no_baseline)
    if args.update_baseline:
        from predictionio_tpu.analysis.baseline import write_baseline
        bpath = args.baseline or default_baseline_path()
        existing = load_baseline(bpath)
        todo = write_baseline(bpath, report.findings, existing)
        print(f"wrote {bpath}: {len(report.findings)} entr"
              f"{'y' if len(report.findings) == 1 else 'ies'}"
              + (f", {todo} needing a justification (search for "
                 f"'TODO')" if todo else ""))
        return 1 if todo else 0
    if args.json:
        print(json.dumps(report.to_dict(), indent=1))
    else:
        print(report.render())
    return 0 if report.ok else 1
