"""JAX* rules: hot-path hygiene for serving/fold code.

"Hot zone" = modules whose path has a ``serving``/``ops``/``guard``
segment or is ``fold_in.py`` — the code that runs per query or per fold
tick, where one stray ``.item()`` stalls the dispatch pipeline and one
uncached ``jax.jit`` recompiles for minutes (BENCH_r01: warmup 231 s vs
3.9 ms steady-state).

Device-value taint is per-function and syntactic: a local assigned from
a ``jnp.*``/``jax.*`` call or a known-jitted callable is device-
resident; host conversions of tainted names (or any ``.item()`` in the
zone) are findings.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from predictionio_tpu.analysis.core import (Finding, FunctionInfo,
                                            RepoModel, attr_chain,
                                            jit_donated_positions,
                                            register_rule)

JAX001 = register_rule(
    "JAX001", "implicit host sync on hot path",
    ".item(), float()/int()/bool(), or np.asarray()/np.array() applied "
    "to a device value inside serving/fold code — each one blocks on "
    "the async dispatch queue and forces a device-to-host transfer per "
    "call. Batch the readback or keep the value on device.")

JAX002 = register_rule(
    "JAX002", "jit of closure (recompile hazard)",
    "jax.jit applied to a locally-defined function that captures "
    "enclosing variables. Every call of the enclosing function builds "
    "a NEW closure; jit's cache keys on function identity, so each "
    "build recompiles unless the wrapper is cached by the enclosing "
    "scope. Cache the jitted callable (module dict / lru_cache) keyed "
    "by the captured statics.")

JAX003 = register_rule(
    "JAX003", "jit constructed per call (uncached)",
    "jax.jit(...) executed inside a function body without a visible "
    "cache (no lru_cache decorator, result not stored in a cache "
    "container). On a per-request or per-tick path this recompiles "
    "every invocation — minutes of XLA time per BENCH_r01.")

JAX004 = register_rule(
    "JAX004", "donated buffer reused after dispatch",
    "An argument at a donate_argnums position is used again after the "
    "jitted call. Donation invalidates the buffer; reuse returns "
    "garbage or raises depending on backend (and silently breaks when "
    "donation is re-enabled on TPU).")

JAX005 = register_rule(
    "JAX005", "serve-zone jit dispatch bypasses compile plane",
    "A module-level jitted callable is dispatched directly from "
    "serve-zone code (serving/guard modules, fold_in.py, the serve "
    "kernels ops/{als,similarity,topk}.py) by a function that never "
    "touches the compile plane (predictionio_tpu/compile: AOT registry "
    "dispatch, shared_jit, warm). Direct dispatch re-traces per shape "
    "and pays a full XLA compile whenever a vocabulary/batch/k size "
    "moves; plane dispatch gets shape-bucketed, deploy-warmed AOT "
    "executables (ISSUE 9).")

JAX006 = register_rule(
    "JAX006", "host sync in the pipelined serve zone",
    "A host-synchronizing call — jax.block_until_ready(), .item(), or "
    "np.asarray()/np.array() on a device value — inside the pipelined "
    "serving executor's modules (predictionio_tpu/serving/). ISSUE 14 "
    "keeps the serve path's formation/dispatch/serialization stages "
    "overlapped with device compute by deferring every readback to "
    "the completion stage's finish() closures (ops-layer *_begin "
    "kernels); one stray sync in serving/ code re-serializes the "
    "pipeline and silently gives back the overlap. The costmon "
    "1-in-N sampled sync lives in obs/costmon.py, outside this zone "
    "by construction; result readbacks belong in the ops-layer "
    "finish() callables, not in serving/ modules. The ONE sanctioned "
    "serve d2h site is ops/readback.py (ISSUE 19): begin_fetch() "
    "initiates copy_to_host_async at dispatch and its wait() closure "
    "attributes every second and byte — serving/ code that wants "
    "readback timing samples readback.thread_wait_s() deltas instead "
    "of touching a device handle.")

_HOT_SEGMENTS = {"serving", "ops", "guard"}


def in_hot_zone(relpath: str) -> bool:
    parts = relpath.split("/")
    return bool(_HOT_SEGMENTS.intersection(parts[:-1])) \
        or parts[-1] == "fold_in.py"


_DEVICE_ROOTS = {"jnp", "jax", "lax"}
_HOST_CASTS = {"float", "int", "bool"}
_NP_CONVERTERS = {("np", "asarray"), ("np", "array"),
                  ("numpy", "asarray"), ("numpy", "array"),
                  ("onp", "asarray"), ("onp", "array")}


def _tainted_names(fn: FunctionInfo) -> Set[str]:
    """Locals assigned from a jax/jnp call or a known-jitted callable
    anywhere in the function (flow-insensitive: assignment order inside
    branches isn't tracked, the zone restriction carries the signal)."""
    jitted = set(fn.module.jitted)
    for ev in fn.events:
        if ev.kind == "store" and ev.chain and ev.chain[-1] == "jit":
            jitted.add(ev.name)
    out: Set[str] = set()
    for ev in fn.events:
        if ev.kind != "store" or not ev.chain:
            continue
        root = ev.chain[0]
        if root in _DEVICE_ROOTS or root in jitted:
            out.add(ev.name)
    return out


def check_jax001(repo: RepoModel) -> List[Finding]:
    findings: List[Finding] = []
    for key, fn in repo.functions.items():
        if not in_hot_zone(fn.module.relpath):
            continue
        tainted = _tainted_names(fn)
        for ev in fn.events:
            if ev.kind != "call":
                continue
            chain, node = ev.chain, ev.node
            if chain[-1] == "item" and len(chain) >= 2:
                findings.append(Finding(
                    JAX001.id, fn.module.relpath, ev.line, fn.qualname,
                    f"item:{chain[-2]}",
                    f"{'.'.join(chain)}() forces a device sync per "
                    f"call"))
                continue
            arg0 = _first_arg_name(node)
            if arg0 is None or arg0 not in tainted:
                continue
            if len(chain) == 1 and chain[0] in _HOST_CASTS:
                findings.append(Finding(
                    JAX001.id, fn.module.relpath, ev.line, fn.qualname,
                    f"{chain[0]}:{arg0}",
                    f"{chain[0]}({arg0}) converts a device value on "
                    f"the host (implicit transfer + sync)"))
            elif tuple(chain[-2:]) in _NP_CONVERTERS:
                findings.append(Finding(
                    JAX001.id, fn.module.relpath, ev.line, fn.qualname,
                    f"asarray:{arg0}",
                    f"{'.'.join(chain)}({arg0}) pulls a device value "
                    f"to host memory (implicit transfer + sync)"))
    return findings


def _first_arg_name(node: Optional[ast.AST]) -> Optional[str]:
    if not isinstance(node, ast.Call) or not node.args:
        return None
    a = node.args[0]
    return a.id if isinstance(a, ast.Name) else None


def _free_vars(fn_node: ast.AST, params: Set[str]) -> Set[str]:
    """Names loaded but never bound in the function — closure captures
    (module globals are filtered by the caller)."""
    bound = set(params)
    loaded: Set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Store):
                bound.add(node.id)
            elif isinstance(node.ctx, ast.Load):
                loaded.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn_node:
                bound.add(node.name)
    import builtins
    return {n for n in loaded - bound if not hasattr(builtins, n)}


def _jit_calls(fn: FunctionInfo):
    for ev in fn.events:
        if ev.kind in ("call", "store") and ev.chain \
                and ev.chain[-1] == "jit" and ev.node is not None:
            # the actual jit Call node: stores carry the Assign node
            node = ev.node
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                node = node.value
            if isinstance(node, ast.Call):
                yield ev, node


def check_jax002(repo: RepoModel) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for key, fn in repo.functions.items():
        nested_by_name = {repo.functions[k].name: repo.functions[k]
                          for k in fn.nested}
        module_globals = _module_globals(fn)
        for ev, call in _jit_calls(fn):
            if (fn.key, ev.line) in seen:
                continue
            seen.add((fn.key, ev.line))
            if not call.args or not isinstance(call.args[0], ast.Name):
                continue
            target = nested_by_name.get(call.args[0].id)
            if target is None:
                continue
            free = _free_vars(target.node, target.params)
            free -= module_globals
            free -= fn.module.imports.keys()
            if free:
                findings.append(Finding(
                    JAX002.id, fn.module.relpath, ev.line, fn.qualname,
                    f"closure:{target.name}",
                    f"jax.jit({target.name}) where {target.name} "
                    f"captures {sorted(free)} from the enclosing scope "
                    f"— a fresh closure per call recompiles unless the "
                    f"jitted wrapper is cached"))
    return findings


def _module_globals(fn: FunctionInfo) -> Set[str]:
    out: Set[str] = set()
    for node in fn.module.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            out.add(node.name)
    return out


#: calls that hand a jitted callable to the compile plane for caching
#: (AOTRegistry.adopt / shared_jit): the registry owns its lifetime,
#: so the construction is a cached-jit pattern, not a recompile hazard
_PLANE_ADOPT_NAMES = {"adopt", "shared_jit"}


def _has_cache_exemption(fn: FunctionInfo, jit_store_name: str) -> bool:
    """The enclosing function visibly caches the jitted callable:
    lru_cache-decorated, the jit result stored into a subscript
    (``_CACHE[key] = fn``), or handed to the AOT registry
    (``AOT.adopt(key, jax.jit(impl))`` / stored then adopted) — the
    compile-plane idiom (ISSUE 9)."""
    for dec in getattr(fn.node, "decorator_list", []):
        chain = attr_chain(dec if not isinstance(dec, ast.Call)
                           else dec.func)
        if chain and chain[-1] in ("lru_cache", "cache"):
            return True
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    v = node.value
                    if isinstance(v, ast.Name) and v.id == jit_store_name:
                        return True
                    if isinstance(v, ast.Call) and \
                            (attr_chain(v.func) or ())[-1:] == ("jit",):
                        return True
        elif isinstance(node, ast.Call):
            # terminal attribute name, resolvable even through a
            # call-rooted chain like get_aot().adopt(...)
            tail = (node.func.attr if isinstance(node.func, ast.Attribute)
                    else node.func.id if isinstance(node.func, ast.Name)
                    else None)
            if tail in _PLANE_ADOPT_NAMES:
                for a in node.args:
                    if isinstance(a, ast.Name) and jit_store_name \
                            and a.id == jit_store_name:
                        return True
                    if isinstance(a, ast.Call) and \
                            (attr_chain(a.func) or ())[-1:] == ("jit",):
                        return True
    return False


def check_jax003(repo: RepoModel) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for key, fn in repo.functions.items():
        for ev, call in _jit_calls(fn):
            if (fn.key, ev.line) in seen:
                continue
            seen.add((fn.key, ev.line))
            store_name = ev.name if ev.kind == "store" else ""
            if _has_cache_exemption(fn, store_name):
                continue
            findings.append(Finding(
                JAX003.id, fn.module.relpath, ev.line, fn.qualname,
                f"jit:{store_name or 'inline'}",
                f"jax.jit constructed inside {fn.qualname} with no "
                f"visible cache — recompiles on every invocation"))
    return findings


#: the serve zone: code dispatching device programs per query or per
#: fold tick — where the compile plane's shape buckets + AOT warming
#: are the contract. Narrower than the JAX001 hot zone: train-only
#: kernels (markov, forest, ...) re-trace once per run, not per tick.
_SERVE_KERNELS = {"als.py", "similarity.py", "topk.py"}


def in_serve_zone(relpath: str) -> bool:
    parts = relpath.split("/")
    # tenancy/ (ISSUE 15) joins the serve zone: the multi-tenant host
    # sits directly on the query path, so a jit dispatched there
    # without the compile plane recompiles per tenant shape.
    # dataplane/ (ISSUE 16) joins too: the bulk loader's steady phase
    # stages a chunk per iteration — a jit dispatched there without
    # the compile plane's pow2 buckets recompiles per chunk shape,
    # which is exactly the zero-steady-compile contract it must keep
    if {"serving", "guard", "tenancy", "dataplane"}.intersection(
            parts[:-1]):
        return True
    if parts[-1] == "fold_in.py":
        return True
    return "ops" in parts[:-1] and parts[-1] in _SERVE_KERNELS


_PLANE_MODULE_PREFIX = "predictionio_tpu.compile"
_PLANE_NAMES = {"get_aot", "shared_jit", "warm_models"}


def _references_plane(fn: FunctionInfo) -> bool:
    """Does this function resolve anything through the compile plane?
    Either by name (get_aot / shared_jit / warm_models, however
    imported) or through any alias the module imports from
    predictionio_tpu.compile.*."""
    imports = fn.module.imports
    for ev in fn.events:
        if not ev.chain:
            continue
        root = ev.chain[0]
        if root in _PLANE_NAMES or "shared_jit" in ev.chain \
                or "get_aot" in ev.chain:
            return True
        if imports.get(root, "").startswith(_PLANE_MODULE_PREFIX):
            return True
    return False


def check_jax005(repo: RepoModel) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, str]] = set()
    for key, fn in repo.functions.items():
        if not in_serve_zone(fn.module.relpath):
            continue
        roster = set(fn.module.jitted)
        if not roster or _references_plane(fn):
            continue
        for ev in fn.events:
            if ev.kind != "call" or len(ev.chain) != 1 \
                    or ev.chain[0] not in roster:
                continue
            if (fn.key, ev.chain[0]) in seen:
                continue
            seen.add((fn.key, ev.chain[0]))
            findings.append(Finding(
                JAX005.id, fn.module.relpath, ev.line, fn.qualname,
                f"jit_dispatch:{ev.chain[0]}",
                f"{fn.qualname} dispatches jitted {ev.chain[0]} "
                f"directly on a serve-zone path — no compile-plane "
                f"resolution (shape buckets / AOT warm) covers it"))
    return findings


#: the pipelined serve zone (ISSUE 14): the executor's own modules,
#: where NO host sync may appear — readbacks live in the ops-layer
#: finish() closures and the sampled sync in obs/costmon.py, both
#: outside this zone. Narrower than the JAX001 hot zone on purpose:
#: the ops kernels legitimately np.asarray inside their finish()
#: callables (that IS the completion stage).
def in_pipelined_zone(relpath: str) -> bool:
    parts = relpath.split("/")
    # tenancy/ routes into the pipelined executor (ISSUE 15): a host
    # sync there would stall every tenant's overlap, not just one's.
    # dataplane/ (ISSUE 16) is pipelined the same way: read/decode of
    # chunk N+1 overlaps the async upload of chunk N, and the only
    # legitimate syncs live in ops/staging.py (device_stage submit,
    # wait_ready) — a sync in dataplane/ re-serializes the backfill
    return bool({"serving", "tenancy", "dataplane"}.intersection(
        parts[:-1]))


def check_jax006(repo: RepoModel) -> List[Finding]:
    findings: List[Finding] = []
    for key, fn in repo.functions.items():
        if not in_pipelined_zone(fn.module.relpath):
            continue
        tainted = _tainted_names(fn)
        for ev in fn.events:
            if ev.kind != "call" or not ev.chain:
                continue
            chain, node = ev.chain, ev.node
            if chain[-1] == "block_until_ready":
                findings.append(Finding(
                    JAX006.id, fn.module.relpath, ev.line, fn.qualname,
                    "block_until_ready",
                    f"{'.'.join(chain)}() synchronizes on the device "
                    f"inside the pipelined serve zone — the overlap "
                    f"ISSUE 14 bought is re-serialized here"))
                continue
            if chain[-1] == "item" and len(chain) >= 2:
                findings.append(Finding(
                    JAX006.id, fn.module.relpath, ev.line, fn.qualname,
                    f"item:{chain[-2]}",
                    f"{'.'.join(chain)}() forces a device sync in the "
                    f"pipelined serve zone"))
                continue
            arg0 = _first_arg_name(node)
            if arg0 is not None and arg0 in tainted \
                    and tuple(chain[-2:]) in _NP_CONVERTERS:
                findings.append(Finding(
                    JAX006.id, fn.module.relpath, ev.line, fn.qualname,
                    f"asarray:{arg0}",
                    f"{'.'.join(chain)}({arg0}) reads a device value "
                    f"back in the pipelined serve zone — defer it to "
                    f"the completion stage's finish()"))
    return findings


def check_jax004(repo: RepoModel) -> List[Finding]:
    findings: List[Finding] = []
    for key, fn in repo.functions.items():
        donating = dict(fn.module.jitted)   # name -> positions
        donating = {n: p for n, p in donating.items() if p}
        for ev in fn.events:                # local jit wrappers
            if ev.kind == "store" and ev.chain \
                    and ev.chain[-1] == "jit" and ev.node is not None:
                node = ev.node
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    pos = jit_donated_positions(node.value)
                    if pos:
                        donating[ev.name] = pos
        if not donating:
            continue
        # calls to donating wrappers: donated positional Name args must
        # not be loaded after the call line
        for ev in fn.events:
            if ev.kind != "call" or len(ev.chain) != 1 \
                    or ev.chain[0] not in donating:
                continue
            call = ev.node
            if not isinstance(call, ast.Call):
                continue
            for pos in donating[ev.chain[0]]:
                if pos >= len(call.args):
                    continue
                arg = call.args[pos]
                if not isinstance(arg, ast.Name):
                    continue
                # rebinding kills the hazard: `G = f(G)` in a loop
                # re-points the name at the RESULT buffer, so loads
                # after the re-store (including next iteration's arg)
                # are safe. Only loads between donation and the next
                # store of the name are findings.
                restore = min((s.line for s in fn.events
                               if s.kind == "store" and s.name == arg.id
                               and s.line >= ev.line),
                              default=None)
                for later in fn.events:
                    if later.kind != "load" or later.name != arg.id \
                            or later.line <= ev.line:
                        continue
                    if restore is not None and later.line > restore:
                        continue
                    findings.append(Finding(
                        JAX004.id, fn.module.relpath, later.line,
                        fn.qualname, f"donated:{arg.id}",
                        f"{arg.id} donated to {ev.chain[0]} at "
                        f"line {ev.line} is used again — the "
                        f"buffer is invalid after donation"))
                    break
    return findings
