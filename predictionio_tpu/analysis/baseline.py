"""Accepted-finding baseline: ``conf/lint_baseline.json``.

Every entry suppresses exactly ONE finding by its line-independent
fingerprint and must carry a one-line justification — there are no
wildcard/blanket suppressions by construction (a fingerprint names a
rule, file, symbol and evidence). The gate is therefore "zero NEW
findings": the analyzer stays honest about the debt it has accepted,
and a suppressed finding that stops firing surfaces as a *stale* entry
so the baseline shrinks as defects are paid down.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

from predictionio_tpu.analysis.core import RULE_ID_PATTERN, Finding

MIN_JUSTIFICATION_CHARS = 10

#: fingerprint shape: RULE:path:symbol:evidence[#n] — validated so a
#: hand-edited entry can't silently match nothing (or everything)
_FPRINT_RE = re.compile(
    r"^(LOCK|JAX|COST)[0-9]{3}:[^:]+:[^:]*:.+$")


class BaselineError(ValueError):
    pass


@dataclass(frozen=True)
class BaselineEntry:
    fingerprint: str
    justification: str


def load_baseline(path: str) -> List[BaselineEntry]:
    """Parse + validate. Raises BaselineError on blanket suppressions
    (wildcards), missing/short justifications, or duplicates."""
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("entries", [])
    out: List[BaselineEntry] = []
    seen = set()
    for i, e in enumerate(entries):
        fp = e.get("fingerprint", "")
        just = (e.get("justification") or "").strip()
        if "*" in fp or not _FPRINT_RE.match(fp):
            raise BaselineError(
                f"{path} entry {i}: fingerprint {fp!r} is not a full "
                f"single-finding fingerprint (no wildcards/blanket "
                f"suppressions)")
        if len(just) < MIN_JUSTIFICATION_CHARS:
            raise BaselineError(
                f"{path} entry {i} ({fp}): justification is required "
                f"(>= {MIN_JUSTIFICATION_CHARS} chars explaining why "
                f"this finding is accepted)")
        if fp in seen:
            raise BaselineError(f"{path}: duplicate fingerprint {fp}")
        seen.add(fp)
        out.append(BaselineEntry(fp, just))
    return out


def apply_baseline(findings: Sequence[Finding],
                   entries: Sequence[BaselineEntry]
                   ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """-> (new, suppressed, stale_fingerprints)."""
    by_fp: Dict[str, BaselineEntry] = {e.fingerprint: e for e in entries}
    new: List[Finding] = []
    suppressed: List[Finding] = []
    hit = set()
    for f in findings:
        if f.fingerprint in by_fp:
            suppressed.append(f)
            hit.add(f.fingerprint)
        else:
            new.append(f)
    stale = [fp for fp in by_fp if fp not in hit]
    return new, suppressed, stale


def write_baseline(path: str, findings: Sequence[Finding],
                   existing: Sequence[BaselineEntry],
                   placeholder: str = "TODO: justify this accepted "
                                      "finding") -> int:
    """``pio lint --update-baseline``: rewrite with the CURRENT finding
    set, keeping justifications for fingerprints that survive and
    stamping new entries with a placeholder the operator must edit
    (load_baseline accepts it, review should not). Returns the number
    of placeholder entries written."""
    keep = {e.fingerprint: e.justification for e in existing}
    out = []
    todo = 0
    for f in sorted(findings, key=lambda f: f.fingerprint):
        just = keep.get(f.fingerprint)
        if just is None:
            just = placeholder
            todo += 1
        out.append({"fingerprint": f.fingerprint,
                    "rule": f.rule_id, "path": f.path,
                    "justification": just})
    doc = {"version": 1,
           "comment": "Accepted `pio lint` findings. Every entry "
                      "suppresses exactly one fingerprint and needs a "
                      "one-line justification; the CI gate is zero "
                      "findings outside this file. See "
                      "docs/operations.md 'Running pio lint'.",
           "entries": out}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return todo
