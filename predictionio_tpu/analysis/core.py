"""Analyzer core: AST facts extraction shared by every rule family.

Pure ``ast`` — the analyzer never imports the code under analysis (it
must be able to lint a module whose import would start threads or touch
storage). One parse + one recursive walk per function produces an
ordered **event stream** (calls, lock acquisitions, attribute stores,
name loads) where every event carries the locks held at that point;
rules are then linear passes over the streams plus two small fixpoints
(may-acquire and may-block closures over the resolvable call graph).

Resolution is deliberately name-based and two-tier:

- tier A (high confidence): ``self.method`` within the defining class
  (single-inheritance chain included when the base is in-repo), plain
  names within the same module, ``mod.func`` through the import map,
  and nested ``def``s (conservatively assumed to run in their parent —
  the ``attempt()``-closure idiom the resilience layer uses).
- tier B (distinctive names, used only for hot-path reachability): a
  method name defined by at most ``TIER_B_MAX_IMPLS`` in-repo classes
  and absent from ``COMMON_METHOD_NAMES`` resolves to all of them.

Findings carry a line number for humans and a line-independent
``fingerprint`` (rule:path:symbol:evidence[#n]) for the baseline, so
accepted findings survive unrelated edits to the same file.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# -- rule registry ------------------------------------------------------

#: rule ids are API: the baseline and the docs key on them, and
#: tests/test_static_analysis.py lints the ids themselves (family
#: prefix + 3 digits, unique, titled) so they stay stable.
RULE_ID_PATTERN = r"^(LOCK|JAX|COST)[0-9]{3}$"


@dataclass(frozen=True)
class Rule:
    id: str
    title: str
    description: str


RULES: Dict[str, Rule] = {}


def register_rule(id: str, title: str, description: str) -> Rule:
    rule = Rule(id, title, description)
    if id in RULES:
        raise ValueError(f"duplicate rule id {id}")
    RULES[id] = rule
    return rule


@dataclass
class Finding:
    rule_id: str
    path: str            # repo-relative, forward slashes
    line: int
    symbol: str          # enclosing function qualname ("" = module)
    evidence: str        # the stable what ("os.fsync", attr name, ...)
    message: str
    occurrence: int = 0  # disambiguates same-evidence repeats

    @property
    def fingerprint(self) -> str:
        base = f"{self.rule_id}:{self.path}:{self.symbol}:{self.evidence}"
        return base if self.occurrence == 0 else f"{base}#{self.occurrence}"

    def to_dict(self) -> dict:
        return {"rule": self.rule_id, "path": self.path, "line": self.line,
                "symbol": self.symbol, "evidence": self.evidence,
                "message": self.message, "fingerprint": self.fingerprint}


def number_occurrences(findings: List[Finding]) -> List[Finding]:
    """Assign ``occurrence`` so identical (rule, path, symbol, evidence)
    repeats — two fsyncs in one function — fingerprint distinctly, in
    source order (stable as long as their relative order is)."""
    seen: Dict[str, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        key = f"{f.rule_id}:{f.path}:{f.symbol}:{f.evidence}"
        f.occurrence = seen.get(key, 0)
        seen[key] = f.occurrence + 1
    return findings


# -- call-chain + event model ------------------------------------------

def attr_chain(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """``self.wal.append`` -> ("self", "wal", "append"); None when any
    link is a call/subscript (those don't name a stable symbol)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


@dataclass
class Event:
    kind: str                 # call | acquire | selfstore | store | load
    line: int
    held: Tuple[str, ...]     # lock ids held at this point
    chain: Tuple[str, ...] = ()   # call: callee chain; store: value root
    node: Optional[ast.AST] = None
    name: str = ""            # selfstore/store/load: the target name
    held_src: Tuple[str, ...] = ()  # source names of held locks


@dataclass
class FunctionInfo:
    qualname: str             # "Class.method", "func" or "outer.<inner>"
    name: str
    module: "ModuleInfo"
    node: ast.AST
    class_name: Optional[str]
    parent: Optional[str]     # enclosing function qualname
    events: List[Event] = field(default_factory=list)
    params: Set[str] = field(default_factory=set)
    local_names: Set[str] = field(default_factory=set)
    nested: List[str] = field(default_factory=list)

    @property
    def key(self) -> str:
        return f"{self.module.relpath}::{self.qualname}"


@dataclass
class ClassInfo:
    name: str
    module: "ModuleInfo"
    bases: Tuple[str, ...]
    methods: Dict[str, str] = field(default_factory=dict)  # name -> key
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr->kind
    thread_targets: Set[str] = field(default_factory=set)  # method names


@dataclass
class ModuleInfo:
    relpath: str
    tree: ast.Module
    imports: Dict[str, str] = field(default_factory=dict)  # alias->module
    module_locks: Dict[str, str] = field(default_factory=dict)  # name->kind
    jitted: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    #                      ^ module-level jitted name -> donated positions
    functions: List[str] = field(default_factory=list)     # top-level fns

    @property
    def basename(self) -> str:
        return os.path.basename(self.relpath)


_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}


def _lock_ctor_kind(value: ast.AST) -> Optional[str]:
    """``threading.Lock()`` / ``Lock()`` / ``threading.Condition(lk)``."""
    if not isinstance(value, ast.Call):
        return None
    chain = attr_chain(value.func)
    if not chain:
        return None
    if chain[-1] in _LOCK_CTORS and (
            len(chain) == 1 or chain[0] in ("threading", "_threading")):
        return _LOCK_CTORS[chain[-1]]
    return None


def _import_rooted_chain(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """attr_chain for the ``__import__("jax").jit`` spelling: the root
    Call's literal module name substitutes for the Name link (the
    lazy-import idiom the kernel modules use at module scope)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "__import__" and node.args \
            and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        parts.append(node.args[0].value)
        return tuple(reversed(parts))
    return None


def _jit_ref_chain(node: ast.AST) -> Optional[Tuple[str, ...]]:
    return attr_chain(node) or _import_rooted_chain(node)


def _is_jit_call(value: ast.AST) -> bool:
    """``jax.jit(...)``, ``jit(...)``, ``functools.partial(jax.jit,
    ...)`` or the ``__import__("jax").jit(...)`` lazy-import spelling —
    the forms the repo uses."""
    if not isinstance(value, ast.Call):
        return False
    chain = _jit_ref_chain(value.func)
    if chain and chain[-1] == "jit":
        return True
    if chain and chain[-1] == "partial" and value.args:
        inner = _jit_ref_chain(value.args[0])
        return bool(inner) and inner[-1] == "jit"
    return False


def jit_donated_positions(call: ast.Call) -> Tuple[int, ...]:
    """The ``donate_argnums`` literal of a jit call, () when absent or
    non-literal (a conditional expression donates only sometimes — the
    reuse rule stays quiet rather than guessing)."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            try:
                v = ast.literal_eval(kw.value)
            except ValueError:
                return ()
            if isinstance(v, int):
                return (v,)
            if isinstance(v, (tuple, list)):
                return tuple(x for x in v if isinstance(x, int))
    return ()


# -- per-function walk --------------------------------------------------

class _FunctionWalker:
    """Recursive statement walk producing the ordered event stream.

    Tracks the held-lock stack through ``with`` statements; nested
    ``def``/``lambda`` bodies are NOT walked here (each gets its own
    FunctionInfo) but are recorded so the call graph can add the
    conservative parent->nested edge.
    """

    def __init__(self, fn: FunctionInfo, scanner: "_ModuleScanner"):
        self.fn = fn
        self.scanner = scanner
        self.held: List[Tuple[str, str, str]] = []  # (id, kind, srcname)

    # lock id resolution for a with-context expression ------------------
    def _lock_of(self, expr: ast.AST) -> Optional[Tuple[str, str, str]]:
        """(lock_id, kind, source_name) when ``expr`` names a lock, or
        is ``timed_acquire(lock, probe)`` wrapping one."""
        if isinstance(expr, ast.Call):
            chain = attr_chain(expr.func)
            if chain and chain[-1] == "timed_acquire" and expr.args:
                inner = self._lock_of(expr.args[0])
                if inner is not None:
                    return inner
                src = self._src_name(expr.args[0])
                return (f"local:{src}", "lock", src) if src else None
            return None
        chain = attr_chain(expr)
        if chain is None:
            return None
        cls = self.scanner.current_class
        if len(chain) == 2 and chain[0] == "self" and cls is not None:
            kind = cls.lock_attrs.get(chain[1])
            if kind is not None:
                return (f"{cls.name}.{chain[1]}", kind, chain[1])
            return None
        if len(chain) == 1:
            kind = self.fn.module.module_locks.get(chain[0])
            if kind is not None:
                mod = os.path.splitext(self.fn.module.basename)[0]
                return (f"{mod}:{chain[0]}", kind, chain[0])
            if chain[0] in self.scanner.local_lock_names.get(
                    self.fn.key, set()):
                return (f"local:{chain[0]}", "lock", chain[0])
        return None

    @staticmethod
    def _src_name(expr: ast.AST) -> str:
        chain = attr_chain(expr)
        return chain[-1] if chain else ""

    # event emission ----------------------------------------------------
    def _emit(self, kind: str, line: int, **kw):
        self.fn.events.append(Event(
            kind=kind, line=line,
            held=tuple(h[0] for h in self.held),
            held_src=tuple(h[2] for h in self.held), **kw))

    # walk --------------------------------------------------------------
    def walk(self, body: Sequence[ast.stmt]):
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return                      # nested defs walked separately
        if isinstance(stmt, ast.ClassDef):
            return                      # function-local classes too
        if isinstance(stmt, ast.With):
            self._with(stmt)
            return
        self._expr_events(stmt)
        # recurse into compound statements' bodies with held preserved
        for attr in ("body", "orelse", "finalbody"):
            for child in getattr(stmt, attr, []) or []:
                if isinstance(child, ast.stmt):
                    self._stmt(child)
        for handler in getattr(stmt, "handlers", []) or []:
            for child in handler.body:
                self._stmt(child)

    def _expr_events(self, stmt: ast.stmt):
        """Emit call/store/load events for the statement's own
        expressions (compound bodies recurse via ``_stmt``)."""
        skip_bodies = ("body", "orelse", "finalbody", "handlers")
        if isinstance(stmt, (ast.If, ast.While)):
            roots: List[ast.AST] = [stmt.test]
        elif isinstance(stmt, ast.For):
            roots = [stmt.target, stmt.iter]
        elif isinstance(stmt, ast.Try):
            roots = []
        elif any(getattr(stmt, a, None) for a in skip_bodies):
            roots = [v for a, v in ast.iter_fields(stmt)
                     if a not in skip_bodies and isinstance(v, ast.AST)]
        else:
            roots = [stmt]
        for root in roots:
            for node in _walk_skipping_callables(root):
                self._node_event(node)

    def _node_event(self, node: ast.AST):
        line = getattr(node, "lineno", 0)
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain:
                self._emit("call", line, chain=chain, node=node)
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            value = node.value
            vchain = (attr_chain(value.func)
                      if isinstance(value, ast.Call) else None) or ()
            for t in targets:
                tc = attr_chain(t)
                if tc and len(tc) == 2 and tc[0] == "self":
                    self._emit("selfstore", line, name=tc[1],
                               chain=vchain, node=node)
                elif isinstance(t, ast.Name):
                    self.fn.local_names.add(t.id)
                    self._emit("store", line, name=t.id, chain=vchain,
                               node=node)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            self._emit("load", line, name=node.id)

    def _with(self, stmt: ast.With):
        acquired = []
        for item in stmt.items:
            lk = self._lock_of(item.context_expr)
            if lk is not None:
                self._emit("acquire", stmt.lineno, name=lk[2],
                           chain=(lk[0], lk[1]))
                self.held.append(lk)
                acquired.append(lk)
            else:
                # a non-lock context manager: still scan its expression
                for node in _walk_skipping_callables(item.context_expr):
                    self._node_event(node)
        for child in stmt.body:
            self._stmt(child)
        for _ in acquired:
            self.held.pop()


def _walk_skipping_callables(root: ast.AST):
    """``ast.walk`` minus nested ``def``/``lambda``/``class`` subtrees
    — their bodies belong to their own FunctionInfo's event stream, not
    the enclosing function's (marking the shared tree would blank the
    nested function's OWN walk). The root itself is always yielded."""
    yield root
    stack = [root]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            yield child
            stack.append(child)


def _immediate_nested_defs(fn_node: ast.AST) -> List[ast.AST]:
    """The ``def``s directly nested in ``fn_node``'s body (not the ones
    inside those, which recurse through their own FunctionInfo)."""
    found: List[ast.AST] = []

    def visit(n: ast.AST):
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                found.append(child)
            elif not isinstance(child, (ast.Lambda, ast.ClassDef)):
                visit(child)

    visit(fn_node)
    return found


def _immediate_nested_classes(fn_node: ast.AST) -> List[ast.ClassDef]:
    """Function-local ``class`` definitions (the HttpServer
    ``_make_handler`` -> ``_Handler`` idiom): analyzed as ordinary
    classes so their methods — e.g. the per-request ``_handle`` — are
    visible to every rule."""
    found: List[ast.ClassDef] = []

    def visit(n: ast.AST):
        for child in ast.iter_child_nodes(n):
            if isinstance(child, ast.ClassDef):
                found.append(child)
            elif not isinstance(child, (ast.Lambda, ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                visit(child)

    visit(fn_node)
    return found


# -- per-module scan ----------------------------------------------------

class _ModuleScanner:
    def __init__(self, mod: ModuleInfo, repo: "RepoModel"):
        self.mod = mod
        self.repo = repo
        self.current_class: Optional[ClassInfo] = None
        #: fn key -> local names assigned from a lock ctor (the
        #: ``lk = self._locks[k]`` nativelog idiom resolves via this
        #: only when the value is literally a Lock() call; dict-fetched
        #: locks resolve through timed_acquire or stay anonymous)
        self.local_lock_names: Dict[str, Set[str]] = {}

    def scan(self):
        self._module_level()
        for node in self.mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._function(node, qual=node.name, cls=None, parent=None)
            elif isinstance(node, ast.ClassDef):
                self._class(node)

    def _module_level(self):
        for node in self.mod.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.mod.imports[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.mod.imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
            elif isinstance(node, ast.Assign):
                kind = _lock_ctor_kind(node.value)
                for t in node.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if kind is not None:
                        self.mod.module_locks[t.id] = kind
                    if _is_jit_call(node.value):
                        self.mod.jitted[t.id] = jit_donated_positions(
                            node.value)

    def _class(self, node: ast.ClassDef):
        bases = tuple(chain[-1] for chain in
                      (attr_chain(b) for b in node.bases) if chain)
        cls = ClassInfo(node.name, self.mod, bases)
        self.repo.classes.setdefault(node.name, []).append(cls)
        # first pass: lock attrs + methods (so with-resolution inside
        # any method sees attrs assigned in __init__ or elsewhere)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                kind = _lock_ctor_kind(sub.value)
                if kind is None:
                    continue
                for t in sub.targets:
                    c = attr_chain(t)
                    if c and len(c) == 2 and c[0] == "self":
                        cls.lock_attrs[c[1]] = kind
        prev, self.current_class = self.current_class, cls
        for sub in node.body:
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{node.name}.{sub.name}"
                cls.methods[sub.name] = f"{self.mod.relpath}::{qual}"
                self._function(sub, qual=qual, cls=cls, parent=None)
        self.current_class = prev

    def _function(self, node, qual: str, cls: Optional[ClassInfo],
                  parent: Optional[str]):
        fn = FunctionInfo(qualname=qual, name=node.name, module=self.mod,
                          node=node,
                          class_name=cls.name if cls else None,
                          parent=parent)
        self.repo.functions[fn.key] = fn
        if parent is None and cls is None:
            self.mod.functions.append(fn.key)
        for a in (node.args.posonlyargs + node.args.args
                  + node.args.kwonlyargs):
            fn.params.add(a.arg)
        if node.args.vararg:
            fn.params.add(node.args.vararg.arg)
        if node.args.kwarg:
            fn.params.add(node.args.kwarg.arg)
        # pre-scan: local lock names + decorator jit (module-level
        # methods decorated @jax.jit are "jitted names" for dispatch)
        locks = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and _lock_ctor_kind(sub.value):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        locks.add(t.id)
        self.local_lock_names[fn.key] = locks
        for dec in getattr(node, "decorator_list", []):
            if _is_jit_call(dec) or (
                    (_jit_ref_chain(dec) or ())[-1:] == ("jit",)):
                donated = (jit_donated_positions(dec)
                           if isinstance(dec, ast.Call) else ())
                self.mod.jitted[node.name] = donated
        saved_cls, self.current_class = self.current_class, cls
        walker = _FunctionWalker(fn, self)
        walker.walk(node.body)
        self.current_class = saved_cls
        for sub in _immediate_nested_defs(node):
            qual = f"{fn.qualname}.<{sub.name}>"
            fn.nested.append(f"{fn.module.relpath}::{qual}")
            self._function(sub, qual=qual, cls=cls, parent=fn.qualname)
        for cls_node in _immediate_nested_classes(node):
            self._class(cls_node)


# -- repo model ---------------------------------------------------------

#: method names too generic for tier-B name-based resolution — the
#: containers-and-protocols vocabulary that would wire the call graph
#: to everything
COMMON_METHOD_NAMES = frozenset({
    "append", "add", "get", "put", "pop", "insert", "update", "remove",
    "delete", "clear", "close", "open", "read", "write", "flush",
    "items", "keys", "values", "copy", "start", "stop", "join", "run",
    "send", "recv", "render", "wait", "set", "acquire", "release",
    "format", "split", "strip", "encode", "decode", "sort", "index",
    "count", "extend", "next", "result", "done", "cancel", "name",
    "with_", "to_dict", "from_dict", "stats", "collect", "match",
    "search", "sub", "group", "inc", "dec", "observe", "labels",
})

TIER_B_MAX_IMPLS = 3


class RepoModel:
    """Parsed repo + derived facts. ``root`` is the directory whose
    ``*.py`` files (recursively) are analyzed; paths in findings are
    relative to ``base`` (default: ``root``'s parent, so the real run
    reports ``predictionio_tpu/...`` paths)."""

    def __init__(self, root: str, base: Optional[str] = None):
        self.root = os.path.abspath(root)
        self.base = os.path.abspath(base) if base else \
            os.path.dirname(self.root)
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, List[ClassInfo]] = {}
        self.parse_errors: List[Tuple[str, str]] = []
        #: call_edges memo, keyed by tier_b — several rules need the
        #: same graph, and tier-B resolution is the dominant
        #: post-parse cost
        self._edges: Dict[bool, Dict[str, Set[str]]] = {}
        self._scan()

    # -- parsing --------------------------------------------------------
    def _scan(self):
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__",))
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                rel = os.path.relpath(path, self.base).replace(os.sep, "/")
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        tree = ast.parse(f.read(), filename=rel)
                except (SyntaxError, UnicodeDecodeError) as e:
                    self.parse_errors.append((rel, str(e)))
                    continue
                mod = ModuleInfo(relpath=rel, tree=tree)
                self.modules[rel] = mod
        for mod in self.modules.values():
            _ModuleScanner(mod, self).scan()
        self._roster_threads()

    # -- thread roster --------------------------------------------------
    def _roster_threads(self):
        """``Thread(target=X)`` sites: mark X (a self-method or a
        nested def) as a background-thread entry point on its class."""
        self.thread_entries: Set[str] = set()   # function keys
        for fn in self.functions.values():
            for ev in fn.events:
                if ev.kind != "call" or ev.chain[-1] != "Thread":
                    continue
                call = ev.node
                target = None
                for kw in getattr(call, "keywords", []):
                    if kw.arg == "target":
                        target = kw.value
                if target is None:
                    continue
                tc = attr_chain(target)
                if not tc:
                    continue
                if len(tc) == 2 and tc[0] == "self" and fn.class_name:
                    for cls in self.classes.get(fn.class_name, []):
                        key = cls.methods.get(tc[1])
                        if key:
                            self.thread_entries.add(key)
                            cls.thread_targets.add(tc[1])
                elif len(tc) == 1:
                    # local nested def in this function
                    for nk in fn.nested:
                        if self.functions[nk].name == tc[0]:
                            self.thread_entries.add(nk)
                    # or a module-level function
                    mk = f"{fn.module.relpath}::{tc[0]}"
                    if mk in self.functions:
                        self.thread_entries.add(mk)

    # -- call graph -----------------------------------------------------
    def resolve_call(self, fn: FunctionInfo, chain: Tuple[str, ...],
                     tier_b: bool = False) -> List[str]:
        """Resolve a call chain to function keys (possibly empty)."""
        out: List[str] = []
        name = chain[-1]
        if len(chain) >= 2 and chain[0] == "self" and fn.class_name:
            if len(chain) == 2:
                for cls in self._mro(fn.class_name):
                    key = cls.methods.get(name)
                    if key:
                        return [key]
                return []
            # self.obj.method(...): falls through to tier B
        elif len(chain) == 1:
            # local nested def first, then module function, then import
            for nk in fn.nested:
                if self.functions[nk].name == name:
                    return [nk]
            mk = f"{fn.module.relpath}::{name}"
            if mk in self.functions:
                return [mk]
            target = fn.module.imports.get(name)
            if target:
                return self._import_target(target)
            return []
        elif len(chain) == 2 and chain[0] in fn.module.imports:
            return self._import_target(
                f"{fn.module.imports[chain[0]]}.{name}")
        elif len(chain) == 2 and chain[0] in self.classes:
            for cls in self._mro(chain[0]):
                key = cls.methods.get(name)
                if key:
                    return [key]
            return []
        if tier_b and name not in COMMON_METHOD_NAMES \
                and not name.startswith("__"):
            impls = [cls.methods[name]
                     for classes in self.classes.values()
                     for cls in classes if name in cls.methods]
            if 0 < len(impls) <= TIER_B_MAX_IMPLS:
                out.extend(impls)
        return out

    def _mro(self, class_name: str) -> Iterable[ClassInfo]:
        seen: Set[str] = set()
        stack = [class_name]
        while stack:
            cn = stack.pop(0)
            if cn in seen:
                continue
            seen.add(cn)
            for cls in self.classes.get(cn, []):
                yield cls
                stack.extend(b for b in cls.bases if b in self.classes)

    def _import_target(self, dotted: str) -> List[str]:
        """``predictionio_tpu.obs.slo.timed_acquire`` -> its key, via
        the module path mapped onto analyzed relpaths."""
        parts = dotted.split(".")
        for split in range(len(parts) - 1, 0, -1):
            rel = "/".join(parts[:split]) + ".py"
            mod = self.modules.get(rel)
            if mod is None:
                continue
            name = parts[split]
            key = f"{rel}::{name}"
            if key in self.functions:
                return [key]
        return []

    def call_edges(self, tier_b: bool = False) -> Dict[str, Set[str]]:
        """fn key -> resolvable callee keys (+ conservative edges to
        nested defs, which run when the parent passes them somewhere).
        Memoized per tier."""
        cached = self._edges.get(tier_b)
        if cached is not None:
            return cached
        edges: Dict[str, Set[str]] = {}
        for key, fn in self.functions.items():
            out: Set[str] = set(fn.nested)
            for ev in fn.events:
                if ev.kind != "call":
                    continue
                out.update(self.resolve_call(fn, ev.chain, tier_b=tier_b))
            out.discard(key)
            edges[key] = out
        self._edges[tier_b] = edges
        return edges

    def closure(self, seed: Dict[str, Set[str]],
                edges: Dict[str, Set[str]]) -> Dict[str, Set[str]]:
        """Fixpoint: propagate ``seed`` sets backwards over call edges
        (caller inherits callees' sets). Used for may-acquire and
        may-block."""
        out = {k: set(v) for k, v in seed.items()}
        changed = True
        while changed:
            changed = False
            for caller, callees in edges.items():
                acc = out.setdefault(caller, set())
                before = len(acc)
                for c in callees:
                    acc.update(out.get(c, ()))
                if len(acc) != before:
                    changed = True
        return out

    def reachable(self, roots: Iterable[str],
                  edges: Dict[str, Set[str]],
                  max_depth: int = 8) -> Set[str]:
        seen: Set[str] = set()
        frontier = [(r, 0) for r in roots if r in self.functions]
        while frontier:
            key, d = frontier.pop()
            if key in seen or d > max_depth:
                continue
            seen.add(key)
            for c in edges.get(key, ()):
                frontier.append((c, d + 1))
        return seen
