"""LOCK* rules: lock-order cycles, locks held across blocking calls,
and unguarded mutation from background threads.

The lock graph is class-attribute granular (``SpillWAL._lock`` is one
node regardless of instance) — the right granularity for order cycles,
and the documented source of instance-aliasing false positives the
baseline absorbs.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from predictionio_tpu.analysis.core import (Event, Finding, FunctionInfo,
                                            RepoModel, register_rule)

LOCK001 = register_rule(
    "LOCK001", "lock-order cycle",
    "Two or more locks are acquired in inconsistent orders somewhere "
    "in the repo (directly or through resolvable calls made while "
    "holding a lock). Two threads taking the cycle's edges "
    "concurrently deadlock. Self-cycles on a non-reentrant "
    "threading.Lock are reported too (re-acquiring wedges the thread).")

LOCK002 = register_rule(
    "LOCK002", "lock held across blocking call",
    "A blocking operation (FFI el_*, os.fsync/open, HTTP, queue/event "
    "waits, thread joins, time.sleep, jit dispatch) runs while a lock "
    "is held — every other thread needing the lock convoys behind the "
    "slow operation (the PR 7 nativelog fsync convoy class).")

LOCK003 = register_rule(
    "LOCK003", "unguarded shared mutation from background thread",
    "An instance attribute is mutated from a background-thread entry "
    "point (Thread(target=...) roster) without holding any lock, while "
    "other methods of the class also touch it. Torn reads/lost updates "
    "unless the attribute is documented single-writer or benign.")

#: call-chain tails treated as blocking while a lock is held. Curated,
#: not exhaustive: high-signal operations only (plain file .write/.flush
#: under a lock is the WAL's whole design, so it is NOT in this set).
_BLOCKING_DOTTED: Dict[Tuple[str, ...], str] = {
    ("os", "fsync"): "os.fsync",
    ("time", "sleep"): "time.sleep",
    ("os", "replace"): "os.replace",
}
_BLOCKING_NAMES = {"fetch_json": "http:fetch_json", "urlopen":
                   "http:urlopen", "open": "open"}
_BLOCKING_ATTRS = {"fsync": "os.fsync", "urlopen": "http:urlopen",
                   "getresponse": "http:getresponse",
                   "wait": "wait", "result": "future.result"}


def _blocking_label(fn: FunctionInfo, ev: Event,
                    repo: RepoModel) -> Optional[str]:
    chain = ev.chain
    name = chain[-1]
    if chain in _BLOCKING_DOTTED:
        return _BLOCKING_DOTTED[chain]
    if len(chain) == 1 and name in _BLOCKING_NAMES:
        return _BLOCKING_NAMES[name]
    if name.startswith("el_"):
        return f"ffi:{name}"
    if len(chain) >= 2:
        if name in _BLOCKING_ATTRS:
            # Condition.wait on the HELD lock releases it while waiting
            # — that is the condition idiom, not a convoy
            if name == "wait" and chain[-2] in ev.held_src:
                return None
            # stop-event waits with timeout are the scheduler/pacer
            # idiom; .wait on anything else under a lock is a finding
            return _BLOCKING_ATTRS[name]
        if name == "get" and any("queue" in part.lower() or
                                 part.rstrip("_").endswith("q")
                                 for part in chain[:-1]):
            return "queue.get"
        if name == "join" and any("thread" in part.lower()
                                  for part in chain[:-1]):
            return "thread.join"
    # dispatch of a known-jitted callable (module-level jitted name or
    # a local assigned from jax.jit earlier in this function)
    if len(chain) == 1 and name in fn.module.jitted:
        return f"jit-dispatch:{name}"
    return None


def _local_jitted(fn: FunctionInfo) -> Set[str]:
    """Names assigned from a jit call within ``fn`` — calling one is a
    dispatch."""
    out = set()
    for ev in fn.events:
        if ev.kind == "store" and ev.chain and ev.chain[-1] == "jit":
            out.add(ev.name)
    return out


def check_lock002(repo: RepoModel) -> List[Finding]:
    findings: List[Finding] = []
    edges = repo.call_edges(tier_b=False)
    # may-block closure: which functions (transitively) hit a blocking
    # op — used for calls made while a lock is held here
    seed: Dict[str, Set[str]] = {}
    for key, fn in repo.functions.items():
        jitted = _local_jitted(fn)
        ops = set()
        for ev in fn.events:
            if ev.kind != "call":
                continue
            label = _blocking_label(fn, ev, repo)
            if label is None and len(ev.chain) == 1 \
                    and ev.chain[0] in jitted:
                label = f"jit-dispatch:{ev.chain[0]}"
            if label is not None:
                ops.add(label)
        seed[key] = ops
    may_block = repo.closure(seed, edges)

    for key, fn in repo.functions.items():
        jitted = _local_jitted(fn)
        for ev in fn.events:
            if ev.kind != "call" or not ev.held:
                continue
            label = _blocking_label(fn, ev, repo)
            if label is None and len(ev.chain) == 1 \
                    and ev.chain[0] in jitted:
                label = f"jit-dispatch:{ev.chain[0]}"
            if label is not None:
                findings.append(Finding(
                    LOCK002.id, fn.module.relpath, ev.line, fn.qualname,
                    label,
                    f"{label} while holding {', '.join(ev.held)}"))
                continue
            # interprocedural: a resolvable callee that may block
            for callee in repo.resolve_call(fn, ev.chain):
                ops = may_block.get(callee, ())
                if ops:
                    cal = repo.functions[callee].qualname
                    findings.append(Finding(
                        LOCK002.id, fn.module.relpath, ev.line,
                        fn.qualname, f"call:{cal}",
                        f"call to {cal} (which may {sorted(ops)[0]}) "
                        f"while holding {', '.join(ev.held)}"))
                    break
    return findings


def check_lock001(repo: RepoModel) -> List[Finding]:
    edges = repo.call_edges(tier_b=False)
    # may-acquire closure over NAMED locks (local: anonymous locks only
    # order intra-function where the held stack already sees them)
    seed: Dict[str, Set[str]] = {}
    for key, fn in repo.functions.items():
        seed[key] = {ev.chain[0] for ev in fn.events
                     if ev.kind == "acquire"
                     and not ev.chain[0].startswith("local:")}
    may_acquire = repo.closure(seed, edges)

    #: lock kind lookup (for RLock self-cycle exemption)
    kinds: Dict[str, str] = {}
    graph: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def edge(a: str, b: str, fn: FunctionInfo, line: int):
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
        sites.setdefault((a, b), (fn.module.relpath, line, fn.qualname))

    for key, fn in repo.functions.items():
        for ev in fn.events:
            if ev.kind == "acquire":
                kinds.setdefault(ev.chain[0], ev.chain[1])
                for held in ev.held:
                    edge(held, ev.chain[0], fn, ev.line)
            elif ev.kind == "call" and ev.held:
                for callee in repo.resolve_call(fn, ev.chain):
                    for lock in may_acquire.get(callee, ()):
                        for held in ev.held:
                            edge(held, lock, fn, ev.line)

    findings: List[Finding] = []
    # self-cycles on non-reentrant locks
    for lock, outs in sorted(graph.items()):
        if lock in outs and kinds.get(lock) == "lock" \
                and not lock.startswith("local:"):
            path, line, symbol = sites[(lock, lock)]
            findings.append(Finding(
                LOCK001.id, path, line, symbol, f"self:{lock}",
                f"non-reentrant {lock} re-acquired while already held "
                f"(threading.Lock deadlocks on re-entry)"))
    # multi-lock cycles via Tarjan SCC
    for scc in _sccs(graph):
        if len(scc) < 2:
            continue
        cyc = sorted(scc)
        # anchor the finding to a REAL edge inside the cycle (the
        # first in sorted pair order) — an arbitrary repo-wide edge
        # would make the fingerprint's path/symbol churn with scan
        # order
        site = next((sites[(a, b)] for a in cyc for b in cyc
                     if (a, b) in sites), None)
        assert site is not None, f"SCC {cyc} has no recorded edge"
        path, line, symbol = site
        findings.append(Finding(
            LOCK001.id, path, line, symbol,
            "cycle:" + ">".join(cyc),
            f"lock-order cycle between {', '.join(cyc)} — two threads "
            f"taking these edges concurrently deadlock"))
    return findings


def _sccs(graph: Dict[str, Set[str]]) -> List[Set[str]]:
    """Iterative Tarjan strongly-connected components."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    for root in sorted(graph):
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                elif nxt in on:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = set()
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                out.append(comp)
    return out


#: attribute types that are themselves synchronization/thread-safe and
#: need no external lock
_SAFE_CTOR_TAILS = {"Lock", "RLock", "Condition", "Event", "Queue",
                    "SimpleQueue", "deque", "Semaphore",
                    "BoundedSemaphore", "Barrier", "local", "Thread"}


def check_lock003(repo: RepoModel) -> List[Finding]:
    findings: List[Finding] = []
    edges = repo.call_edges(tier_b=False)
    for classes in repo.classes.values():
        for cls in classes:
            entry_keys = [cls.methods[m] for m in sorted(cls.thread_targets)
                          if m in cls.methods]
            # nested-def thread targets (loop() defined in start())
            for mkey in cls.methods.values():
                fn = repo.functions.get(mkey)
                if fn is None:
                    continue
                entry_keys.extend(k for k in fn.nested
                                  if k in repo.thread_entries)
            if not entry_keys:
                continue
            thread_keys = repo.reachable(entry_keys, edges, max_depth=3)
            # keep only this class's own methods/closures
            thread_keys = {k for k in thread_keys
                           if repo.functions[k].class_name == cls.name}
            method_keys = set(cls.methods.values())
            safe_attrs = _attr_classes(repo, cls)
            reported: Set[str] = set()
            for key in sorted(thread_keys):
                fn = repo.functions[key]
                for ev in fn.events:
                    if ev.kind != "selfstore" or ev.held:
                        continue
                    attr = ev.name
                    if attr in reported or attr in safe_attrs:
                        continue
                    # shared = some NON-thread method touches it too
                    if not _touched_outside(repo, method_keys
                                            - thread_keys, attr):
                        continue
                    reported.add(attr)
                    findings.append(Finding(
                        LOCK003.id, fn.module.relpath, ev.line,
                        fn.qualname, attr,
                        f"{cls.name}.{attr} mutated from background "
                        f"thread without a lock (also accessed from "
                        f"foreground methods)"))
    return findings


def _attr_classes(repo: RepoModel, cls) -> Set[str]:
    """Attrs holding sync primitives / thread handles (need no external
    lock — they ARE the synchronization)."""
    safe: Set[str] = set(cls.lock_attrs)
    for mkey in cls.methods.values():
        fn = repo.functions.get(mkey)
        if fn is None:
            continue
        for ev in fn.events:
            if ev.kind == "selfstore" and ev.chain \
                    and ev.chain[-1] in _SAFE_CTOR_TAILS:
                safe.add(ev.name)
    return safe


def _touched_outside(repo: RepoModel, other_keys: Set[str],
                     attr: str) -> bool:
    """Does any non-thread method of the class read or write
    ``self.<attr>``? (Event streams don't record attribute loads, so
    reads come from a direct AST scan.)"""
    for key in sorted(other_keys):
        fn = repo.functions.get(key)
        if fn is None or fn.name == "__init__":
            continue
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Attribute) and node.attr == attr \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self":
                return True
    return False
