"""`pio lint` — whole-repo static analysis for the defect classes the
rebuild keeps paying for by hand.

The Spark runtime this repo replaced (MLlib's managed executors) made
whole families of bugs impossible by construction; hand-rolled Python
threading + JAX dispatch re-opened them, and the PR 4 device-cache
gc-callback deadlock, the PR 7 nativelog lock convoy, and a string of
review-round catches (locks held across fsync, jit-capture recompile
hazards) are all instances of classes a mechanical AST pass can find.

Three rule families over ``predictionio_tpu/``:

- ``LOCK*`` — lock discipline: the repo-wide lock graph (order cycles =
  deadlock potential), locks held across blocking calls (FFI ``el_*``,
  fsync/file IO, HTTP, queue waits, jit dispatch — the PR 7 convoy
  class), attributes mutated from background threads without a lock.
- ``JAX*``  — hot-path hygiene: implicit host syncs in serving/fold
  code, jit-of-closure recompile hazards, jit built per request,
  donated-buffer reuse.
- ``COST*`` — hot-path cost: fsync, eager log-string formatting, or
  metric *registration* (vs. increment) on the ingest-ack/query paths.

Accepted findings live in ``conf/lint_baseline.json`` with one-line
justifications; the CI gate (tier-1 ``tests/test_static_analysis.py``
and ``pio lint --json``) is **zero NEW findings**.
"""

from predictionio_tpu.analysis.core import (Finding, RepoModel, Rule,
                                            RULES)
from predictionio_tpu.analysis.runner import (LintReport,
                                              default_baseline_path,
                                              default_root, run_lint)

__all__ = ["Finding", "RepoModel", "Rule", "RULES", "LintReport",
           "run_lint", "default_root", "default_baseline_path"]
