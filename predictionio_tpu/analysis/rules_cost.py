"""COST* rules: per-request overhead on the ingest-ack and query paths.

Roots are the HTTP-facing functions of the event server (single, batch
and columnar create routes + the admission batcher) and the engine
server (query handlers + micro-batcher); reachability runs over the
tier A+B call graph with a depth cap, so helpers the handlers call are
in scope but the whole repo is not.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from predictionio_tpu.analysis.core import (Finding, RepoModel,
                                            register_rule)

COST001 = register_rule(
    "COST001", "fsync on hot path",
    "os.fsync reachable from an ingest-ack or query handler. One fsync "
    "is ~ms on a loaded disk — it serializes the ack behind physical "
    "IO. Durability belongs on the group-commit cadence (PR 7) or the "
    "spill WAL's outage path, not per request.")

COST002 = register_rule(
    "COST002", "eager log-string formatting on hot path",
    "logging call whose message is built eagerly (f-string, %-format, "
    ".format(), concatenation) on a request path — the string is "
    "rendered even when the level is disabled. Use lazy %-style args: "
    "logger.debug(\"x=%s\", x).")

COST003 = register_rule(
    "COST003", "metric registration on hot path",
    "registry.counter()/gauge()/histogram()/lock_probe() reachable "
    "from a request handler. Registration takes the registry lock and "
    "allocates; resolve instruments once at init and call .inc()/"
    ".observe() on the hot path (the PR 2 obs contract).")

#: (module basename, function name) handler roots. Name-based so the
#: fixture suite can exercise the rules with small files of the same
#: shape.
HOT_PATH_ROOTS: Tuple[Tuple[str, str], ...] = (
    # event server: ingest-ack
    ("event_server.py", "_create_event"),
    ("event_server.py", "_create_event_inner"),
    ("event_server.py", "_batch_create"),
    ("event_server.py", "_columnar_post"),
    ("event_server.py", "_columnar_create"),
    ("event_server.py", "_insert_traced"),
    ("event_server.py", "_resilient_insert"),
    ("event_server.py", "_resilient_insert_batch"),
    ("event_server.py", "_resilient_insert_columnar"),
    ("event_server.py", "submit"),
    ("event_server.py", "_dispatch"),
    # engine server: query
    ("server.py", "handle_query"),
    ("server.py", "handle_query_batch"),
    ("batcher.py", "submit"),
    ("batcher.py", "_dispatch"),
    ("batcher.py", "_loop"),
    # bulk data plane (ISSUE 16): the per-chunk steady loop — an fsync,
    # eager log render, or metric registration here repeats per chunk
    # for the whole backfill
    ("reader.py", "_run"),
    ("upload.py", "stage"),
    ("pipeline.py", "run"),
)

_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical", "log"}
_REGISTRATION_ATTRS = {"counter", "gauge", "histogram", "gauge_func",
                       "counter_func", "summary_func"}
_REGISTRY_RECEIVERS = {"registry", "reg", "_registry", "get_registry",
                       "metrics"}


def hot_path_functions(repo: RepoModel) -> Set[str]:
    """Reachable set from the handler roots; memoized on the repo —
    the three COST rules share it."""
    cached = getattr(repo, "_hot_path_fns", None)
    if cached is not None:
        return cached
    roots = []
    for key, fn in repo.functions.items():
        if (fn.module.basename, fn.name) in HOT_PATH_ROOTS:
            roots.append(key)
    edges = repo.call_edges(tier_b=True)
    out = repo.reachable(roots, edges, max_depth=8)
    repo._hot_path_fns = out
    return out


def check_cost001(repo: RepoModel) -> List[Finding]:
    findings: List[Finding] = []
    for key in sorted(hot_path_functions(repo)):
        fn = repo.functions[key]
        for ev in fn.events:
            if ev.kind == "call" and ev.chain == ("os", "fsync"):
                findings.append(Finding(
                    COST001.id, fn.module.relpath, ev.line, fn.qualname,
                    "os.fsync",
                    "os.fsync on a request path — the ack waits on "
                    "physical IO"))
    return findings


def _eager_format_kind(call: ast.Call) -> str:
    """'' when the first logging arg is lazy (constant + args)."""
    if not call.args:
        return ""
    msg = call.args[0]
    if isinstance(msg, ast.JoinedStr):
        return "f-string"
    if isinstance(msg, ast.BinOp) and isinstance(msg.op, ast.Mod):
        return "%-format"
    if isinstance(msg, ast.BinOp) and isinstance(msg.op, ast.Add):
        return "concat"
    if isinstance(msg, ast.Call):
        inner = msg.func
        if isinstance(inner, ast.Attribute) and inner.attr == "format":
            return ".format()"
    return ""


def check_cost002(repo: RepoModel) -> List[Finding]:
    findings: List[Finding] = []
    for key in sorted(hot_path_functions(repo)):
        fn = repo.functions[key]
        for ev in fn.events:
            if ev.kind != "call" or len(ev.chain) < 2:
                continue
            if ev.chain[-1] not in _LOG_METHODS:
                continue
            root = ev.chain[0]
            if not (root in ("logger", "logging", "log", "_logger")
                    or root.endswith("logger")):
                continue
            kind = _eager_format_kind(ev.node) \
                if isinstance(ev.node, ast.Call) else ""
            if kind:
                findings.append(Finding(
                    COST002.id, fn.module.relpath, ev.line, fn.qualname,
                    f"{ev.chain[-1]}:{kind}",
                    f"logger.{ev.chain[-1]} message built eagerly "
                    f"({kind}) on a request path — use lazy %-style "
                    f"args"))
    return findings


def check_cost003(repo: RepoModel) -> List[Finding]:
    findings: List[Finding] = []
    for key in sorted(hot_path_functions(repo)):
        fn = repo.functions[key]
        if fn.name in ("__init__", "_register_metrics"):
            continue   # init-time by definition, not per-request
        for ev in fn.events:
            if ev.kind != "call":
                continue
            chain = ev.chain
            if chain[-1] == "lock_probe" and len(chain) == 1:
                findings.append(Finding(
                    COST003.id, fn.module.relpath, ev.line, fn.qualname,
                    "lock_probe",
                    "lock_probe() resolves the probe under a lock — "
                    "resolve once at init, observe on the hot path"))
                continue
            if chain[-1] in _REGISTRATION_ATTRS and len(chain) >= 2 \
                    and (chain[-2] in _REGISTRY_RECEIVERS
                         or chain[-2].endswith("registry")):
                findings.append(Finding(
                    COST003.id, fn.module.relpath, ev.line, fn.qualname,
                    f"register:{chain[-1]}",
                    f"{'.'.join(chain)}() registers a metric family "
                    f"per request — register at init, increment on "
                    f"the path"))
    return findings
