"""Naive Bayes kernels — multinomial (MLlib parity) and categorical (e2).

Replaces `org.apache.spark.mllib.classification.NaiveBayes.train` as used by
the classification template (reference:
examples/scala-parallel-classification/add-algorithm/src/main/scala/
NaiveBayesAlgorithm.scala:19-25) and the string-categorical
`CategoricalNaiveBayes` engine (reference:
e2/src/main/scala/io/prediction/e2/engine/CategoricalNaiveBayes.scala).

The Spark `combineByKey` count-aggregation becomes one-hot matmuls /
segment-sums on device; across a mesh the per-shard count matrices reduce
with a single psum (SURVEY.md section 7 step 4 — "NaiveBayes: one psum of
count matrices").
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.parallel.mesh import MeshContext, current_mesh


# ---------------------------------------------------------------------------
# Multinomial NB (MLlib NaiveBayes.train parity)
# ---------------------------------------------------------------------------

@dataclass
class MultinomialNBModel:
    pi: np.ndarray      # [C] log prior
    theta: np.ndarray   # [C, D] log likelihood
    labels: np.ndarray  # [C] original label values (float, like MLlib)

    def predict(self, x: np.ndarray) -> float:
        scores = self.pi + self.theta @ np.asarray(x, dtype=np.float64)
        return float(self.labels[int(np.argmax(scores))])


@functools.partial(__import__("jax").jit, static_argnames=("n_classes",))
def _nb_counts(features, label_ix, n_classes: int):
    """Per-class doc counts and feature sums via one-hot matmul (MXU-friendly;
    under a sharded batch dim GSPMD turns the sums into a psum)."""
    import jax.numpy as jnp
    onehot = jnp.equal(label_ix[:, None],
                       jnp.arange(n_classes)[None, :]).astype(jnp.float32)
    class_counts = onehot.sum(axis=0)                      # [C]
    feature_sums = jnp.einsum("nc,nd->cd", onehot, features,
                              preferred_element_type=jnp.float32)
    return class_counts, feature_sums


def multinomial_nb_train(features: np.ndarray, labels: np.ndarray,
                         lam: float = 1.0,
                         mesh: Optional[MeshContext] = None
                         ) -> MultinomialNBModel:
    """MLlib multinomial NaiveBayes:
      pi_c    = log((N_c + lam) / (N + C*lam))
      theta_cd = log((sum_d + lam) / (sum_all_d + D*lam))
    """
    mesh = mesh or current_mesh()
    features = np.asarray(features, dtype=np.float32)
    labels = np.asarray(labels)
    classes, label_ix = np.unique(labels, return_inverse=True)
    n_classes, n_features = len(classes), features.shape[1]
    feats_p, n = mesh.pad_to_multiple(features)
    # padded rows get label -1 -> one-hot all-zero -> contribute nothing
    lab_p = np.full(feats_p.shape[0], -1, dtype=np.int32)
    lab_p[:n] = label_ix
    class_counts, feature_sums = _nb_counts(
        mesh.put_batch(feats_p), mesh.put_batch(lab_p), n_classes)
    class_counts = np.asarray(class_counts, dtype=np.float64)
    feature_sums = np.asarray(feature_sums, dtype=np.float64)
    total = class_counts.sum()
    pi = np.log(class_counts + lam) - math.log(total + n_classes * lam)
    denom = np.log(feature_sums.sum(axis=1, keepdims=True)
                   + n_features * lam)
    theta = np.log(feature_sums + lam) - denom
    return MultinomialNBModel(pi=pi, theta=theta,
                              labels=classes.astype(np.float64))


# ---------------------------------------------------------------------------
# Categorical NB (e2 CategoricalNaiveBayes parity)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class LabeledPoint:
    """(e2/engine/LabeledPoint analog) — label + string features."""
    label: str
    features: Tuple[str, ...]


@dataclass
class CategoricalNBModel:
    """priors[label] = log P(label); likelihoods[label][pos][value] =
    log P(value | label) — exact counting, no smoothing, matching
    CategoricalNaiveBayes.scala."""
    priors: Dict[str, float]
    likelihoods: Dict[str, List[Dict[str, float]]]

    def log_score(self, point: LabeledPoint,
                  default=None) -> Optional[float]:
        """(CategoricalNaiveBayes.scala logScore): None when the label is
        unknown or a feature value is unseen and no default is given;
        `default` is a fn(featureLikelihoodMap) -> float."""
        if point.label not in self.priors:
            return None
        feat_l = self.likelihoods[point.label]
        total = self.priors[point.label]
        for pos, value in enumerate(point.features):
            m = feat_l[pos]
            if value in m:
                total += m[value]
            elif default is not None:
                total += default(m)
            else:
                return None
        return total

    def predict(self, features: Sequence[str],
                default=None) -> Optional[str]:
        best, best_score = None, -math.inf
        for label in self.priors:
            s = self.log_score(LabeledPoint(label, tuple(features)), default)
            if s is not None and s > best_score:
                best, best_score = label, s
        return best


def categorical_nb_train(points: Sequence[LabeledPoint],
                         mesh: Optional[MeshContext] = None
                         ) -> CategoricalNBModel:
    """Vocabulary build on host (BiMap-style dense ranks), counting on
    device: one [N] -> [C, P, V] scatter-count expressed as one-hot einsum."""
    mesh = mesh or current_mesh()
    if not points:
        return CategoricalNBModel({}, {})
    n_pos = len(points[0].features)
    labels = sorted({p.label for p in points})
    label_ix = {l: i for i, l in enumerate(labels)}
    vocabs: List[Dict[str, int]] = []
    for pos in range(n_pos):
        vals = sorted({p.features[pos] for p in points})
        vocabs.append({v: i for i, v in enumerate(vals)})
    max_v = max(len(v) for v in vocabs)
    n, c = len(points), len(labels)

    lab = np.array([label_ix[p.label] for p in points], dtype=np.int32)
    feat = np.zeros((n, n_pos), dtype=np.int32)
    for j, p in enumerate(points):
        for pos in range(n_pos):
            feat[j, pos] = vocabs[pos][p.features[pos]]

    import jax.numpy as jnp
    import jax

    @functools.partial(jax.jit, static_argnames=("c", "v"))
    def _counts(lab, feat, c: int, v: int):
        lab1 = jax.nn.one_hot(lab, c, dtype=jnp.float32)       # [N, C]
        feat1 = jax.nn.one_hot(feat, v, dtype=jnp.float32)     # [N, P, V]
        counts = jnp.einsum("nc,npv->cpv", lab1, feat1,
                            preferred_element_type=jnp.float32)
        return counts, lab1.sum(axis=0)

    feat_p, real = mesh.pad_to_multiple(feat)
    lab_p = np.full(feat_p.shape[0], -1, dtype=np.int32)
    lab_p[:real] = lab
    counts, label_counts = _counts(mesh.put_batch(lab_p),
                                   mesh.put_batch(feat_p), c, max_v)
    counts = np.asarray(counts, dtype=np.float64)
    label_counts = np.asarray(label_counts, dtype=np.float64)

    priors = {l: math.log(label_counts[i] / n) for l, i in label_ix.items()}
    likelihoods: Dict[str, List[Dict[str, float]]] = {}
    for l, i in label_ix.items():
        per_pos = []
        for pos in range(n_pos):
            m = {}
            for v, vi in vocabs[pos].items():
                cnt = counts[i, pos, vi]
                if cnt > 0:
                    m[v] = math.log(cnt / label_counts[i])
            per_pos.append(m)
        likelihoods[l] = per_pos
    return CategoricalNBModel(priors=priors, likelihoods=likelihoods)
