"""Cosine-similarity scoring with business-rule filters — on-device top-k.

Replaces the similarproduct template's driver-side cosine scan
(reference: examples/scala-parallel-similarproduct/multi/src/main/scala/
ALSAlgorithm.scala:146-190: score = sum over query items of cosine(qf, f),
keep score > 0, apply category/white/black filters, top N) with one jitted
masked matmul + `lax.top_k` over the whole item-factor table resident in
HBM. Filters arrive as a packed boolean mask built on host.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import numpy as np


@functools.partial(__import__("jax").jit, static_argnames=("k",))
def _cosine_topk(query_vecs, item_norms, allowed, k: int):
    """query_vecs [Q, R] (raw), item_norms [I, R] (L2-normalized rows),
    allowed [I] bool. Score = sum_q cos(q, item); items with score <= 0 or
    not allowed are excluded (score -> -inf)."""
    import jax
    import jax.numpy as jnp
    qn = query_vecs / jnp.maximum(
        jnp.linalg.norm(query_vecs, axis=-1, keepdims=True), 1e-12)
    scores = jnp.einsum("qr,ir->i", qn, item_norms,
                        preferred_element_type=jnp.float32)
    scores = jnp.where(allowed & (scores > 0), scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def _masked_topk_impl(query_mat, item_table, allowed, k: int,
                      filter_positive: bool):
    """Traced body shared by the packed and unpacked masked-top-k
    executables (unjitted — always composed under one of the two jit
    wrappers below, so both variants rank identically)."""
    import jax
    import jax.numpy as jnp
    scores = jnp.einsum("br,ir->bi", query_mat, item_table,
                        preferred_element_type=jnp.float32)
    if filter_positive:
        allowed = allowed & (scores > 0)
    scores = jnp.where(allowed, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


@functools.partial(__import__("jax").jit,
                   static_argnames=("k", "filter_positive"))
def _batched_masked_topk(query_mat, item_table, allowed, k: int,
                         filter_positive: bool):
    """query_mat [B, R], item_table [I, R], allowed [B, I] bool.
    Score = query_mat @ item_table.T; not-allowed items (and, when
    filter_positive, items with score <= 0 — the cosine templates' rule)
    are excluded (score -> -inf). One device call for the whole batch."""
    return _masked_topk_impl(query_mat, item_table, allowed, k=k,
                             filter_positive=filter_positive)


@functools.partial(__import__("jax").jit,
                   static_argnames=("k", "filter_positive", "p"))
def _batched_masked_topk_packed(query_mat, item_table, allowed, k: int,
                                filter_positive: bool, p: int):
    """:func:`_batched_masked_topk` with the readback-plane pack fused
    on (ISSUE 19): identical ranking, one contiguous ids+quantized-
    scores output payload per window."""
    from predictionio_tpu.ops import readback
    scores, idx = _masked_topk_impl(query_mat, item_table, allowed,
                                    k=k,
                                    filter_positive=filter_positive)
    return readback.pack_device(scores, idx, p)


def _aot_masked_topk_builder(b: int = 0, i: int = 0, r: int = 0,
                             k: int = 0, fp: int = 0, s: int = 0,
                             p: int = 0):
    """(jit_fn, example avals, statics) for one masked-top-k bucket
    (the compile plane's batch_predict executable for the cosine /
    filtered model families). ``s`` > 0 lowers the model-sharded
    variant with sharding-aware avals (item table over the model axis,
    masks sharded on the item dim). ``p`` > 0 lowers the packed-
    readback variant (ISSUE 19) whose single output aval is the
    contiguous payload — warmed packed buckets compile nothing at
    serve time."""
    import jax
    sds = jax.ShapeDtypeStruct
    if s:
        from predictionio_tpu.compile.aot import sharded_aval
        from predictionio_tpu.ops.topk import (make_batched_sharded_topk,
                                               sharded_k_split)
        from predictionio_tpu.parallel.mesh import model_mesh
        mesh = model_mesh(s)
        k_local, k_final = sharded_k_split(k, i, s)
        fn = make_batched_sharded_topk(mesh, k_local, k_final,
                                       has_mask=True,
                                       filter_positive=bool(fp),
                                       pack=p)
        return (fn,
                (sharded_aval((b, r), np.float32, mesh=mesh),
                 sharded_aval((i, r), np.float32, "model", None,
                              mesh=mesh),
                 sds((), np.int32),
                 sharded_aval((b, i), bool, None, "model", mesh=mesh)),
                {})
    avals = (sds((b, r), np.float32), sds((i, r), np.float32),
             sds((b, i), bool))
    if p:
        return (_batched_masked_topk_packed, avals,
                {"k": k, "filter_positive": bool(fp), "p": p})
    return (_batched_masked_topk, avals,
            {"k": k, "filter_positive": bool(fp)})


_aot_specs_registered = False


def register_aot_specs():
    """Idempotently register the masked-top-k executable spec with the
    compile plane (ISSUE 9)."""
    global _aot_specs_registered
    if _aot_specs_registered:
        return
    from predictionio_tpu.obs import costmon
    from predictionio_tpu.compile.aot import get_aot
    get_aot().register(costmon.BATCH_PREDICT_MASKED,
                       _aot_masked_topk_builder)
    _aot_specs_registered = True


def masked_topk_dims(n_items: int, rank: int, batch: int, k: int,
                     filter_positive: bool = True) -> dict:
    """Shape-bucket dims for one masked-top-k call — shared by the
    serve dispatch and the deploy/swap warm path."""
    from predictionio_tpu.compile import buckets as B
    from predictionio_tpu.ops import readback
    i_b = B.bucket_rows(n_items)
    return {"b": B.bucket_batch(batch), "i": i_b, "r": int(rank),
            "k": min(B.bucket_batch(k, floor=B.K_FLOOR), i_b),
            "fp": int(bool(filter_positive)),
            "p": readback.pack_flag()}


def masked_top_k_batch(item_table: np.ndarray, query_vecs: np.ndarray,
                       masks: np.ndarray, k: int,
                       filter_positive: bool = True
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Batched masked dot top-k: one jitted call for B queries.

    query_vecs [B, R] (already in the scoring space: raw user factors for
    dot scoring, summed-normalized item vectors for cosine), masks [B, I]
    bool. Every moving dim is shape-bucketed (ISSUE 9 compile plane):
    batch and k pad to powers of two, the item table uploads at its
    vocab bucket (padding rows masked out), so neither request-batch
    size, client-chosen num, NOR catalog growth inside a bucket mints a
    new program — and the dispatch resolves through the AOT registry,
    so a warmed bucket runs zero trace / zero compile.
    filter_positive additionally drops score <= 0 (cosine-template
    semantics; explicit-ALS callers pass False). Returns ([B, k'],
    [B, k']) numpy arrays with k' >= min(k, I); rows may contain -inf
    for excluded slots (caller filters non-finite and slices to its
    own num)."""
    return masked_top_k_batch_begin(item_table, query_vecs, masks, k,
                                    filter_positive=filter_positive)()


def masked_top_k_batch_begin(item_table: np.ndarray,
                             query_vecs: np.ndarray, masks: np.ndarray,
                             k: int, filter_positive: bool = True):
    """Two-phase sibling of :func:`masked_top_k_batch` (ISSUE 14
    pipelined executor): enqueue the masked ranking and return
    ``finish() -> (scores, idx)`` which performs the deferred
    device->host readback, so the completion stage can overlap the
    next window's formation."""
    from predictionio_tpu.compile import buckets as B
    from predictionio_tpu.compile.aot import get_aot
    from predictionio_tpu.obs import costmon
    from predictionio_tpu.ops import readback
    from predictionio_tpu.parallel.sharded_table import is_sharded
    from predictionio_tpu.utils.device_cache import cached_put_rows
    register_aot_specs()
    if is_sharded(item_table):
        return _masked_top_k_batch_sharded_begin(
            item_table, query_vecs, masks, k, filter_positive)
    n_items = item_table.shape[0]
    n = query_vecs.shape[0]
    dims = masked_topk_dims(n_items, query_vecs.shape[1], n, k,
                            filter_positive)
    qp = np.zeros((dims["b"], query_vecs.shape[1]), dtype=np.float32)
    qp[:n] = query_vecs
    # padding rows of the bucketed table stay masked False -> -inf
    mp = np.zeros((dims["b"], dims["i"]), dtype=bool)
    mp[:n, :n_items] = masks
    k_eff, p = dims["k"], dims["p"]
    item_dev = cached_put_rows(item_table, dims["i"])
    if p:
        packed = get_aot().dispatch(
            costmon.BATCH_PREDICT_MASKED, dims,
            lambda *a: _batched_masked_topk_packed(
                *a, k=k_eff, filter_positive=filter_positive, p=p),
            qp, item_dev, mp)
        fetch = readback.begin_fetch_packed(packed, p)
    else:
        scores, idx = get_aot().dispatch(
            costmon.BATCH_PREDICT_MASKED, dims,
            lambda *a: _batched_masked_topk(
                *a, k=k_eff, filter_positive=filter_positive),
            qp, item_dev, mp)
        fetch = readback.begin_fetch(scores, idx)
    if B.should_promote(n_items, dims["i"]):
        get_aot().ensure(
            costmon.BATCH_PREDICT_MASKED,
            dict(dims, i=B.next_bucket(dims["i"]),
                 k=min(k_eff, B.next_bucket(dims["i"]))),
            background=True)

    def finish() -> Tuple[np.ndarray, np.ndarray]:
        scores_h, idx_h = fetch()
        return scores_h[:n], idx_h[:n]
    return finish


def _masked_top_k_batch_sharded_begin(item_table,
                                      query_vecs: np.ndarray,
                                      masks: np.ndarray, k: int,
                                      filter_positive: bool):
    """Sharded route of :func:`masked_top_k_batch`: the item table
    stays model-sharded in HBM (its resident handle), the padded
    [B, I] candidate mask uploads sharded over the item dim, and the
    ranking is the per-shard top-k + cross-shard merge. Same
    ``batch_predict_masked`` label; the ``s`` dim keeps sharded and
    replicated buckets from ever aliasing in the AOT registry.
    Returns the pipelined ``finish()`` readback callable."""
    from predictionio_tpu.compile import buckets as B
    from predictionio_tpu.obs import costmon
    from predictionio_tpu.ops import readback
    from predictionio_tpu.ops.topk import batched_sharded_top_k_begin
    from predictionio_tpu.parallel.mesh import model_mesh
    mesh = model_mesh(item_table.n_shards)
    n_items = item_table.shape[0]
    n = query_vecs.shape[0]
    i_b = max(item_table.padded_rows,
              B.bucket_rows_sharded(n_items, item_table.n_shards))
    dims = {"b": B.bucket_batch(n), "i": i_b,
            "r": int(query_vecs.shape[1]),
            "k": min(B.bucket_batch(k, floor=B.K_FLOOR), i_b),
            "fp": int(bool(filter_positive)),
            "s": item_table.n_shards,
            "p": readback.pack_flag()}
    qp = np.zeros((dims["b"], query_vecs.shape[1]), dtype=np.float32)
    qp[:n] = query_vecs
    mp_ = np.zeros((dims["b"], dims["i"]), dtype=bool)
    mp_[:n, :n_items] = masks
    fetch = batched_sharded_top_k_begin(
        item_table.device(mesh, target_rows=i_b), qp, n_items,
        dims["k"], mesh, masks=mp_, filter_positive=filter_positive,
        label=costmon.BATCH_PREDICT_MASKED, dims=dims)

    def finish() -> Tuple[np.ndarray, np.ndarray]:
        scores, idx = fetch()
        return scores[:n], idx[:n]
    return finish


def unpack_top_k_rows(scores_row: np.ndarray, idx_row: np.ndarray,
                      num: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-query view of one masked_top_k_batch row: slice to the query's
    own num and drop -inf (excluded) slots."""
    scores_row = scores_row[:num]
    idx_row = idx_row[:num]
    keep = np.isfinite(scores_row)
    return scores_row[keep], idx_row[keep]


def normalize_rows(factors: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(factors, axis=-1, keepdims=True)
    return (factors / np.maximum(norms, 1e-12)).astype(np.float32)


def cosine_top_k(item_factors_normalized: np.ndarray,
                 query_vecs: np.ndarray, k: int,
                 allowed_mask: Optional[np.ndarray] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (scores, item_indices), length <= k, excluding -inf entries."""
    from predictionio_tpu.utils.device_cache import cached_put
    n_items = item_factors_normalized.shape[0]
    if allowed_mask is None:
        allowed_mask = np.ones(n_items, dtype=bool)
    k_eff = min(k, n_items)
    scores, idx = _cosine_topk(
        np.asarray(query_vecs, dtype=np.float32),
        cached_put(item_factors_normalized), allowed_mask, k_eff)
    scores = np.asarray(scores)
    idx = np.asarray(idx)
    keep = np.isfinite(scores)
    return scores[keep], idx[keep]


def build_filter_mask(n_items: int,
                      exclude: Sequence[int] = (),
                      white_list: Optional[Sequence[int]] = None,
                      item_categories: Optional[Sequence[Optional[set]]] = None,
                      categories: Optional[set] = None) -> np.ndarray:
    """Host-side candidate mask implementing isCandidateItem
    (ALSAlgorithm.scala:192+): whitelist wins, blacklist/query items
    excluded, category intersection required when given."""
    mask = np.ones(n_items, dtype=bool)
    if white_list is not None:
        mask[:] = False
        wl = np.asarray(list(white_list), dtype=np.int64)
        wl = wl[(wl >= 0) & (wl < n_items)]
        mask[wl] = True
    ex = np.asarray(list(exclude), dtype=np.int64)
    ex = ex[(ex >= 0) & (ex < n_items)]
    mask[ex] = False
    if categories is not None and item_categories is not None:
        cat = np.array([bool(c and (c & categories))
                        for c in item_categories], dtype=bool)
        mask &= cat
    return mask


@functools.partial(__import__("jax").jit, donate_argnums=(0,))
def _gram_accum(G, chunk):
    import jax.numpy as jnp
    return G + jnp.einsum("ci,cj->ij", chunk, chunk,
                          preferred_element_type=jnp.float32)


def item_cosine_similarities(user_ix: np.ndarray, item_ix: np.ndarray,
                             n_users: int, n_items: int,
                             threshold: float = 0.0,
                             chunk_users: int = 4096) -> np.ndarray:
    """Exact all-pairs item-column cosine similarity from binary
    (user, item) interactions — the role of RowMatrix.columnSimilarities
    in the dimsum variant (reference: examples/experimental/
    scala-parallel-similarproduct-dimsum/.../DIMSUMAlgorithm.scala:125-131).

    DIMSUM itself is a sampling approximation invented to bound Spark
    shuffle traffic; on TPU the co-occurrence Gram G = M^T M streams
    through the MXU in user-row chunks (items^2 accumulator resident in
    HBM, never a dense [users, items] matrix), so we compute the exact
    cosine and use `threshold` only to sparsify the result the way
    columnSimilarities(threshold) drops sub-threshold entries.

    Duplicate (user, item) pairs collapse to a single binary entry, same
    as the variant's "keep one copy" dedup. Diagonal is zeroed.
    """
    import jax.numpy as jnp
    order = np.argsort(user_ix, kind="stable")
    u, i = user_ix[order], item_ix[order]
    G = jnp.zeros((n_items, n_items), jnp.float32)
    for start in range(0, n_users, chunk_users):
        stop = start + chunk_users
        lo, hi = np.searchsorted(u, [start, stop])
        chunk = np.zeros((min(chunk_users, n_users - start), n_items),
                         np.float32)
        chunk[u[lo:hi] - start, i[lo:hi]] = 1.0  # set, not add: binary dedup
        G = _gram_accum(G, jnp.asarray(chunk))
    G = np.asarray(G)
    d = np.sqrt(np.maximum(np.diag(G), 1e-12))
    S = G / np.outer(d, d)
    np.fill_diagonal(S, 0.0)
    if threshold > 0:
        S[S < threshold] = 0.0
    return S.astype(np.float32)
