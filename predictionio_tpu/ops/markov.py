"""Markov chain over a sparse transition-count matrix.

Rebuilds the reference's ``MarkovChain`` engine
(reference: e2/src/main/scala/io/prediction/e2/engine/MarkovChain.scala):
row-normalize counts, keep the top-N entries per row, predict next-state
probabilities as state-vector x matrix. Device-side: the pruned matrix is a
dense [S, N] (index, prob) pair of arrays so predict is one gather+scatter
einsum, avoiding host sparse structures.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np


@dataclass
class MarkovChainModel:
    """Top-N row-normalized transitions. indices[s, j] = target state (or -1
    padding), probs[s, j] = P(target | s)."""
    indices: np.ndarray  # [S, N] int32
    probs: np.ndarray    # [S, N] float32
    n_states: int
    top_n: int

    def predict(self, current_state: np.ndarray) -> np.ndarray:
        """next[j] = sum_s current[s] * P(j | s) (MarkovChain.scala predict)."""
        return np.asarray(_mc_predict(
            self.indices, self.probs,
            np.asarray(current_state, dtype=np.float32), self.n_states))


@functools.partial(__import__("jax").jit, static_argnames=("n_states",))
def _mc_predict(indices, probs, current, n_states: int):
    import jax.numpy as jnp
    contrib = probs * current[:, None]          # [S, N]
    flat_idx = jnp.where(indices >= 0, indices, n_states)
    out = jnp.zeros(n_states + 1, dtype=jnp.float32)
    out = out.at[flat_idx.reshape(-1)].add(contrib.reshape(-1))
    return out[:n_states]


def markov_chain_train(row_idx: np.ndarray, col_idx: np.ndarray,
                       counts: np.ndarray, n_states: int,
                       top_n: int) -> MarkovChainModel:
    """Build the pruned transition model from COO counts (host numpy: the
    data is tiny next to factorization workloads)."""
    row_idx = np.asarray(row_idx, dtype=np.int64)
    col_idx = np.asarray(col_idx, dtype=np.int64)
    counts = np.asarray(counts, dtype=np.float64)
    indices = np.full((n_states, top_n), -1, dtype=np.int32)
    probs = np.zeros((n_states, top_n), dtype=np.float32)
    order = np.argsort(row_idx, kind="stable")
    r, c, v = row_idx[order], col_idx[order], counts[order]
    bounds = np.searchsorted(r, np.arange(n_states + 1))
    for s in range(n_states):
        lo, hi = bounds[s], bounds[s + 1]
        if lo == hi:
            continue
        total = v[lo:hi].sum()
        k = min(top_n, hi - lo)
        top = np.argsort(-v[lo:hi], kind="stable")[:k]
        sel_c = c[lo:hi][top]
        sel_p = v[lo:hi][top] / total
        # reference sorts kept entries by column index
        colsort = np.argsort(sel_c)
        indices[s, :k] = sel_c[colsort]
        probs[s, :k] = sel_p[colsort]
    return MarkovChainModel(indices=indices, probs=probs,
                            n_states=n_states, top_n=top_n)
