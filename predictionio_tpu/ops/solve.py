"""Batched SPD solves for the normal-equation sweeps — the MXU-native
replacement for factorize-and-substitute.

Why not Cholesky: XLA's TPU cholesky + triangular_solve on batched
[B, rank, rank] systems runs at ~0.05% MXU utilization (measured: ~9.3 s
of a 9.8 s ML-20M ALS iteration; see docs/benchmarks.md). Iterative
methods whose only primitive is multiply-accumulate map to the hardware
instead, and the ALS normal matrix A = Gram + lam*n*I arrives
pre-regularized — its condition number is bounded by
~rank*E[v^2]/lam — so fixed iteration counts converge to f32 working
precision.

Production path (TPU): batched conjugate gradient in a Pallas kernel,
grid over 16-entity tiles whose [16, R, R] systems stay VMEM-resident for
every iteration (HBM reads A exactly once). Measured on v5e at B=2048,
R=200, cond~230: 27 ms and rel err 3e-6, vs 140 ms for XLA
cholesky+trsm.

Also provided: the Schulz/Hotelling–Bodewig inverse iteration
X_{k+1} = X_k(2I - A X_k) (pure batched MXU matmuls, bf16-safe because
self-correcting, plus two f32 refinement steps) in jnp and Pallas forms —
slower than CG here (~35 ms) but useful where an explicit inverse or a
matmul-only formulation is wanted — and LAPACK-style `cholesky_solve`,
the CPU path and numerical reference.

`spd_solve` picks per backend: cholesky on CPU, CG-Pallas on TPU, jnp CG
under GSPMD meshes.

Replaces the `choleskyDecomposition.solve` step of MLlib ALS
(reference consumer: examples/scala-parallel-recommendation/custom-prepartor/
src/main/scala/ALSAlgorithm.scala:55 `ALS.train` -> mllib
NNLS/CholeskySolver).
"""

from __future__ import annotations

import functools

import numpy as np


def _tpu_compiler_params(**kw):
    """pltpu.CompilerParams across jax versions (older: TPUCompilerParams)."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)


def _schulz_iters_default(rank: int) -> int:
    # quadratic convergence: error after k steps ~ (1 - 1/kappa)^(2^k);
    # 18 doublings resolve kappa ~ 1e4 to f32 eps with margin
    return 18


def schulz_solve(A, b, iters: int | None = None, compute_dtype="bfloat16"):
    """Solve A x = b for batched SPD A [B, R, R], b [B, R] by Schulz
    iteration. Pure jnp — runs on any backend, used as the Pallas
    kernel's correctness reference."""
    import jax
    import jax.numpy as jnp

    rank = A.shape[-1]
    iters = iters or _schulz_iters_default(rank)
    cd = jnp.dtype(compute_dtype)
    alpha = 1.0 / jnp.maximum(
        jnp.max(jnp.sum(jnp.abs(A), axis=-1), axis=-1), 1e-30)   # 1/||A||_inf
    eye = jnp.eye(rank, dtype=jnp.float32)
    X = alpha[:, None, None] * eye

    def body(_, X):
        Y = jnp.einsum("brs,bst->brt", A.astype(cd), X.astype(cd),
                       preferred_element_type=jnp.float32)
        return 2.0 * X - jnp.einsum("brs,bst->brt", X.astype(cd),
                                    Y.astype(cd),
                                    preferred_element_type=jnp.float32)

    X = jax.lax.fori_loop(0, iters, body, X)
    x = jnp.einsum("brs,bs->br", X, b, preferred_element_type=jnp.float32)
    # two f32 iterative-refinement steps: with X ~ A^-1 to epsilon_it, each
    # step multiplies the solution error by epsilon_it — recovers near-f32
    # solutions even when the iterate converged in bf16
    for _ in range(2):
        r = b - jnp.einsum("brs,bs->br", A, x,
                           preferred_element_type=jnp.float32)
        x = x + jnp.einsum("brs,bs->br", X, r,
                           preferred_element_type=jnp.float32)
    return x


def _schulz_kernel(a_ref, b_ref, x_ref, *, iters: int, compute_dtype):
    import jax
    import jax.numpy as jnp

    A = a_ref[:]                                   # [BT, R, R] f32, VMEM
    rank = A.shape[-1]
    cd = jnp.dtype(compute_dtype)
    alpha = 1.0 / jnp.maximum(
        jnp.max(jnp.sum(jnp.abs(A), axis=-1), axis=-1), 1e-30)
    eye = jnp.eye(rank, dtype=jnp.float32)[None]
    X = alpha[:, None, None] * eye
    Abf = A.astype(cd)
    bmm = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)

    def body(_, X):
        Y = bmm(Abf, X.astype(cd))
        return 2.0 * X - bmm(X.astype(cd), Y.astype(cd))

    X = jax.lax.fori_loop(0, iters, body, X)
    bvec = b_ref[:]
    bmv = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    x = bmv(X, bvec)
    for _ in range(2):   # f32 iterative refinement (see schulz_solve)
        x = x + bmv(X, bvec - bmv(A, x))
    x_ref[:] = x


def schulz_solve_pallas(A, b, iters: int | None = None,
                        compute_dtype="bfloat16", tile: int = 8):
    """TPU kernel: grid over batch tiles; each tile's inverse iterate lives
    in VMEM for all `iters` Schulz steps, so HBM traffic is one read of A +
    one write of x (vs one read/write of [B,R,R] per step for the XLA
    loop)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, rank = A.shape[0], A.shape[-1]
    iters = iters or _schulz_iters_default(rank)
    if B % tile != 0:
        pad = tile - B % tile
        A = jnp.concatenate(
            [A, jnp.broadcast_to(jnp.eye(rank, dtype=A.dtype),
                                 (pad, rank, rank))], axis=0)
        b = jnp.concatenate([b, jnp.zeros((pad, rank), b.dtype)], axis=0)
    nb = A.shape[0] // tile
    kernel = functools.partial(_schulz_kernel, iters=iters,
                               compute_dtype=compute_dtype)
    x = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((A.shape[0], rank), jnp.float32),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((tile, rank, rank), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, rank), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, rank), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
    )(A.astype(jnp.float32), b)
    return x[:B]


def cholesky_solve(A, b):
    """LAPACK-style direct solve — the CPU path and the numerical
    reference."""
    import jax
    chol = jax.lax.linalg.cholesky(A)
    x = jax.lax.linalg.triangular_solve(chol, b[..., None], left_side=True,
                                        lower=True)
    return jax.lax.linalg.triangular_solve(
        chol, x, left_side=True, lower=True, transpose_a=True)[..., 0]


def cg_solve(A, b, iters: int = 48):
    """Batched Jacobi-preconditioned conjugate gradient on SPD A [B,R,R] —
    pure jnp reference (and the GSPMD-mesh path, where pallas_call can't
    take sharded operands). The ALS normal matrix's per-entity regularizer
    lam*n*I plus its dominant diagonal keep the *preconditioned* condition
    number small, so a fixed iteration count converges to f32 working
    precision; adversarial spectra need iters ~ sqrt(cond)*ln(1/eps)
    (tests/test_solve.py covers both)."""
    import jax
    import jax.numpy as jnp

    dinv = 1.0 / jnp.maximum(
        jnp.diagonal(A, axis1=-2, axis2=-1), 1e-30)        # Jacobi M^-1
    x = jnp.zeros_like(b)
    r = b
    z = dinv * r
    p = z
    rz = jnp.sum(r * z, axis=1)

    def body(_, c):
        x, r, p, rz = c
        Ap = jnp.einsum("brs,bs->br", A, p,
                        preferred_element_type=jnp.float32)
        alpha = rz / jnp.maximum(jnp.sum(p * Ap, axis=1), 1e-30)
        x = x + alpha[:, None] * p
        r = r - alpha[:, None] * Ap
        z = dinv * r
        rz2 = jnp.sum(r * z, axis=1)
        p = z + (rz2 / jnp.maximum(rz, 1e-30))[:, None] * p
        return (x, r, p, rz2)

    x, *_ = jax.lax.fori_loop(0, iters, body, (x, r, p, rz))
    return x


def _cg_kernel(a_ref, b_ref, x_ref, *, iters: int):
    """Per-tile Jacobi-PCG: A stays VMEM-resident for every iteration; the
    matvec contracts over the sublane axis (A is symmetric, so A[t,s,:]
    rows serve as columns), which reduces to cheap vreg adds instead of
    cross-lane shuffles."""
    import jax
    import jax.numpy as jnp

    A = a_ref[:]
    bb = b_ref[:]
    rank = A.shape[-1]
    eye = jnp.eye(rank, dtype=jnp.float32)[None]
    dinv = 1.0 / jnp.maximum(jnp.sum(A * eye, axis=1), 1e-30)

    def mv(p):
        return jnp.sum(A * p[:, :, None], axis=1)

    x = jnp.zeros_like(bb)
    r = bb
    z = dinv * r
    p = z
    rz = jnp.sum(r * z, axis=1)

    def body(_, c):
        x, r, p, rz = c
        Ap = mv(p)
        alpha = rz / jnp.maximum(jnp.sum(p * Ap, axis=1), 1e-30)
        x = x + alpha[:, None] * p
        r = r - alpha[:, None] * Ap
        z = dinv * r
        rz2 = jnp.sum(r * z, axis=1)
        p = z + (rz2 / jnp.maximum(rz, 1e-30))[:, None] * p
        return (x, r, p, rz2)

    x, *_ = jax.lax.fori_loop(0, iters, body, (x, r, p, rz))
    x_ref[:] = x


def cg_solve_pallas(A, b, iters: int = 48, tile: int = 16):
    """TPU production solver: grid over batch tiles of 16 entities, each
    tile's [16, R, R] system VMEM-resident across all CG iterations.
    Measured (v5e, B=2048, R=200): ~27 ms vs 140 ms for XLA batched
    cholesky+trsm — and the full ALS sweep goes from 9.8 s to ~2 s per
    ML-20M iteration."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, rank = A.shape[0], A.shape[-1]
    # pad the batch UP to a full tile (never shrink the tile: sub-8 batch
    # dims produce vector shapes Mosaic can't reduce over)
    if B % tile != 0:
        pad = tile - B % tile
        A = jnp.concatenate(
            [A, jnp.broadcast_to(jnp.eye(rank, dtype=A.dtype),
                                 (pad, rank, rank))], axis=0)
        b = jnp.concatenate([b, jnp.zeros((pad, rank), b.dtype)], axis=0)
    kernel = functools.partial(_cg_kernel, iters=iters)
    x = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((A.shape[0], rank), jnp.float32),
        grid=(A.shape[0] // tile,),
        in_specs=[
            pl.BlockSpec((tile, rank, rank), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, rank), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, rank), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        compiler_params=_tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024),
    )(A.astype(jnp.float32), b)
    return x[:B]


def _blocked_cholesky_solve(A, b, panel: int = 8):
    """Batched blocked (right-looking) Cholesky + blocked substitution,
    written so every slice is static AND scatter-free: Mosaic's TPU
    lowering has no scatter, so instead of writing panels back into a
    full L, the Python panel loop keeps each panel's factors in lists
    (static slices recover any L block during substitution), per-column
    updates are where-masks over a traced broadcasted_iota (an eager
    jnp.arange would be captured as a kernel constant, which pallas_call
    rejects), and the trailing Schur update recurses on the shrinking
    submatrix rather than scattering into A. Flop layout per system:
    ~R^3/3 in trailing matmul updates (MXU) + 2R^2 substitution, vs CG's
    ~96 R^2 of cross-sublane VPU matvecs and Schulz's ~72 R^3 of
    matmuls. Used inside the Pallas tile kernel AND directly
    (interpret/CPU correctness path, GSPMD meshes as 'chol_blocked').

    A: [B, R, R] SPD (R % panel == 0 — wrappers pad), b: [B, R]."""
    import jax
    import jax.numpy as jnp

    B, R = b.shape
    PW = panel
    A = jnp.asarray(A, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    rank_in = R
    if R % PW:
        # pad to a whole panel with an identity block (decoupled rows
        # solve to 0) — without this, trailing rows would silently never
        # be factored. Outside-kernel path only: wrappers pre-pad before
        # pallas_call, so jnp.pad/jnp.eye never trace inside a kernel.
        pad = PW - R % PW
        A = (jnp.pad(A, ((0, 0), (0, pad), (0, pad)))
             + jnp.pad(jnp.eye(pad, dtype=jnp.float32),
                       ((rank_in, 0), (rank_in, 0)))[None])
        b = jnp.pad(b, ((0, 0), (0, pad)))
        R = R + pad
    nP = R // PW
    # [1, PW] traced column ids — where-masks replace .at[] column sets
    cids = jax.lax.broadcasted_iota(jnp.int32, (1, PW), 1)
    L11s, L21s = [], []
    Atr = A                                    # trailing [B, M, M]
    for p in range(nP):
        A11 = Atr[:, :PW, :PW]                 # [B, PW, PW]
        # unblocked factor of the diagonal block (PW static steps)
        L11 = jnp.zeros_like(A11)
        for c in range(PW):
            d = jnp.sqrt(jnp.maximum(A11[:, c, c], 1e-30))
            col = A11[:, :, c] / d[:, None]    # [B, PW]
            col = jnp.where(cids >= c, col, 0.0)   # lower part only
            L11 = jnp.where((cids == c).reshape(1, 1, PW),
                            col[:, :, None], L11)
            A11 = A11 - col[:, :, None] * col[:, None, :]
        L11s.append(L11)
        if Atr.shape[1] > PW:
            A21 = Atr[:, PW:, :PW]             # [B, M, PW]
            # L21 L11^T = A21: forward substitution, PW static steps
            L21 = jnp.zeros_like(A21)
            for c in range(PW):
                acc = A21[:, :, c]
                for k in range(c):
                    acc = acc - L21[:, :, k] * L11[:, c, k][:, None]
                L21 = jnp.where((cids == c).reshape(1, 1, PW),
                                (acc / L11[:, c, c][:, None])[:, :, None],
                                L21)
            L21s.append(L21)
            # trailing syrk — the MXU step: A22 -= L21 @ L21^T
            upd = jnp.einsum("bmk,bnk->bmn", L21, L21,
                             preferred_element_type=jnp.float32)
            Atr = Atr[:, PW:, PW:] - upd
        else:
            L21s.append(None)

    def _l_block(p, q):
        # L[lo_p:hi_p, lo_q:hi_q] for p > q, recovered from panel q's
        # below-diagonal strip (its row 0 is global row hi_q)
        o = (p - q - 1) * PW
        return L21s[q][:, o:o + PW, :]

    # blocked forward substitution: L y = b
    ys = []
    for p in range(nP):
        rhs = b[:, p * PW:(p + 1) * PW]
        for q in range(p):
            rhs = rhs - jnp.einsum("bmk,bk->bm", _l_block(p, q), ys[q],
                                   preferred_element_type=jnp.float32)
        L11 = L11s[p]
        yp = jnp.zeros_like(rhs)
        for c in range(PW):
            acc = rhs[:, c]
            for k in range(c):
                acc = acc - L11[:, c, k] * yp[:, k]
            yp = jnp.where(cids == c, (acc / L11[:, c, c])[:, None], yp)
        ys.append(yp)
    # blocked back substitution: L^T x = y
    xs = [None] * nP
    for p in reversed(range(nP)):
        rhs = ys[p]
        for q in range(p + 1, nP):
            rhs = rhs - jnp.einsum("bkm,bk->bm", _l_block(q, p), xs[q],
                                   preferred_element_type=jnp.float32)
        L11 = L11s[p]
        xp = jnp.zeros_like(rhs)
        for c in reversed(range(PW)):
            acc = rhs[:, c]
            for k in range(c + 1, PW):
                acc = acc - L11[:, k, c] * xp[:, k]
            xp = jnp.where(cids == c, (acc / L11[:, c, c])[:, None], xp)
        xs[p] = xp
    # assemble [B, R] from panels with iota-built selector matmuls
    # (concatenate on a non-lane-aligned minor dim is exactly what
    # Mosaic dislikes; a [PW, R] one-hot embed is a cheap MXU op and
    # fully traced)
    x = jnp.zeros_like(b)
    rows = jax.lax.broadcasted_iota(jnp.int32, (PW, R), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (PW, R), 1)
    for p in range(nP):
        sel = (rows + p * PW == cols).astype(jnp.float32)   # [PW, R]
        x = x + jnp.einsum("bp,pr->br", xs[p], sel,
                           preferred_element_type=jnp.float32)
    return x[:, :rank_in]


def _chol_kernel(a_ref, b_ref, x_ref, *, panel: int):
    x_ref[:] = _blocked_cholesky_solve(a_ref[:], b_ref[:], panel)


def cholesky_solve_pallas(A, b, tile: int = 8, panel: int = 8,
                          interpret: bool = False):
    """MXU-packed panel factorization: grid over batch tiles; each tile's
    [tile, R, R] systems are factorized in VMEM with panel-width trailing
    updates as batched matmuls (the MXU share grows as R^3/3 while the
    sequential column work stays R^2-ish). The candidate replacement for
    CG on the dense (K >= rank) ALS buckets, whose cross-sublane matvecs
    bound the VPU path (docs/benchmarks.md)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, rank = A.shape[0], A.shape[-1]
    if rank % panel:
        pad = panel - rank % panel
        R2 = rank + pad
        Ap = jnp.zeros((B, R2, R2), A.dtype)
        Ap = Ap.at[:, :rank, :rank].set(A)
        Ap = Ap.at[:, rank:, rank:].set(jnp.eye(pad, dtype=A.dtype))
        A = Ap
        b = jnp.concatenate([b, jnp.zeros((B, pad), b.dtype)], axis=1)
    R2 = A.shape[-1]
    if B % tile != 0:
        padb = tile - B % tile
        A = jnp.concatenate(
            [A, jnp.broadcast_to(jnp.eye(R2, dtype=A.dtype),
                                 (padb, R2, R2))], axis=0)
        b = jnp.concatenate([b, jnp.zeros((padb, R2), b.dtype)], axis=0)
    kernel = functools.partial(_chol_kernel, panel=panel)
    x = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((A.shape[0], R2), jnp.float32),
        grid=(A.shape[0] // tile,),
        in_specs=[
            pl.BlockSpec((tile, R2, R2), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, R2), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, R2), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        compiler_params=_tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(A.astype(jnp.float32), b)
    return x[:B, :rank]


def resolve_solver(method: str, n_devices: int = 1) -> str:
    """'auto' -> concrete method: CG on TPU (Pallas single-device; the jnp
    formulation under GSPMD meshes, where pallas_call can't consume sharded
    operands), cholesky on CPU/GPU (LAPACK/cuSOLVER are fine there)."""
    if method != "auto":
        return method
    import jax
    if jax.default_backend() == "tpu":
        return "cg_pallas" if n_devices == 1 else "cg"
    return "cholesky"


def spd_solve(A, b, method: str = "auto", iters: int | None = None,
              compute_dtype: str = "bfloat16"):
    """Batched SPD solve with backend-appropriate method selection.

    method: 'auto' | 'cholesky' | 'cg' | 'cg_pallas' | 'schulz' |
            'schulz_pallas'
    """
    if method == "auto":
        method = resolve_solver(method)
    if method == "cholesky":
        return cholesky_solve(A, b)
    if method == "cg":
        return cg_solve(A, b, iters or 48)
    if method == "cg_pallas":
        return cg_solve_pallas(A, b, iters or 48)
    if method == "schulz":
        return schulz_solve(A, b, iters, compute_dtype)
    if method == "schulz_pallas":
        return schulz_solve_pallas(A, b, iters, compute_dtype)
    if method == "chol_pallas":
        return cholesky_solve_pallas(A, b)
    if method == "chol_blocked":   # jnp form (any backend / GSPMD meshes)
        return _blocked_cholesky_solve(A, b)
    raise ValueError(f"unknown solver {method!r}")
