"""Random decision forest — TPU-native replacement for MLlib RandomForest.

Plays the role of `org.apache.spark.mllib.tree.RandomForest.trainClassifier`
as used by the classification template's add-algorithm variant (reference:
examples/scala-parallel-classification/add-algorithm/src/main/scala/
RandomForestAlgorithm.scala:30-41): same knob surface (numClasses, numTrees,
featureSubsetStrategy, impurity, maxDepth, maxBins), same prediction rule
(per-tree class vote, majority wins).

This is not a port of MLlib's distributed tree induction (per-node task
queues + row shuffles). The TPU-first formulation is level-synchronous and
fully dense, so everything jits with static shapes:

  * features are quantile-binned once into ``max_bins`` ordered bins;
  * all trees grow in lockstep.  At depth d, the class histogram for every
    (tree, heap-node, feature, bin) cell is one one-hot einsum over the
    example axis — the same MXU-counting trick as ops/naive_bayes.py — so
    split search is a dense cumulative reduction, never per-node recursion;
  * best split per node = max impurity gain (gini or entropy) over the
    bin-cumulative histograms, restricted to that node's random feature
    subset; nodes with no admissible gain freeze into leaves;
  * trees are heap-indexed array pytrees (node i's children are 2i+1 and
    2i+2), so prediction is ``max_depth`` gathers under jit and the forest
    vote is a one-hot sum.

Bootstrap resampling uses per-tree Poisson(1) example weights (the standard
large-n limit of sampling-with-replacement, also what MLlib's BaggedPoint
uses for subsamplingRate=1).  Histogram memory is
O(trees * 2^depth * features * bins * classes); the template workloads
(4 features, tens of trees, depth <= 10) stay far under HBM limits.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

_STRATEGIES = ("auto", "all", "sqrt", "log2", "onethird")


def feature_subset_size(strategy: str, num_features: int,
                        num_trees: int) -> int:
    """MLlib RandomForest.scala featureSubsetStrategy semantics: "auto" is
    sqrt for a forest, all features for a single tree."""
    s = strategy.lower()
    if s not in _STRATEGIES:
        raise ValueError(
            f"featureSubsetStrategy must be one of {_STRATEGIES}, got {s!r}")
    if s == "auto":
        s = "sqrt" if num_trees > 1 else "all"
    if s == "all":
        return num_features
    # ceil throughout, as in Spark's DecisionTreeMetadata.buildMetadata.
    if s == "sqrt":
        return max(1, int(math.ceil(math.sqrt(num_features))))
    if s == "log2":
        return max(1, int(math.ceil(math.log2(max(2, num_features)))))
    return max(1, int(math.ceil(num_features / 3.0)))


@dataclass
class ForestModel:
    """Heap-layout forest. Node i: children 2i+1 / 2i+2; leaves carry the
    majority class of the training rows that reached them."""
    feature: np.ndarray      # [T, nodes] int32 split feature (internal nodes)
    threshold: np.ndarray    # [T, nodes] float32; go right iff x[f] > thr
    is_leaf: np.ndarray      # [T, nodes] bool
    leaf_class: np.ndarray   # [T, nodes] int32
    num_classes: int
    max_depth: int

    @property
    def num_trees(self) -> int:
        return self.feature.shape[0]

    def predict(self, x: Sequence[float]) -> float:
        """Single-query vote on host (serve path; no device round-trip)."""
        votes = self.predict_batch(np.asarray(x, np.float32)[None, :])
        return float(votes[0])

    def predict_batch(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, np.float32)
        node = np.zeros((self.num_trees, X.shape[0]), np.int64)
        tree_ix = np.arange(self.num_trees)[:, None]
        for _ in range(self.max_depth):
            f = self.feature[tree_ix, node]
            go_right = X[np.arange(X.shape[0])[None, :], f] > \
                self.threshold[tree_ix, node]
            child = 2 * node + 1 + go_right
            node = np.where(self.is_leaf[tree_ix, node], node, child)
        cls = self.leaf_class[tree_ix, node]            # [T, q]
        votes = np.zeros((X.shape[0], self.num_classes), np.int64)
        for t in range(self.num_trees):
            votes[np.arange(X.shape[0]), cls[t]] += 1
        return np.argmax(votes, axis=1).astype(np.float64)


def _impurity(counts, kind: str):
    """counts [..., C] -> impurity [...]. Gini or entropy (MLlib's two
    classification impurities)."""
    total = counts.sum(axis=-1, keepdims=True)
    p = counts / jnp.maximum(total, 1e-9)
    if kind == "gini":
        return 1.0 - jnp.sum(p * p, axis=-1)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log2(jnp.maximum(p, 1e-12)),
                              0.0), axis=-1)


@functools.partial(
    jax.jit,
    static_argnames=("num_classes", "num_trees", "max_depth", "max_bins",
                     "impurity", "subset_k"))
def _grow_forest(Xb, y, edges, key, *, num_classes: int, num_trees: int,
                 max_depth: int, max_bins: int, impurity: str, subset_k: int):
    """Level-synchronous growth of all trees at once.

    Xb    [n, F] int32  bin index per example/feature
    y     [n]    int32  class index
    edges [F, B-1] f32  bin upper edges: bin b <=> x <= edges[f, b]
    """
    n, F = Xb.shape
    T, C, B, D = num_trees, num_classes, max_bins, max_depth
    num_nodes = 2 ** (D + 1) - 1

    kw, kf = jax.random.split(key)
    # Poisson(1) bootstrap weights per (tree, example).
    w = jax.random.poisson(kw, 1.0, (T, n)).astype(jnp.float32)

    bin1h = jax.nn.one_hot(Xb, B, dtype=jnp.float32)       # [n, F, B]
    cls1h = jax.nn.one_hot(y, C, dtype=jnp.float32)        # [n, C]

    feature = jnp.zeros((T, num_nodes), jnp.int32)
    threshold = jnp.full((T, num_nodes), jnp.inf, jnp.float32)
    is_leaf = jnp.zeros((T, num_nodes), bool)
    leaf_class = jnp.zeros((T, num_nodes), jnp.int32)

    node = jnp.zeros((T, n), jnp.int32)    # index within current level
    active = w > 0                         # example still flowing in tree

    for d in range(D + 1):
        nd = 2 ** d
        base = nd - 1                      # heap offset of this level
        node1h = jax.nn.one_hot(node, nd, dtype=jnp.float32) \
            * (active * w)[:, :, None]                     # [T, n, nd]
        # Class histogram per (tree, node, feature, bin): the hot einsum.
        # Three operands so XLA picks the contraction order without ever
        # materialising an [n, F, B, C] intermediate.
        hist = jnp.einsum("tnm,nfb,nc->tmfbc", node1h, bin1h,
                          cls1h)                           # [T,nd,F,B,C]

        # Per-node class totals (the bin axis partitions each node's rows,
        # so any single feature slice sums to the node totals).
        cls_tot = hist[:, :, 0, :, :].sum(axis=2)          # [T, nd, C]
        total = cls_tot.sum(axis=-1)                       # [T, nd]
        majority = jnp.argmax(cls_tot, axis=-1).astype(jnp.int32)
        parent_imp = _impurity(cls_tot, impurity)          # [T, nd]

        if d == D:
            # Bottom level: everything still active becomes a leaf.
            sl = slice(base, base + nd)
            is_leaf = is_leaf.at[:, sl].set(True)
            leaf_class = leaf_class.at[:, sl].set(majority)
            break

        # Candidate split "bin <= b goes left" for b in 0..B-2.
        cum = jnp.cumsum(hist, axis=3)                     # [T,nd,F,B,C]
        left = cum[:, :, :, :-1, :]                        # [T,nd,F,B-1,C]
        right = cls_tot[:, :, None, None, :] - left
        nl = left.sum(axis=-1)
        nr = right.sum(axis=-1)
        child_imp = (nl * _impurity(left, impurity)
                     + nr * _impurity(right, impurity)) \
            / jnp.maximum(nl + nr, 1e-9)
        gain = parent_imp[:, :, None, None] - child_imp    # [T,nd,F,B-1]
        # Random feature subset per (tree, node): keep the subset_k features
        # with the smallest random scores (exact-k mask, no replacement).
        scores = jax.random.uniform(
            jax.random.fold_in(kf, d), (T, nd, F))
        kth = jnp.sort(scores, axis=-1)[..., subset_k - 1]
        fmask = scores <= kth[..., None]                   # [T, nd, F]
        valid = (nl > 0) & (nr > 0) & fmask[:, :, :, None]
        gain = jnp.where(valid, gain, -jnp.inf)

        flat = gain.reshape(T, nd, F * (B - 1))
        best = jnp.argmax(flat, axis=-1)                   # [T, nd]
        best_gain = jnp.take_along_axis(flat, best[..., None],
                                        axis=-1)[..., 0]
        best_f = (best // (B - 1)).astype(jnp.int32)
        best_b = (best % (B - 1)).astype(jnp.int32)
        # Leaf iff: nothing reached it, already pure, or no usable split.
        make_leaf = (total <= 1) | (parent_imp <= 1e-9) | \
            (best_gain <= 0) | (~jnp.isfinite(best_gain))

        thr = edges[best_f, best_b]                        # [T, nd]
        sl = slice(base, base + nd)
        feature = feature.at[:, sl].set(best_f)
        threshold = threshold.at[:, sl].set(thr)
        is_leaf = is_leaf.at[:, sl].set(make_leaf)
        leaf_class = leaf_class.at[:, sl].set(majority)

        # Route examples: right iff bin > best_b of their node's feature.
        nf = jnp.take_along_axis(best_f, node, axis=1)     # [T, n]
        nb = jnp.take_along_axis(best_b, node, axis=1)
        xb_f = Xb[jnp.arange(n)[None, :], nf]              # [T, n]
        go_right = xb_f > nb
        froze = jnp.take_along_axis(make_leaf, node, axis=1)
        active = active & ~froze
        node = 2 * node + go_right.astype(jnp.int32)

    return feature, threshold, is_leaf, leaf_class


def forest_train(X: np.ndarray, y: np.ndarray, *, num_classes: int,
                 num_trees: int = 10, feature_subset_strategy: str = "auto",
                 impurity: str = "gini", max_depth: int = 5,
                 max_bins: int = 32, seed: int = 42) -> ForestModel:
    """Train a classification forest. `y` holds class indices 0..C-1 (MLlib
    labels are doubles with the same contract)."""
    if impurity not in ("gini", "entropy"):
        raise ValueError(f"impurity must be gini|entropy, got {impurity!r}")
    X = np.asarray(X, np.float32)
    y_arr = np.asarray(y)
    if not np.all(np.equal(np.mod(y_arr, 1), 0)):
        raise ValueError("forest labels must be integer-valued class ids")
    y_ix = y_arr.astype(np.int64).astype(np.int32)
    if y_ix.size and (y_ix.min() < 0 or y_ix.max() >= num_classes):
        # MLlib's trainClassifier throws on labels outside [0, numClasses);
        # silently dropping them would zero their one-hot rows instead.
        raise ValueError(
            f"forest labels must be in [0, {num_classes}); got range "
            f"[{y_ix.min()}, {y_ix.max()}]")
    n, F = X.shape
    max_bins = max(2, min(max_bins, max(2, n)))
    # Quantile bin edges; bin index = #(edges < x), so bin b <=> x <= edge[b].
    qs = np.linspace(0.0, 1.0, max_bins + 1)[1:-1]
    edges = np.quantile(X, qs, axis=0).T.astype(np.float32)  # [F, B-1]
    Xb = (X[:, :, None] > edges[None, :, :]).sum(axis=2).astype(np.int32)

    subset_k = feature_subset_size(feature_subset_strategy, F, num_trees)
    feat, thr, leaf, leaf_cls = _grow_forest(
        jnp.asarray(Xb), jnp.asarray(y_ix),
        jnp.asarray(edges), jax.random.PRNGKey(seed),
        num_classes=num_classes, num_trees=num_trees, max_depth=max_depth,
        max_bins=max_bins, impurity=impurity, subset_k=subset_k)
    return ForestModel(
        feature=np.asarray(feat), threshold=np.asarray(thr),
        is_leaf=np.asarray(leaf), leaf_class=np.asarray(leaf_cls),
        num_classes=num_classes, max_depth=max_depth)
