"""TPU compute kernels (JAX/XLA/Pallas) — the MLlib replacement.

Each module is a pure-function kernel family taking host or device arrays:
  als         — explicit/implicit alternating least squares (the MLlib
                ALS.train / ALS.trainImplicit replacement)
  naive_bayes — categorical naive bayes (MLlib NaiveBayes replacement)
  similarity  — normalized-embedding cosine scoring + filtered top-k
  ratings     — host-side preprocessing: COO ratings -> bucketed solve plans
"""
