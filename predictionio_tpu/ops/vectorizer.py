"""Binary one-hot vectorizer for (property, value) pairs.

Rebuilds the reference's ``BinaryVectorizer``
(reference: e2/src/main/scala/io/prediction/e2/engine/BinaryVectorizer.scala):
maps each observed (property, value) string pair to a column index; vectorize
emits a dense 0/1 float array. Dense output (vs the reference's SparseVector)
because XLA wants fixed shapes and downstream kernels are matmuls.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, Mapping, Sequence, Tuple

import numpy as np


@dataclass
class BinaryVectorizer:
    property_map: Dict[Tuple[str, str], int]

    @property
    def n_features(self) -> int:
        return len(self.property_map)

    @staticmethod
    def fit(maps: Iterable[Mapping[str, str]],
            properties: Sequence[str]) -> "BinaryVectorizer":
        pairs = sorted({(p, m[p]) for m in maps for p in properties
                        if p in m})
        return BinaryVectorizer({pv: i for i, pv in enumerate(pairs)})

    def transform(self, m: Mapping[str, str]) -> np.ndarray:
        out = np.zeros(self.n_features, dtype=np.float32)
        for p, v in m.items():
            ix = self.property_map.get((p, str(v)))
            if ix is not None:
                out[ix] = 1.0
        return out

    def transform_batch(self, maps: Sequence[Mapping[str, str]]) -> np.ndarray:
        return np.stack([self.transform(m) for m in maps]) if maps else \
            np.zeros((0, self.n_features), dtype=np.float32)
