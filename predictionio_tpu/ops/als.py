"""Alternating least squares on the TPU mesh — the MLlib ALS replacement.

Replaces `org.apache.spark.mllib.recommendation.ALS.train/trainImplicit` as
called by the reference templates (reference:
examples/scala-parallel-recommendation/custom-prepartor/src/main/scala/
ALSAlgorithm.scala:55 explicit; examples/scala-parallel-similarproduct/multi/
src/main/scala/ALSAlgorithm.scala:130 implicit).

Design (ALX-style, PAPERS.md "ALX: Large Scale Matrix Factorization on
TPUs"): instead of MLlib's factor-block shuffles, both factor tables live in
HBM; each half-iteration sweeps bucketed [B, K] batches of entities
(ops/ratings.build_solve_plan), gathering counterpart factors, forming the
normal equations with batched einsums on the MXU, and solving by batched
Cholesky. The batch dim B is sharded over the mesh `data` axis; factor
tables are replicated (or sharded over `model` for tables larger than one
device's HBM — GSPMD inserts the all-gathers).

Math parity with MLlib 1.3:
  explicit  — ALS-WR: minimize sum (r - x.v)^2 + lambda * (n_u |x|^2 + ...)
              i.e. per-entity regularizer lambda * n ratings (`lambda_scaling
              ='nratings'`, MLlib's default behavior in 1.3).
  implicit  — Hu-Koren confidence c = 1 + alpha * |r|, preference p = 1(r>0),
              solve (G + V_u^T (C_u - I) V_u + lambda*n*I) x = V_u^T C_u p
              with G = V^T V computed once per half-sweep. Negative ratings
              (e.g. "dislike" events mapped to r = -1) contribute confidence
              with preference 0, exactly MLlib 1.3's c1 = alpha*|r| /
              b += (c1+1)*x when r > 0.
"""

from __future__ import annotations

import functools
import logging
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from predictionio_tpu.ops.ratings import (RatingsCOO, SolvePlan,
                                          plan_for_items, plan_for_users)
from predictionio_tpu.parallel.mesh import MeshContext, current_mesh

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class ALSConfig:
    rank: int = 10
    iterations: int = 10
    lam: float = 0.01                  # MLlib's lambda_
    implicit_prefs: bool = False
    alpha: float = 1.0                 # implicit confidence scale
    lambda_scaling: str = "nratings"   # 'nratings' (ALS-WR) | 'constant'
    seed: int = 0
    work_budget: int = 1 << 20         # B*K per solve batch
    compute_dtype: str = "float32"     # einsum dtype ('bfloat16' on TPU ok)
    factor_dtype: str = "float32"      # HBM storage dtype of factor tables
    # 'bfloat16' halves the per-iteration gather traffic (the dominant HBM
    # cost once solves are fast); solves still build/solve f32 normal
    # equations from the gathered rows, so per-iteration quality loss is
    # bounded by bf16 rounding of the carried factors.
    solver: str = "auto"  # see ops/solve.py spd_solve
    # auto = VMEM-resident CG Pallas kernel on TPU (XLA's batched cholesky
    # runs at ~0.05% MXU there), LAPACK cholesky on CPU.
    solver_iters: Optional[int] = None  # primal CG iteration budget
    # None = the solver default (48). The primal rank-dim CG can stall in
    # ill-conditioned implicit configs (large alpha * |r| confidences);
    # K<rank buckets are unaffected (the dual route solves a better-
    # conditioned K-dim system exactly), but large-count entities ride
    # the primal solver — raise this (or set solver='cholesky') there.
    dual_iters_cap: Optional[int] = None  # cap on the dual CG budget
    # None = K+8 per bucket (finite-termination bound + roundoff margin).
    # CG converges far earlier on these well-conditioned K-dim systems;
    # if solve time scales with the iteration count (rather than being
    # per-call fixed), capping trades a bounded residual for wall-clock.
    # Measured by the ablation's dualcap row before any default change.
    dual_solve: str = "auto"  # 'auto' | 'never'
    # Woodbury/dual formulation for ALS buckets whose padded segment
    # length K < rank — exact algebra replacing the rank-dim solve with a
    # K-dim one. Explicit: solve (M M^T + reg I_K) z = y, x = M^T z.
    # Implicit: A = (G + reg I) + V_S^T D V_S with D = diag(alpha*|r|);
    # eigendecompose the base B = G + reg I ONCE per half-sweep (G is
    # shared by every entity) and apply Sherman-Morrison-Woodbury
    # through the eigenbasis: A^-1 b = B^-1 b - B^-1 V^T D^1/2
    # (I_K + D^1/2 V B^-1 V^T D^1/2)^-1 D^1/2 V B^-1 b — the D^1/2 form
    # stays exact when D has zeros (padding, zero-confidence rows).
    # Under a power-law count distribution most entities live in small-K
    # buckets, so this removes most of the solve work on both paths.
    factor_sharding: str = "replicated"  # 'replicated' | 'model'
    # 'model' shards factor-table rows over the mesh model axis (tables too
    # large for one device's HBM); GSPMD inserts the all-gathers the
    # per-batch index gathers need — the analog of MLlib's factor-block
    # shuffles, but compiler-scheduled over ICI.
    keep_sharded: bool = False
    # With factor_sharding='model': return the trained tables as
    # ShardedTable handles (per-shard host slices via
    # host_fetch_sharded + the resident device arrays attached) instead
    # of gathering one monolithic host table — the entry point of the
    # sharded online plane, where the full table never crosses the
    # host link again (fold ticks patch the mirrors, serving ranks
    # per shard). False keeps the legacy gather-to-host behavior.
    sweep_chunk: int = 0
    # Merge this many same-shape solve batches into one scan step (one
    # solver call over chunk*B systems). The measured solver cost is
    # per-CALL fixed (~20-30 ms on v5e regardless of CG iteration count —
    # docs/benchmarks.md), so fewer, larger calls amortize it; batches
    # within a half-sweep are independent (they read only the counterpart
    # table), so merging changes no math. Bounded by the normal-matrix
    # memory per step (chunk * B * S^2 * 4B). 0 = auto: 4 on single-device
    # TPU, 1 elsewhere.
    bucket_ratio: float = 1.125
    # Geometric step of the segment-length ladder (ops/ratings.py
    # bucket_lengths). At ML-20M scale nearly every ladder K is its own
    # uniquely-shaped batch, so the ladder size IS the solver-call count
    # per sweep (~125/iteration at 1.125); a coarser ratio trades padding
    # (more gather bytes + Gram flops) for fewer calls. The ablation's
    # ratio rows measure the tradeoff on hardware before any flip.
    fuse_iteration: bool = False
    # Trace both half-sweeps (and the implicit Grams) into ONE program per
    # iteration, letting XLA overlap the item-side gather DMAs with the
    # tail of the user-side solves and dropping a dispatch boundary.
    sentinel: bool = True
    # Numerical sentinel (ISSUE 5, guard/sentinels.py): after every
    # iteration the factor tables are checked on-device for finiteness
    # and norm explosion (one tiny reduction + scalar fetch per table),
    # and the last clean iteration is checkpointed as an HBM copy. A
    # breach returns the last-good model instead of NaN factors (or
    # raises NumericalFault when no iteration completed cleanly).
    # PIO_GUARD=off disables at runtime; set False to shave the
    # per-iteration copy + sync off latency-critical benches.
    sentinel_norm_cap: float = 1e4
    # Absolute max-row-norm bound for the train sentinel (there is no
    # incumbent model to scale from; init rows are O(1), converged rows
    # O(sqrt(max rating)) — 1e4 only trips on genuine blow-ups).

    def __post_init__(self):
        if self.dual_iters_cap is not None and self.dual_iters_cap < 1:
            # reject at construction: a 0 cap would otherwise surface
            # only when (and if) some bucket takes the dual route, mid-
            # training from inside a jitted trace — or never, falling
            # into spd_solve's `iters or 48` unset-default
            raise ValueError("dual_iters_cap must be >= 1, got "
                             f"{self.dual_iters_cap}")
        if not self.bucket_ratio > 1.0:
            # ratio <= 1 degrades the geometric walk to the linear,
            # maximally fine ladder (bucket_lengths always advances by
            # at least one alignment step) — never what a caller wants,
            # so reject it rather than silently maximize program count
            raise ValueError("bucket_ratio must be > 1.0, got "
                             f"{self.bucket_ratio}")


def default_compute_dtype() -> str:
    """bf16 Gram einsums on TPU (MXU-native, f32 accumulation), f32 on
    CPU where bf16 is emulated."""
    import jax
    return "bfloat16" if jax.default_backend() == "tpu" else "float32"


@dataclass
class ALSModel:
    """Trained factorization. Arrays are host numpy after training; serving
    re-uploads them with the sharding the query path wants."""
    user_factors: np.ndarray   # [n_users, rank] float32
    item_factors: np.ndarray   # [n_items, rank] float32
    rank: int

    @property
    def n_users(self) -> int:
        return self.user_factors.shape[0]

    @property
    def n_items(self) -> int:
        return self.item_factors.shape[0]


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------

def _dual_system_solve(M, y, K: int, solver: str,
                       iters_cap: Optional[int] = None):
    """Solve the K-dim dual/Woodbury system: the shared policy for both
    explicit and implicit dual branches. K+8 iterations (CG's exact-
    arithmetic finite termination is <= K; the margin absorbs f32
    roundoff — capping below K silently under-solves the larger
    buckets unless the caller opts in via `iters_cap`, whose accuracy
    cost is ALSConfig.dual_iters_cap's to document); tiny systems skip
    the Pallas kernel, whose per-tile overhead dominates below 32."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.ops.solve import spd_solve
    if solver == "diag_nosolve":
        # perf diagnostic, NOT a solver (wrong math by design): skip the
        # solve but keep M alive — the dual Gram einsum is the
        # traffic/flops being measured. The optimization_barrier stops
        # XLA's algebraic simplifier from folding sum-of-einsum into a
        # cheaper contraction that never materializes the Gram. Covers
        # every dual call site (explicit Woodbury and implicit eig-SMW).
        M_live = jax.lax.optimization_barrier(M)
        return y + M_live.sum(axis=2) * jnp.float32(1e-12)
    method = "cg" if (K < 32 and solver == "cg_pallas") else solver
    if iters_cap is not None and iters_cap < 1:
        # 0 would fall into spd_solve's `iters or 48` unset-default and
        # run MORE iterations than uncapped — reject it loudly
        raise ValueError(f"dual_iters_cap must be >= 1, got {iters_cap}")
    iters = K + 8 if iters_cap is None else min(K + 8, iters_cap)
    return spd_solve(M, y, method=method, iters=iters)


def _scatter_rows(factors_out, rows, x):
    """Scatter solved rows; padding rows (-1) land on the dummy tail."""
    import jax.numpy as jnp
    safe = jnp.where(rows < 0, factors_out.shape[0] - 1, rows)
    return factors_out.at[safe].set(x.astype(factors_out.dtype),
                                    mode="drop")


def _solve_batch(factors_out, counter_factors, gram, rows, idx, val, mask,
                 lam, alpha, *, nratings_reg: bool, implicit: bool,
                 rank: int, compute_dtype: str, solver: str,
                 dual_solve: str = "auto",
                 solver_iters: Optional[int] = None,
                 dual_iters_cap: Optional[int] = None):
    """Solve one [B, K] batch of normal equations and scatter results into
    factors_out. Traced inside `_solve_sweep`'s scan body — gather ->
    einsum -> solve -> scatter fuse into one XLA program. Explicit batches
    with K < rank take the dual (Woodbury) K x K route; K is static per
    batch group, so the choice costs nothing at runtime."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.ops.solve import spd_solve

    cd = jnp.dtype(compute_dtype)
    Vg = counter_factors[idx]                       # [B, K, R] gather
    Vc = Vg.astype(cd)
    K = idx.shape[1]
    eye = jnp.eye(rank, dtype=jnp.float32)
    n = mask.sum(axis=-1)                            # ratings per entity
    reg = lam * jnp.maximum(n, 1.0) if nratings_reg else jnp.full_like(n, lam)

    if solver == "diag_gather":
        # perf diagnostic, NOT a solver (wrong math by design): gather +
        # one light K*R einsum + scatter, i.e. the sweep minus the Gram
        # and minus the solve. Ablation rows subtract it from
        # diag_nosolve / full rows to locate the iteration time.
        x = jnp.einsum("bk,bkr->br", mask.astype(cd), Vc,
                       preferred_element_type=jnp.float32)
        return _scatter_rows(factors_out, rows, x)

    if dual_solve == "auto" and not implicit and K < rank:
        # dual/Woodbury: with M = mask-weighted factor rows [K, R],
        # (M^T M + reg I_R)^-1 M^T y == M^T (M M^T + reg I_K)^-1 y.
        # Gram is K^2*R instead of K*R^2, solve is K-dimensional.
        Vm = Vc * mask[..., None].astype(cd)
        Ad = jnp.einsum("bkr,blr->bkl", Vm, Vm,
                        preferred_element_type=jnp.float32)
        Ad = Ad + reg[:, None, None] * jnp.eye(K, dtype=jnp.float32)
        y = (val * mask)
        z = _dual_system_solve(Ad, y, K, solver,
                               iters_cap=dual_iters_cap)
        x = jnp.einsum("bkr,bk->br", Vm, z.astype(cd),
                       preferred_element_type=jnp.float32)
        return _scatter_rows(factors_out, rows, x)

    if implicit:
        G, gram_w, gram_q = gram if isinstance(gram, tuple) \
            else (gram, None, None)
        absval = jnp.abs(val)
        conf_minus_1 = (alpha * absval) * mask       # c - 1, zero on padding
        # preference p = 1(r>0): negative signals add confidence to A only
        pos = (val > 0).astype(val.dtype) * mask
        b = jnp.einsum("bk,bkr->br",
                       ((1.0 + alpha * absval) * pos).astype(cd), Vc,
                       preferred_element_type=jnp.float32)
        if gram_w is not None and dual_solve == "auto" and K < rank:
            # implicit dual: B = G + reg I = Q (w + reg) Q^T (eig shared
            # across the whole half-sweep); Woodbury for the K-rank
            # confidence update, all R-dim work as eigenbasis einsums.
            # G is PSD, so clamp eigh's roundoff-negative tail: a small
            # reg (constant lambda_scaling grid points) must never meet
            # a negative w and flip the sign of 1/denom.
            denom = (jnp.maximum(gram_w, 0.0)[None, :]
                     + reg[:, None])                          # [B, R]
            Vq = jnp.einsum("bkr,rs->bks", Vc,
                            gram_q.astype(cd),
                            preferred_element_type=jnp.float32)  # V~ Q
            bq = jnp.einsum("br,rs->bs", b.astype(cd),
                            gram_q.astype(cd),
                            preferred_element_type=jnp.float32)
            bq_d = bq / denom
            u = jnp.einsum("bs,rs->br", bq_d.astype(cd),
                           gram_q.astype(cd),
                           preferred_element_type=jnp.float32)  # B^-1 b
            W = jnp.einsum("bks,bs,bls->bkl", Vq.astype(cd),
                           (1.0 / denom).astype(cd), Vq.astype(cd),
                           preferred_element_type=jnp.float32)
            dhalf = jnp.sqrt(conf_minus_1)                     # [B, K]
            M = (jnp.eye(K, dtype=jnp.float32)
                 + dhalf[:, :, None] * W * dhalf[:, None, :])
            t = jnp.einsum("bks,bs->bk", Vq.astype(cd),
                           bq_d.astype(cd),
                           preferred_element_type=jnp.float32)  # V B^-1 b
            z = _dual_system_solve(M, dhalf * t, K, solver,
                                   iters_cap=dual_iters_cap)
            s = jnp.einsum("bks,bk->bs", Vq.astype(cd),
                           (dhalf * z).astype(cd),
                           preferred_element_type=jnp.float32)
            x = u - jnp.einsum("bs,rs->br", (s / denom).astype(cd),
                               gram_q.astype(cd),
                               preferred_element_type=jnp.float32)
            return _scatter_rows(factors_out, rows, x)
        A = G + jnp.einsum("bk,bkr,bks->brs", conf_minus_1.astype(cd),
                           Vc, Vc,
                           preferred_element_type=jnp.float32)
    else:
        A = jnp.einsum("bk,bkr,bks->brs", mask.astype(cd), Vc, Vc,
                       preferred_element_type=jnp.float32)
        b = jnp.einsum("bk,bkr->br", (val * mask).astype(cd), Vc,
                       preferred_element_type=jnp.float32)
    A = A + reg[:, None, None] * eye
    if solver == "diag_nosolve":
        # perf diagnostic: keep A alive against algebraic simplification
        # (see the _dual_system_solve note)
        x = b + jax.lax.optimization_barrier(A).sum(axis=2) \
            * jnp.float32(1e-12)
    else:
        x = spd_solve(A, b, method=solver, iters=solver_iters,
                      compute_dtype=compute_dtype)
    return _scatter_rows(factors_out, rows, x)


def _solve_sweep_impl(factors_out, counter_factors, gram, groups, lam,
                      alpha, *, nratings_reg: bool, implicit: bool,
                      rank: int, compute_dtype: str, solver: str,
                      dual_solve: str = "auto",
                      solver_iters: Optional[int] = None,
                      dual_iters_cap: Optional[int] = None):
    import jax

    def body(f, batch):
        rows, idx, val, mask = batch
        f = _solve_batch(f, counter_factors, gram, rows, idx, val, mask,
                         lam, alpha, nratings_reg=nratings_reg,
                         implicit=implicit, rank=rank,
                         compute_dtype=compute_dtype, solver=solver,
                         dual_solve=dual_solve,
                         solver_iters=solver_iters,
                         dual_iters_cap=dual_iters_cap)
        return f, None

    for group in groups:
        factors_out, _ = jax.lax.scan(body, factors_out, group)
    return factors_out


def _donation_safe() -> bool:
    """Donating the carried factor table saves an HBM copy per sweep on
    accelerators, but on multi-device CPU (the 8-fake-device test mesh)
    older jaxlib releases corrupt the allocator under donated multi-shard
    buffers (observed: 'corrupted double-linked list' segfaults mid-
    suite on jaxlib 0.4.x). Donation is purely a memory optimization, so
    restrict it to non-CPU backends."""
    import jax
    return jax.default_backend() != "cpu"


_SWEEP_STATICS = ("nratings_reg", "implicit", "rank", "compute_dtype",
                  "solver", "dual_solve", "solver_iters", "dual_iters_cap")
_ITER_STATICS = _SWEEP_STATICS + ("n_users", "n_items")
_jitted = {}


def _jitted_sweep():
    key = ("sweep", _donation_safe())
    fn = _jitted.get(key)
    if fn is None:
        import jax
        fn = jax.jit(_solve_sweep_impl, static_argnames=_SWEEP_STATICS,
                     donate_argnums=(0,) if key[1] else ())
        _jitted[key] = fn
    return fn


def _jitted_iteration():
    key = ("iteration", _donation_safe())
    fn = _jitted.get(key)
    if fn is None:
        import jax
        fn = jax.jit(_solve_iteration_impl, static_argnames=_ITER_STATICS,
                     donate_argnums=(0, 1) if key[1] else ())
        _jitted[key] = fn
    return fn


class _JitProxy:
    """Defers jit construction to call time (donation depends on the
    backend, unknown at import) while keeping the jitted-function surface
    (`lower`, `trace`, ...) callers like the collective-stats tests use."""

    def __init__(self, factory):
        self._factory = factory

    def __call__(self, *a, **kw):
        return self._factory()(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._factory(), name)


#: One half-iteration in ONE dispatch: `groups` is a tuple of stacked
#: same-shape batch groups (rows [N,B], idx/val/mask [N,B,K]); each group
#: is consumed by a `lax.scan` over its leading dim, carrying the (on
#: accelerators, donated) factor table through every scatter. Collapses
#: the previous ~45 dispatches per half-sweep (each with fresh host
#: scalars over a ~65 ms tunnel round-trip) to a single device program,
#: and the per-bucket compile count to one program per plan signature.
_solve_sweep = _JitProxy(_jitted_sweep)


def _solve_iteration_impl(U, V, user_groups, item_groups, lam, alpha, *,
                          nratings_reg: bool, implicit: bool, rank: int,
                          compute_dtype: str, solver: str,
                          dual_solve: str = "auto",
                          solver_iters: Optional[int] = None,
                          dual_iters_cap: Optional[int] = None,
                          n_users: int = 0, n_items: int = 0):
    gram_of = _gram_eig_impl if dual_solve == "auto" else _gram_impl
    gram_v = gram_of(V[:n_items]) if implicit else None
    U = _solve_sweep_impl(
        U, V, gram_v, user_groups, lam, alpha, nratings_reg=nratings_reg,
        implicit=implicit, rank=rank, compute_dtype=compute_dtype,
        solver=solver, dual_solve=dual_solve, solver_iters=solver_iters,
        dual_iters_cap=dual_iters_cap)
    gram_u = gram_of(U[:n_users]) if implicit else None
    V = _solve_sweep_impl(
        V, U, gram_u, item_groups, lam, alpha, nratings_reg=nratings_reg,
        implicit=implicit, rank=rank, compute_dtype=compute_dtype,
        solver=solver, dual_solve=dual_solve, solver_iters=solver_iters,
        dual_iters_cap=dual_iters_cap)
    return U, V


#: One FULL iteration (user sweep then item sweep, plus the implicit
#: Grams) traced as a single program: the half-sweeps are data-dependent
#: (the item sweep reads the just-updated U), but fusing them lets XLA
#: prefetch the item side's gather DMAs behind the tail of the user
#: side's solves and drops a host dispatch boundary per iteration.
_solve_iteration = _JitProxy(_jitted_iteration)


def _gram_impl(factors):
    import jax.numpy as jnp
    return jnp.einsum("ir,is->rs", factors, factors,
                      preferred_element_type=jnp.float32)


def _gram_eig_impl(factors):
    """Gram + its eigendecomposition — computed ONCE per implicit
    half-sweep and shared by every entity's Woodbury solve (the base
    B = G + reg*I diagonalizes as Q diag(w + reg) Q^T for any reg)."""
    import jax.numpy as jnp
    G = jnp.einsum("ir,is->rs", factors, factors,
                   preferred_element_type=jnp.float32)
    w, q = jnp.linalg.eigh(G)
    return G, w, q


_gram = __import__("jax").jit(_gram_impl)
_gram_eig = __import__("jax").jit(_gram_eig_impl)


# ---------------------------------------------------------------------------
# Training driver
# ---------------------------------------------------------------------------

def _init_factors(n: int, rank: int, seed: int, salt: int,
                  row_multiple: int = 1) -> np.ndarray:
    # MLlib seeds factors with abs(normal)/sqrt(rank) per block; we use a
    # deterministic numpy RNG — scale keeps initial predictions O(1).
    # At least one trailing dummy row is allocated (the scatter target for
    # padding); total rows are rounded up so a model-axis sharding divides.
    rows = n + 1
    rows = ((rows + row_multiple - 1) // row_multiple) * row_multiple
    rng = np.random.default_rng(seed * 2654435761 % (2 ** 31) + salt)
    f = rng.standard_normal((rows, rank), dtype=np.float32)
    return np.abs(f) / np.sqrt(rank)


def resolve_sweep_chunk(chunk: int, n_devices: int = 1) -> int:
    """0 (auto) -> 4 on a single TPU device, 1 elsewhere. The chunked
    layout is shape-identical math; the default only changes where the
    per-solver-call fixed cost is measured to matter."""
    if chunk:
        return chunk
    import jax
    return 4 if (jax.default_backend() == "tpu" and n_devices == 1) else 1


def _upload_plan(mesh: MeshContext, plan: SolvePlan, chunk: int = 1):
    """Stack same-shape batches into [N, B(, K)] groups and upload each
    group once, sharded on the batch dim (dim 1) over the mesh data axis.
    The index/rating/mask tensors are constant across iterations, so they
    stay resident in HBM for the whole train (re-uploading per sweep would
    put ~NNZ*12B on the host<->device link every iteration — the dominant
    cost on a tunneled chip). Stacking is what lets `_solve_sweep` consume
    a whole side in one dispatch via scan.

    `chunk` > 1 merges that many batches into each scan step ([N, B] ->
    [N/chunk, chunk*B]): batches within a half-sweep are independent, so
    this only amortizes the solver's per-call fixed cost over more
    systems (ALSConfig.sweep_chunk); a remainder that doesn't fill a
    chunk becomes its own group."""
    by_shape = {}
    for b in plan.batches:
        by_shape.setdefault(b.shape, []).append(b)
    groups = []
    for shape in sorted(by_shape):
        bs = by_shape[shape]
        rows = np.stack([b.rows for b in bs])    # [N, B]
        idx = np.stack([b.idx for b in bs])      # [N, B, K]
        val = np.stack([b.val for b in bs])
        mask = np.stack([b.mask for b in bs])
        chunks = [(rows, idx, val, mask)]
        if chunk > 1 and len(bs) > 1:
            m = min(chunk, len(bs))
            n_full = (len(bs) // m) * m
            chunks = []
            if n_full:
                chunks.append(tuple(
                    x[:n_full].reshape(n_full // m, m * x.shape[1],
                                       *x.shape[2:])
                    for x in (rows, idx, val, mask)))
            if len(bs) > n_full:
                chunks.append(tuple(x[n_full:]
                                    for x in (rows, idx, val, mask)))
        for tensors in chunks:
            groups.append(tuple(mesh.put_stacked(x) for x in tensors))
    # host->device transfer accounting (obs.jaxmon): the plan upload is
    # the dominant per-train / per-fold-in link cost on a tunneled chip
    from predictionio_tpu.obs import jaxmon
    jaxmon.record_h2d(jaxmon.nbytes_of(
        t for group in groups for t in group))
    return tuple(groups)


def _run_side(device_groups, factors, counter_factors, cfg: ALSConfig,
              gram, lam=None, alpha=None):
    """One half-iteration: solve every batch of one side in one dispatch.
    `lam`/`alpha` should be device-resident scalars (uploaded once per
    train); numpy fallbacks keep ad-hoc callers working."""
    if lam is None:
        lam = np.float32(cfg.lam)
    if alpha is None:
        alpha = np.float32(cfg.alpha)
    # compile attribution (obs/costmon): sweeps dispatched from a fold
    # tick keep the fold's label; bare train sweeps book as als_sweep
    from predictionio_tpu.obs import costmon
    with costmon.executable(costmon.ALS_SWEEP, defer_to_outer=True):
        return _solve_sweep(
            factors, counter_factors, gram, device_groups, lam, alpha,
            nratings_reg=(cfg.lambda_scaling == "nratings"),
            implicit=cfg.implicit_prefs, rank=cfg.rank,
            compute_dtype=cfg.compute_dtype, solver=cfg.solver,
            dual_solve=cfg.dual_solve, solver_iters=cfg.solver_iters,
            dual_iters_cap=cfg.dual_iters_cap)


def als_train(ratings: RatingsCOO, cfg: ALSConfig,
              mesh: Optional[MeshContext] = None,
              telemetry: Optional[dict] = None) -> ALSModel:
    """Train explicit/implicit ALS. Factor tables carry one extra dummy row
    (index n) used as the scatter target for padding; it is dropped in the
    returned model.

    `telemetry`, when a dict, receives per-phase wall times (plan_s,
    upload_s, iters_s, s_per_iter, fetch_s). The iteration timing is
    closed by a hard one-element host fetch (a dispatch-queue timer would
    lie on asynchronous backends), which costs one extra tiny transfer —
    only paid when telemetry is requested."""
    import time as _time

    import jax
    mesh = mesh or current_mesh()
    t0 = _time.perf_counter()
    if cfg.solver == "auto":
        import dataclasses
        from predictionio_tpu.ops.solve import resolve_solver
        cfg = dataclasses.replace(
            cfg, solver=resolve_solver(cfg.solver, mesh.n_devices))
    dp = mesh.data_parallelism
    user_plan = plan_for_users(ratings, work_budget=cfg.work_budget,
                               batch_multiple=dp,
                               bucket_ratio=cfg.bucket_ratio)
    item_plan = plan_for_items(ratings, work_budget=cfg.work_budget,
                               batch_multiple=dp,
                               bucket_ratio=cfg.bucket_ratio)
    logger.info(
        "ALS: %d users, %d items, %d ratings; %d user batches %s "
        "(pad %.2fx), %d item batches %s (pad %.2fx)",
        ratings.n_users, ratings.n_items, ratings.nnz,
        len(user_plan.batches), user_plan.kernel_shapes,
        user_plan.padding_overhead,
        len(item_plan.batches), item_plan.kernel_shapes,
        item_plan.padding_overhead)
    if telemetry is not None:
        telemetry["plan_s"] = _time.perf_counter() - t0
        t0 = _time.perf_counter()

    if cfg.factor_sharding == "model":
        put_factors = mesh.put_model_sharded
        row_multiple = mesh.model_parallelism
    else:
        put_factors = mesh.put_replicated
        row_multiple = 1
    fdt = np.dtype(cfg.factor_dtype) if cfg.factor_dtype != "bfloat16" \
        else __import__("jax").numpy.bfloat16
    U = put_factors(_init_factors(ratings.n_users, cfg.rank, cfg.seed, 1,
                                  row_multiple).astype(fdt))
    V = put_factors(_init_factors(ratings.n_items, cfg.rank, cfg.seed, 2,
                                  row_multiple).astype(fdt))
    chunk = resolve_sweep_chunk(cfg.sweep_chunk, mesh.n_devices)
    user_batches = _upload_plan(mesh, user_plan, chunk)
    item_batches = _upload_plan(mesh, item_plan, chunk)
    # hyperparameters ride along as device-resident scalars: no per-call
    # host uploads, and sweeping lam/alpha (evaluation tuning) does not
    # recompile the sweep program
    lam_dev = mesh.put_replicated(np.float32(cfg.lam))
    alpha_dev = mesh.put_replicated(np.float32(cfg.alpha))
    if telemetry is not None:
        # hard sync: uploads must have landed before iteration timing
        # (one element of the factor table AND of the last-enqueued batch
        # group — per-device transfers complete in order, so the latter
        # fences the bulk of the plan upload)
        float(np.asarray(jax.device_get(V[:1, :1]))[0, 0])
        if item_batches:
            float(np.asarray(jax.device_get(
                item_batches[-1][2][:1, :1, :1])).ravel()[0])
        telemetry["upload_s"] = _time.perf_counter() - t0
        t0 = _time.perf_counter()
    gram_of = _gram_eig if cfg.dual_solve == "auto" else _gram
    # train-sweep sentinel (ISSUE 5): per-iteration finite/norm check +
    # a checkpointed last-good iteration (HBM copies, never host fetch)
    sentinel = None
    last_good = None
    # diag_* pseudo-solvers are perf diagnostics with wrong math by
    # design — their outputs are not factor tables worth guarding
    if cfg.sentinel and not cfg.solver.startswith("diag_"):
        from predictionio_tpu.guard.sentinels import (SweepSentinel,
                                                      device_copy,
                                                      guard_enabled)
        if guard_enabled():
            sentinel = SweepSentinel("train", 0.0,
                                     norm_floor=cfg.sentinel_norm_cap)

    def _checked(it: int) -> bool:
        """True to continue; False when a breach rolled back (training
        stops at the last clean iteration). Raises on iteration 0."""
        nonlocal U, V, last_good
        if sentinel is None:
            return True
        fault = (sentinel.check_table(U, f"iteration {it} user table")
                 or sentinel.check_table(V, f"iteration {it} item table"))
        if fault is None:
            # copies survive the next iteration's donated sweep
            last_good = (device_copy(U), device_copy(V))
            return True
        if last_good is None:
            raise fault
        logger.error("ALS %s — rolling back to iteration %d and "
                     "stopping early", fault, it - 1)
        U, V = last_good
        return False

    from predictionio_tpu.obs import costmon
    if cfg.fuse_iteration:
        for it in range(cfg.iterations):
            with costmon.executable(costmon.ALS_SWEEP,
                                    defer_to_outer=True):
                U, V = _solve_iteration(
                    U, V, user_batches, item_batches, lam_dev, alpha_dev,
                    nratings_reg=(cfg.lambda_scaling == "nratings"),
                    implicit=cfg.implicit_prefs, rank=cfg.rank,
                    compute_dtype=cfg.compute_dtype, solver=cfg.solver,
                    dual_solve=cfg.dual_solve,
                    solver_iters=cfg.solver_iters,
                    dual_iters_cap=cfg.dual_iters_cap,
                    n_users=ratings.n_users, n_items=ratings.n_items)
            if not _checked(it):
                break
    else:
        for it in range(cfg.iterations):
            gram_v = gram_of(V[:ratings.n_items]) if cfg.implicit_prefs \
                else None
            U = _run_side(user_batches, U, V, cfg, gram_v, lam_dev,
                          alpha_dev)
            gram_u = gram_of(U[:ratings.n_users]) if cfg.implicit_prefs \
                else None
            V = _run_side(item_batches, V, U, cfg, gram_u, lam_dev,
                          alpha_dev)
            if not _checked(it):
                break
    if telemetry is not None:
        # hard sync again: the loop above only enqueues device work
        float(np.asarray(jax.device_get(V[:1, :1]))[0, 0])
        telemetry["iters_s"] = _time.perf_counter() - t0
        telemetry["s_per_iter"] = (telemetry["iters_s"]
                                   / max(cfg.iterations, 1))
        t0 = _time.perf_counter()
    from predictionio_tpu.parallel.mesh import host_fetch
    if cfg.factor_sharding == "model" and cfg.keep_sharded:
        # sharded online plane: the tables leave training as
        # ShardedTable handles — per-shard host mirrors (each process
        # fetches only its addressable slices) plus the trained device
        # arrays attached as the resident fast path for the first fold
        # tick / serve call. No replicating gather ever runs.
        from predictionio_tpu.parallel.mesh import host_fetch_sharded
        from predictionio_tpu.parallel.sharded_table import ShardedTable

        def _as_sharded(dev, n_rows):
            offsets, slices = host_fetch_sharded(dev)
            t = ShardedTable(slices, offsets, n_rows,
                             int(dev.shape[0]), mesh.model_parallelism)
            return t.attach_device(dev)

        U_t = _as_sharded(U, ratings.n_users)
        V_t = _as_sharded(V, ratings.n_items)
        if telemetry is not None:
            telemetry["fetch_s"] = _time.perf_counter() - t0
        return ALSModel(user_factors=U_t, item_factors=V_t,
                        rank=cfg.rank)
    if cfg.factor_sharding == "model":
        # gather the model-sharded tables through a replicating jit (a
        # direct np.asarray on a cross-process sharded array is illegal)
        import jax.numpy as jnp
        gather = __import__("jax").jit(lambda a: jnp.asarray(a),
                                       out_shardings=mesh.replicated())
        U, V = gather(U), gather(V)
    U_host = host_fetch(U)[:ratings.n_users].astype(np.float32, copy=False)
    V_host = host_fetch(V)[:ratings.n_items].astype(np.float32, copy=False)
    if telemetry is not None:
        telemetry["fetch_s"] = _time.perf_counter() - t0
    return ALSModel(user_factors=U_host, item_factors=V_host, rank=cfg.rank)


# ---------------------------------------------------------------------------
# Scoring / prediction
# ---------------------------------------------------------------------------

@functools.partial(__import__("jax").jit, static_argnames=("k",))
def _user_topk(user_factors, item_factors, user_ix, exclude_ix, k: int):
    """Single-dispatch serve path: inputs are one scalar index + a small
    padded exclude-index array (pad = -1), so only a few hundred bytes move
    host->device per query — the factor tables are device-resident."""
    import jax
    import jax.numpy as jnp
    u = user_factors[user_ix]                                  # [R]
    scores = jnp.einsum("ir,r->i", item_factors, u,
                        preferred_element_type=jnp.float32)
    safe = jnp.where(exclude_ix < 0, scores.shape[0], exclude_ix)
    scores = scores.at[safe].set(-jnp.inf, mode="drop")
    return jax.lax.top_k(scores, k)


def _pad_exclude(exclude, multiple: int = 64) -> np.ndarray:
    ex = np.asarray(exclude, dtype=np.int32).ravel()
    n = max(multiple, ((ex.size + multiple - 1) // multiple) * multiple)
    out = np.full(n, -1, dtype=np.int32)
    out[:ex.size] = ex
    return out


@functools.partial(__import__("jax").jit, static_argnames=("k",))
def _users_topk(user_factors, item_factors, user_ixs, k: int):
    """Batched top-k over EXACT-size tables — kept as the reference
    implementation the compile plane's bucketed kernel
    (``_users_topk_b`` via ``users_topk_serve``) is parity-tested
    against, the same role ``solve_rows`` plays for ``fold_in_coo``.
    Serving dispatches the bucketed path."""
    import jax
    import jax.numpy as jnp
    u = user_factors[user_ixs]                                # [B, R]
    scores = jnp.einsum("br,ir->bi", u, item_factors,
                        preferred_element_type=jnp.float32)
    return jax.lax.top_k(scores, k)


def _users_topk_impl(user_factors, item_factors, user_ixs, n_items,
                     k: int):
    """Traced body shared by the packed and unpacked serve executables
    (unjitted — always composed under one of the two jit wrappers
    below, so both variants rank identically)."""
    import jax
    import jax.numpy as jnp
    u = user_factors[user_ixs]                                # [B, R]
    scores = jnp.einsum("br,ir->bi", u, item_factors,
                        preferred_element_type=jnp.float32)
    valid = jnp.arange(item_factors.shape[0]) < n_items
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


@functools.partial(__import__("jax").jit, static_argnames=("k",))
def _users_topk_b(user_factors, item_factors, user_ixs, n_items, k: int):
    """Bucket-stable serve kernel (ISSUE 9 compile plane): the factor
    tables arrive padded to their vocab shape-buckets, so vocabulary
    growth inside a bucket changes NO traced shape — ``n_items`` rides
    along as a device scalar masking the padding rows (-inf, sorted
    last, filtered by the caller). k is a pow2 bucket, so client-chosen
    ``num`` never mints a program either."""
    return _users_topk_impl(user_factors, item_factors, user_ixs,
                            n_items, k=k)


@functools.partial(__import__("jax").jit, static_argnames=("k", "p"))
def _users_topk_b_packed(user_factors, item_factors, user_ixs, n_items,
                         k: int, p: int):
    """:func:`_users_topk_b` with the readback-plane pack fused on
    (ISSUE 19): same ranking, but the executable's ONE output is the
    contiguous ids+quantized-scores payload — k x batch x 6 bytes
    instead of two full-width arrays, so each serve window pays one
    small d2h wall. ``p`` (the pack mode) is a static bucket dim."""
    from predictionio_tpu.ops import readback
    scores, idx = _users_topk_impl(user_factors, item_factors,
                                   user_ixs, n_items, k=k)
    return readback.pack_device(scores, idx, p)


def _aot_batch_predict_builder(u: int = 0, i: int = 0, b: int = 0,
                               k: int = 0, r: int = 0, s: int = 0,
                               p: int = 0):
    """(jit_fn, example avals, statics) for one batch_predict bucket —
    what the AOT registry lowers+compiles at deploy/swap time.

    ``s`` > 0 selects the model-sharded layout (sharded online plane):
    the item table's aval carries a NamedSharding over the ``s``-wide
    model axis and the program is the two-phase per-shard top-k +
    cross-shard merge (ops/topk) — so the bucket ladder and swap-time
    warmup cover both layouts through one label.

    ``p`` > 0 selects the packed-readback variant (ISSUE 19): the pack
    is fused into the SAME executable, so the bucket's output aval IS
    the contiguous payload and steady-state packing compiles nothing —
    each (layout, pack-mode) pair owns its own warmed programs."""
    import jax
    sds = jax.ShapeDtypeStruct
    if s:
        from predictionio_tpu.compile.aot import sharded_aval
        from predictionio_tpu.ops.topk import (make_batched_sharded_topk,
                                               sharded_k_split)
        from predictionio_tpu.parallel.mesh import model_mesh
        mesh = model_mesh(s)
        k_local, k_final = sharded_k_split(k, i, s)
        fn = make_batched_sharded_topk(mesh, k_local, k_final,
                                       has_mask=False,
                                       filter_positive=False,
                                       pack=p)
        return (fn,
                (sharded_aval((b, r), np.float32, mesh=mesh),
                 sharded_aval((i, r), np.float32, "model", None,
                              mesh=mesh),
                 sds((), np.int32)),
            {})
    avals = (sds((u, r), np.float32), sds((i, r), np.float32),
             sds((b,), np.int32), sds((), np.int32))
    if p:
        return (_users_topk_b_packed, avals, {"k": k, "p": p})
    return (_users_topk_b, avals, {"k": k})


_aot_specs_registered = False


def register_aot_specs():
    """Idempotently register this module's executable specs with the
    compile plane (deferred off import so `import ops.als` stays
    side-effect-light)."""
    global _aot_specs_registered
    if _aot_specs_registered:
        return
    from predictionio_tpu.obs import costmon
    from predictionio_tpu.compile.aot import get_aot
    get_aot().register(costmon.BATCH_PREDICT, _aot_batch_predict_builder)
    _aot_specs_registered = True


def batch_predict_dims(model: "ALSModel", batch: int, k: int) -> dict:
    """The shape-bucket dims covering one batched top-k over ``model``
    — shared by the serve dispatch and the deploy/swap warm path.
    Model-sharded tables get the sharded-layout dims (``s`` = shard
    count, item bucket = the table's resident sharded bucket, no user
    dim — query vectors come from the host shard mirrors), so the same
    warm path covers both layouts."""
    from predictionio_tpu.compile import buckets as B
    from predictionio_tpu.ops import readback
    from predictionio_tpu.parallel.sharded_table import is_sharded
    p = readback.pack_flag()
    if is_sharded(model.item_factors):
        V = model.item_factors
        i_b = max(V.padded_rows,
                  B.bucket_rows_sharded(model.n_items, V.n_shards))
        return {"i": i_b, "b": B.bucket_batch(batch),
                "k": min(B.bucket_batch(k, floor=B.K_FLOOR), i_b),
                "r": model.rank, "s": V.n_shards, "p": p}
    i_b = B.bucket_rows(model.n_items)
    return {"u": B.bucket_rows(model.n_users), "i": i_b,
            "b": B.bucket_batch(batch),
            "k": min(B.bucket_batch(k, floor=B.K_FLOOR), i_b),
            "r": model.rank, "p": p}


def users_topk_serve(model: "ALSModel", user_ixs, k: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Batched serve top-k through the compile plane: tables uploaded
    at vocab-bucket shapes (cached), batch and k padded to their
    buckets, dispatched via the AOT registry (a warmed bucket runs
    zero trace / zero compile; a cold one falls back to the jit and
    adopts in the background). Returns ([n, k_b], [n, k_b]) host
    arrays — rows may carry -inf/padding entries past ``model.n_items``
    valid items, which callers drop via their finite-filter."""
    return users_topk_serve_begin(model, user_ixs, k)()


def users_topk_serve_begin(model: "ALSModel", user_ixs, k: int):
    """Two-phase serve top-k for the pipelined executor (ISSUE 14):
    enqueue the device program NOW (JAX async dispatch — the call
    returns as soon as the work is queued) and defer the device->host
    readback to the returned ``finish() -> (scores, idx)`` callable,
    so batch formation / supplement / serialization of neighboring
    windows overlap this window's device compute. The d2h copy is
    initiated here too (ops/readback ``copy_to_host_async`` — packed
    to ids + quantized scores under ``PIO_SERVE_PACK``), so ``finish``
    only waits on an already-in-flight transfer. ``finish`` is safe
    to call from another thread; calling it is the only sync."""
    from predictionio_tpu.compile import buckets as B
    from predictionio_tpu.compile.aot import get_aot
    from predictionio_tpu.obs import costmon
    from predictionio_tpu.ops import readback
    from predictionio_tpu.parallel.sharded_table import is_sharded
    from predictionio_tpu.utils.device_cache import cached_put_rows
    register_aot_specs()
    user_ixs = np.asarray(user_ixs, dtype=np.int32)
    n = user_ixs.shape[0]
    dims = batch_predict_dims(model, n, k)
    if is_sharded(model.item_factors):
        return _users_topk_serve_sharded_begin(model, user_ixs, dims)
    ixs = np.zeros(dims["b"], dtype=np.int32)
    ixs[:n] = user_ixs
    U = cached_put_rows(model.user_factors, dims["u"])
    V = cached_put_rows(model.item_factors, dims["i"])
    k_b, p = dims["k"], dims["p"]
    if p:
        packed = get_aot().dispatch(
            costmon.BATCH_PREDICT, dims,
            lambda *a: _users_topk_b_packed(*a, k=k_b, p=p),
            U, V, ixs, np.int32(model.n_items))
        fetch = readback.begin_fetch_packed(packed, p)
    else:
        scores, idx = get_aot().dispatch(
            costmon.BATCH_PREDICT, dims,
            lambda *a: _users_topk_b(*a, k=k_b),
            U, V, ixs, np.int32(model.n_items))
        # packing off still pays ONE d2h wall: both copies go in
        # flight now, the finish() below only waits
        fetch = readback.begin_fetch(scores, idx)
    # bucket promotion: a vocab nearing its bucket pre-compiles the
    # next bucket's executable in the background, BEFORE growth needs it
    aot = get_aot()
    if B.should_promote(model.n_items, dims["i"]):
        aot.ensure(costmon.BATCH_PREDICT,
                   dict(dims, i=B.next_bucket(dims["i"]),
                        k=min(k_b, B.next_bucket(dims["i"]))),
                   background=True)
    if B.should_promote(model.n_users, dims["u"]):
        aot.ensure(costmon.BATCH_PREDICT,
                   dict(dims, u=B.next_bucket(dims["u"])),
                   background=True)

    def finish() -> Tuple[np.ndarray, np.ndarray]:
        scores_h, idx_h = fetch()
        return scores_h[:n], idx_h[:n]
    return finish


def _users_topk_serve_sharded_begin(model: "ALSModel",
                                    user_ixs: np.ndarray, dims: dict):
    """The sharded serve route of :func:`users_topk_serve`: query
    vectors gathered from the USER table's host shard mirrors (the
    user table needs no serving HBM at all), the item table resident
    model-sharded, ranking via per-shard top-k + cross-shard merge
    (ops/topk.batched_sharded_top_k) dispatched through the AOT
    registry under the same ``batch_predict`` label — warmed sharded
    buckets run zero trace / zero compile, exactly like replicated
    ones. Returns a ``finish() -> (scores, idx)`` readback callable
    (the two-phase pipelined contract of users_topk_serve_begin)."""
    from predictionio_tpu.compile import buckets as B
    from predictionio_tpu.compile.aot import get_aot
    from predictionio_tpu.obs import costmon
    from predictionio_tpu.ops.topk import batched_sharded_top_k_begin
    from predictionio_tpu.parallel.mesh import model_mesh
    from predictionio_tpu.parallel.sharded_table import table_rows
    V = model.item_factors
    mesh = model_mesh(V.n_shards)
    n = user_ixs.shape[0]
    q = np.zeros((dims["b"], model.rank), dtype=np.float32)
    q[:n] = table_rows(model.user_factors, user_ixs)
    # a table padded below its covering sharded bucket (e.g. fresh
    # from training) uploads AT the bucket (zero-filled tail) and the
    # handle stays resident — the published model object is never
    # mutated from the serve path (real promotions are the fold
    # tick's job, where the host mirrors must follow)
    fetch = batched_sharded_top_k_begin(
        V.device(mesh, target_rows=dims["i"]), q, model.n_items,
        dims["k"], mesh, label=costmon.BATCH_PREDICT, dims=dims)
    if B.should_promote(model.n_items, dims["i"]):
        nxt = B.bucket_rows_sharded(dims["i"] + 1, V.n_shards,
                                    floor=B.next_bucket(dims["i"]))
        get_aot().ensure(costmon.BATCH_PREDICT,
                         dict(dims, i=nxt, k=min(dims["k"], nxt)),
                         background=True)

    def finish() -> Tuple[np.ndarray, np.ndarray]:
        scores, idx = fetch()
        return scores[:n], idx[:n]
    return finish


@functools.partial(__import__("jax").jit, static_argnames=("k",))
def _topk_scores(user_vecs, item_factors, seen_mask, k: int):
    """scores = u . V^T with seen items masked out; returns (scores, idx)."""
    import jax.numpy as jnp
    scores = jnp.einsum("br,ir->bi", user_vecs, item_factors,
                        preferred_element_type=jnp.float32)
    scores = jnp.where(seen_mask, -jnp.inf, scores)
    import jax
    return jax.lax.top_k(scores, k)


def recommend_products(model: ALSModel, user_ix: int, k: int,
                       exclude: Optional[np.ndarray] = None
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Top-k items for one user (MatrixFactorizationModel.recommendProducts
    analog). Returns (scores, item_indices). The item-factor table is
    device-cached — only the query row and mask move per call."""
    from predictionio_tpu.utils.device_cache import cached_put
    k_eff = min(k, model.n_items)
    scores, idx = _user_topk(
        cached_put(model.user_factors), cached_put(model.item_factors),
        np.int32(user_ix),
        _pad_exclude(exclude if exclude is not None else ()), k_eff)
    return np.asarray(scores), np.asarray(idx)


def recommend_products_sharded(model: ALSModel, user_ix: int, k: int,
                               mesh: Optional[MeshContext] = None,
                               exclude: Optional[np.ndarray] = None,
                               allowed_mask: Optional[np.ndarray] = None
                               ) -> Tuple[np.ndarray, np.ndarray]:
    """Serve-time top-k with BOTH factor tables kept model-sharded on the
    mesh — the P-model serve path for tables larger than one device's HBM
    (reference: controller/PAlgorithm.scala:44-125's distributed-model
    query; MLlib-side analog examples/scala-parallel-similarproduct/multi/
    src/main/scala/ALSAlgorithm.scala:146-190). The user row is gathered
    across shards by GSPMD; scoring + ranking run as a two-phase sharded
    top-k over ICI (ops/topk.sharded_top_k). Nothing is ever replicated."""
    import jax
    from predictionio_tpu.ops.topk import sharded_top_k
    from predictionio_tpu.parallel.sharded_table import is_sharded
    from predictionio_tpu.utils.device_cache import cached_put_padded

    from predictionio_tpu.utils.device_cache import cached_put

    if mesh is None and is_sharded(model.item_factors):
        from predictionio_tpu.parallel.mesh import model_mesh
        mesh = model_mesh(model.item_factors.n_shards)
    mesh = mesh or current_mesh()
    mp = mesh.model_parallelism
    sh = mesh.model_sharded(2)
    mask_sh = mesh.sharding(mesh.MODEL_AXIS)

    def _dev(table):
        # a ShardedTable already owns a resident sharded device copy
        return table.device(mesh) if is_sharded(table) \
            else cached_put_padded(table, sh, mp)

    U = _dev(model.user_factors)
    V = _dev(model.item_factors)
    has_filter = (allowed_mask is not None or
                  (exclude is not None and len(np.atleast_1d(exclude))))
    if not has_filter:
        # the padding-only mask is a pure function of (table, mp): keep it
        # alive on the model so cached_put keeps it device-resident — no
        # per-query H2D on the latency-sensitive serve path
        base = getattr(model, "_serve_mask", None)
        if base is None or base.shape[0] != V.shape[0]:
            base = np.ones(V.shape[0], dtype=bool)
            base[model.n_items:] = False
            model._serve_mask = base
        mask_dev = cached_put(base, mask_sh)
    else:
        mask = np.zeros(V.shape[0], dtype=bool)
        mask[:model.n_items] = (True if allowed_mask is None
                                else allowed_mask[:model.n_items])
        if exclude is not None and len(np.atleast_1d(exclude)):
            mask[np.asarray(exclude, dtype=np.int64)] = False
        mask_dev = jax.device_put(mask, mask_sh)
    u = _row_of(U, np.int32(user_ix))     # cross-shard gather -> replicated
    k_eff = min(k, model.n_items)
    scores, idx = sharded_top_k(V, u, k_eff, mesh,
                                allowed_mask_sharded=mask_dev)
    return scores[:k_eff], idx[:k_eff]


@functools.partial(__import__("jax").jit)
def _row_of(table, ix):
    return table[ix]


def predict_ratings(model: ALSModel, user_ix: np.ndarray,
                    item_ix: np.ndarray, chunk: int = 1 << 20) -> np.ndarray:
    """Pointwise r_hat = u . v for parallel (user, item) index arrays."""
    import jax.numpy as jnp
    import jax

    from predictionio_tpu.parallel.sharded_table import (is_sharded,
                                                         table_rows)
    if is_sharded(model.user_factors) or is_sharded(model.item_factors):
        # sharded tables: row gathers run against the host shard
        # mirrors (O(pairs * rank) host flops — the fold-tick loss
        # probe's pairs are the touched histories, not the corpus) so
        # the loss never forces a device gather of a replicated table
        out = np.empty(len(user_ix), dtype=np.float32)
        for lo in range(0, len(user_ix), chunk):
            sl = slice(lo, lo + chunk)
            out[sl] = np.sum(
                table_rows(model.user_factors, user_ix[sl])
                * table_rows(model.item_factors, item_ix[sl]), axis=-1)
        return out

    @jax.jit
    def _dot(U, V, ui, ii):
        return jnp.sum(U[ui] * V[ii], axis=-1)

    from predictionio_tpu.utils.device_cache import cached_put
    U = cached_put(model.user_factors)
    V = cached_put(model.item_factors)
    out = np.empty(len(user_ix), dtype=np.float32)
    for lo in range(0, len(user_ix), chunk):
        sl = slice(lo, lo + chunk)
        out[sl] = np.asarray(_dot(U, V, np.asarray(user_ix[sl]),
                                  np.asarray(item_ix[sl])))
    return out


def als_rmse(model: ALSModel, ratings: RatingsCOO) -> float:
    pred = predict_ratings(model, ratings.user_idx, ratings.item_idx)
    return float(np.sqrt(np.mean((pred - ratings.rating) ** 2)))
