"""Distributed top-k over model-sharded score tables.

When an item-factor table is sharded over the mesh `model` axis (catalogs
too large for one device's HBM — ALSConfig.factor_sharding='model'), serving
must rank across shards. `sharded_top_k` runs the canonical two-phase
reduction as one jitted shard_map: each device ranks its local shard
(lax.top_k), the (k, score, index) candidates are all-gathered over ICI —
k*devices values instead of the full score row — and the final top-k picks
globally. This is the serve-time analog of the reference's distributed-model
`RDD.lookup`/collect path (SURVEY.md §2.9 L/P2L/P row).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

from predictionio_tpu.parallel.mesh import MeshContext, current_mesh


def _shard_map():
    try:
        from jax import shard_map
        return shard_map, {"check_vma": False}
    except ImportError:   # jax < 0.5 spelling (and check_rep keyword)
        from jax.experimental.shard_map import shard_map
        return shard_map, {"check_rep": False}


def sharded_top_k(item_factors_sharded, query_vec, k: int,
                  mesh: Optional[MeshContext] = None,
                  allowed_mask_sharded=None
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """item_factors_sharded: [I, R] jax.Array sharded over ('model', None).
    query_vec: [R] host or device. Returns host (scores, global_indices).
    """
    import jax
    import jax.numpy as jnp
    shard_map, _vma_kw = _shard_map()
    from jax.sharding import PartitionSpec as P

    mesh = mesh or current_mesh()
    n_items = item_factors_sharded.shape[0]
    mp = mesh.model_parallelism
    shard_rows = n_items // mp
    # a shard can contribute at most shard_rows candidates, and the global
    # top-k takes at most shard_rows items from any single shard — so
    # k_local candidates per shard are sufficient for an exact answer even
    # when k exceeds shard_rows
    k_local = min(k, shard_rows)
    k_final = min(k, mp * k_local)

    @functools.partial(
        shard_map, mesh=mesh.mesh,
        in_specs=(P("model", None), P(), P("model")),
        out_specs=(P(), P()),
        **_vma_kw)
    def _local_then_global(v_shard, q, mask_shard):
        scores = jnp.einsum("ir,r->i", v_shard, q,
                            preferred_element_type=jnp.float32)
        scores = jnp.where(mask_shard, scores, -jnp.inf)
        local_s, local_i = jax.lax.top_k(scores, k_local)
        # globalize indices: shard offset from the model-axis position
        ax = jax.lax.axis_index("model")
        local_i = local_i + ax * v_shard.shape[0]
        all_s = jax.lax.all_gather(local_s, "model").reshape(-1)
        all_i = jax.lax.all_gather(local_i, "model").reshape(-1)
        top_s, pos = jax.lax.top_k(all_s, k_final)
        return top_s, all_i[pos]

    if allowed_mask_sharded is None:
        allowed_mask_sharded = jax.device_put(
            np.ones(n_items, dtype=bool), mesh.sharding("model"))
    q = jnp.asarray(query_vec, dtype=item_factors_sharded.dtype)
    scores, idx = _local_then_global(item_factors_sharded, q,
                                     allowed_mask_sharded)
    return np.asarray(scores)[:k_final], np.asarray(idx)[:k_final]


# ---------------------------------------------------------------------------
# Batched, masked, bucket-stable serve path (sharded online plane)
#
# The single-query `sharded_top_k` above is the GSPMD reference; the
# functions below are the SERVE-plane siblings: every moving dim is
# shape-bucketed (ISSUE 9 compile plane), query vectors arrive as one
# [B, R] host batch (gathered from the published model's host shard
# mirrors — the user table never needs serving HBM), the item table
# stays model-sharded in HBM, and the ranking runs the two-phase
# reduction per shard: local top-k over the shard's rows, a k*shards
# candidate all-gather over the model axis, and a global top-k — the
# full [B, I] score matrix is never replicated to one device.
# ---------------------------------------------------------------------------

def sharded_k_split(k: int, padded_rows: int,
                    n_shards: int) -> Tuple[int, int]:
    """(k_local, k_final) for one sharded ranking: a shard contributes
    at most its row count, and the final answer at most ``n_shards *
    k_local`` candidates — exact for any k (see sharded_top_k). A pure
    function of BUCKET dims only (never of the live ``n_items``), so
    vocabulary growth inside a bucket keeps every compiled shape;
    columns past the valid items carry -inf, dropped by the callers'
    finite-filter exactly as on the replicated path."""
    shard_rows = max(padded_rows // n_shards, 1)
    k_local = min(k, shard_rows)
    return k_local, min(k, n_shards * k_local)


def make_batched_sharded_topk(mesh: MeshContext, k_local: int,
                              k_final: int, has_mask: bool,
                              filter_positive: bool, pack: int = 0):
    """The jitted batched two-phase top-k for one (mesh, statics)
    combination, resolved through the compile plane's shared-jit
    surface (one process-wide jit per key; the AOT registry lowers the
    same callable with sharded avals at warm time).

    Signature of the returned callable:
    ``(q [B, R] replicated, v_shard [I, R] model-sharded, n_items ()
    int32[, mask [B, I] bool sharded on dim 1]) -> (scores [B, k_final],
    global_indices [B, k_final])`` — or, with ``pack`` > 0 (the
    readback plane, ISSUE 19), ONE replicated ``[B, k_final, slot]``
    uint8 payload: the ids+quantized-scores pack is fused after the
    cross-shard merge inside the same program, so the sharded serve
    window also pays a single small d2h wall."""
    import jax
    import jax.numpy as jnp
    from predictionio_tpu.compile.aot import get_aot

    shard_map, vma_kw = _shard_map()
    P = jax.sharding.PartitionSpec
    in_specs = [P(), P("model", None), P()]
    if has_mask:
        in_specs.append(P(None, "model"))
    out_specs = P() if pack else (P(), P())

    @functools.partial(shard_map, mesh=mesh.mesh,
                       in_specs=tuple(in_specs), out_specs=out_specs,
                       **vma_kw)
    def _kernel(q, v_shard, n_items, *mask):
        scores = jnp.einsum("br,ir->bi", q, v_shard,
                            preferred_element_type=jnp.float32)
        ax = jax.lax.axis_index("model")
        base = ax * v_shard.shape[0]
        # bucket-padding rows (global index >= n_items) rank last
        valid = (jnp.arange(v_shard.shape[0]) + base) < n_items
        allowed = valid[None, :]
        if has_mask:
            allowed = allowed & mask[0]
        if filter_positive:
            allowed = allowed & (scores > 0)
        scores = jnp.where(allowed, scores, -jnp.inf)
        local_s, local_i = jax.lax.top_k(scores, k_local)
        local_i = local_i + base
        all_s = jnp.moveaxis(
            jax.lax.all_gather(local_s, "model"), 0, 1
        ).reshape(local_s.shape[0], -1)
        all_i = jnp.moveaxis(
            jax.lax.all_gather(local_i, "model"), 0, 1
        ).reshape(local_i.shape[0], -1)
        top_s, pos = jax.lax.top_k(all_s, k_final)
        top_i = jnp.take_along_axis(all_i, pos, axis=1)
        if pack:
            from predictionio_tpu.ops import readback
            return readback.pack_device(top_s, top_i, pack)
        return top_s, top_i

    # one process-wide jit per (mesh, statics) key: the compile plane
    # constructs and holds it (shared_jit), so repeated calls here only
    # rebuild the cheap shard_map wrapper, never a fresh jit closure
    key = (f"topk.sharded_batched:{id(mesh.mesh)}:"
           f"{mesh.model_parallelism}:{k_local}:{k_final}:"
           f"{int(has_mask)}:{int(filter_positive)}:{int(pack)}")
    return get_aot().shared_jit(key, _kernel)


def batched_sharded_top_k(item_dev, query_vecs: np.ndarray,
                          n_items: int, k_bucket: int,
                          mesh: MeshContext,
                          masks: Optional[np.ndarray] = None,
                          filter_positive: bool = False,
                          label: Optional[str] = None,
                          dims: Optional[dict] = None
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """Rank ``query_vecs`` (already padded to their batch bucket)
    against the resident model-sharded ``item_dev`` table. ``masks``
    (padded [B, I_bucket] bool, or None) is uploaded sharded over the
    item dim. Dispatches through the AOT registry when ``label`` /
    ``dims`` are given (warmed buckets run zero trace / zero
    compile), else calls the shared jit directly."""
    return batched_sharded_top_k_begin(
        item_dev, query_vecs, n_items, k_bucket, mesh, masks=masks,
        filter_positive=filter_positive, label=label, dims=dims)()


def batched_sharded_top_k_begin(item_dev, query_vecs: np.ndarray,
                                n_items: int, k_bucket: int,
                                mesh: MeshContext,
                                masks: Optional[np.ndarray] = None,
                                filter_positive: bool = False,
                                label: Optional[str] = None,
                                dims: Optional[dict] = None):
    """Two-phase sibling of :func:`batched_sharded_top_k` for the
    pipelined serving executor (ISSUE 14): uploads + enqueues the
    sharded ranking NOW and returns ``finish() -> (scores, idx)``
    which performs the deferred device->host readback — so the
    cross-shard merge of window N overlaps window N+1's host-side
    batch formation. The d2h copy of the (packed) result goes in
    flight HERE via the readback plane, so ``finish`` only waits."""
    import jax
    from predictionio_tpu.obs import jaxmon
    from predictionio_tpu.ops import readback

    padded_rows = int(item_dev.shape[0])
    k_local, k_final = sharded_k_split(k_bucket, padded_rows,
                                       mesh.model_parallelism)
    p = dims["p"] if dims and "p" in dims else readback.pack_flag()
    fn = make_batched_sharded_topk(mesh, k_local, k_final,
                                   masks is not None, filter_positive,
                                   pack=p)
    q = np.ascontiguousarray(query_vecs, dtype=np.float32)
    args = [q, item_dev, np.int32(n_items)]
    if masks is not None:
        mask_dev = jax.device_put(masks, mesh.sharding(None, "model"))
        jaxmon.record_h2d(masks.nbytes)
        args.append(mask_dev)
    jaxmon.record_h2d(q.nbytes)
    if label is not None and dims is not None:
        from predictionio_tpu.compile.aot import get_aot
        out = get_aot().dispatch(label, dims, fn, *args)
    else:
        from predictionio_tpu.obs.costmon import device_timed
        out = device_timed(label or "sharded_topk", fn, *args)
    if p:
        return readback.begin_fetch_packed(out, p)
    scores, idx = out
    fetch = readback.begin_fetch(scores, idx)

    def finish() -> Tuple[np.ndarray, np.ndarray]:
        scores_h, idx_h = fetch()
        return scores_h, idx_h
    return finish
