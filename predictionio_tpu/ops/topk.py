"""Distributed top-k over model-sharded score tables.

When an item-factor table is sharded over the mesh `model` axis (catalogs
too large for one device's HBM — ALSConfig.factor_sharding='model'), serving
must rank across shards. `sharded_top_k` runs the canonical two-phase
reduction as one jitted shard_map: each device ranks its local shard
(lax.top_k), the (k, score, index) candidates are all-gathered over ICI —
k*devices values instead of the full score row — and the final top-k picks
globally. This is the serve-time analog of the reference's distributed-model
`RDD.lookup`/collect path (SURVEY.md §2.9 L/P2L/P row).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

from predictionio_tpu.parallel.mesh import MeshContext, current_mesh


def sharded_top_k(item_factors_sharded, query_vec, k: int,
                  mesh: Optional[MeshContext] = None,
                  allowed_mask_sharded=None
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """item_factors_sharded: [I, R] jax.Array sharded over ('model', None).
    query_vec: [R] host or device. Returns host (scores, global_indices).
    """
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map
        _vma_kw = {"check_vma": False}
    except ImportError:   # jax < 0.5 spelling (and check_rep keyword)
        from jax.experimental.shard_map import shard_map
        _vma_kw = {"check_rep": False}
    from jax.sharding import PartitionSpec as P

    mesh = mesh or current_mesh()
    n_items = item_factors_sharded.shape[0]
    mp = mesh.model_parallelism
    shard_rows = n_items // mp
    # a shard can contribute at most shard_rows candidates, and the global
    # top-k takes at most shard_rows items from any single shard — so
    # k_local candidates per shard are sufficient for an exact answer even
    # when k exceeds shard_rows
    k_local = min(k, shard_rows)
    k_final = min(k, mp * k_local)

    @functools.partial(
        shard_map, mesh=mesh.mesh,
        in_specs=(P("model", None), P(), P("model")),
        out_specs=(P(), P()),
        **_vma_kw)
    def _local_then_global(v_shard, q, mask_shard):
        scores = jnp.einsum("ir,r->i", v_shard, q,
                            preferred_element_type=jnp.float32)
        scores = jnp.where(mask_shard, scores, -jnp.inf)
        local_s, local_i = jax.lax.top_k(scores, k_local)
        # globalize indices: shard offset from the model-axis position
        ax = jax.lax.axis_index("model")
        local_i = local_i + ax * v_shard.shape[0]
        all_s = jax.lax.all_gather(local_s, "model").reshape(-1)
        all_i = jax.lax.all_gather(local_i, "model").reshape(-1)
        top_s, pos = jax.lax.top_k(all_s, k_final)
        return top_s, all_i[pos]

    if allowed_mask_sharded is None:
        allowed_mask_sharded = jax.device_put(
            np.ones(n_items, dtype=bool), mesh.sharding("model"))
    q = jnp.asarray(query_vec, dtype=item_factors_sharded.dtype)
    scores, idx = _local_then_global(item_factors_sharded, q,
                                     allowed_mask_sharded)
    return np.asarray(scores)[:k_final], np.asarray(idx)[:k_final]
