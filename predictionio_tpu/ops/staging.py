"""Device staging primitives for the bulk data plane (ISSUE 16).

The dataplane executor (``predictionio_tpu/dataplane``) lives in the
pipelined zone: no host sync may appear there, the same contract the
serving executor carries (JAX006). The two operations that must touch
the device — the async upload submit and the bounded-slot completion
wait — therefore live HERE, in the ops layer, next to the other
finish()-style sync points:

* :func:`device_stage` — pad a chunk's numeric columns to the compile
  plane's pow2 row bucket and submit an async ``jax.device_put``,
  attributing the bytes to the obs plane (``pio_jax_h2d_bytes_total``
  via ``jaxmon.record_h2d``). Padding means a stream of arbitrary
  chunk sizes produces only O(log n) distinct device shapes, so any
  downstream jitted consumer compiles per bucket, never per chunk —
  zero XLA compiles in the steady streaming phase.
* :func:`wait_ready` — block until a staged segment's transfer has
  completed. The dataplane calls this only when its two-slot in-flight
  window is full (that wait IS the double-buffer back-pressure) and
  once at finalize.
"""

from __future__ import annotations

import time
from typing import Dict, Mapping, Tuple

import numpy as np

from predictionio_tpu.compile.buckets import bucket_rows
from predictionio_tpu.obs import jaxmon


def pad_to_bucket(arr: np.ndarray, rows: int) -> np.ndarray:
    """Zero-pad a 1-D column to ``rows`` (a pow2 bucket) so staged
    shapes come from the compile plane's ladder, not from chunk sizes."""
    n = len(arr)
    if n == rows:
        return np.ascontiguousarray(arr)
    out = np.zeros(rows, dtype=arr.dtype)
    out[:n] = arr
    return out


def device_stage(arrays: Mapping[str, np.ndarray]
                 ) -> Tuple[Dict[str, "object"], int, int, float]:
    """Submit one chunk's numeric columns to the device asynchronously.

    Every column is padded to the SAME pow2 row bucket
    (``compile.buckets.bucket_rows`` of the longest column) and shipped
    with ``jax.device_put``; the put is async on real accelerators, so
    the caller's next chunk decodes while this one's bytes move.
    Returns ``(device_arrays, valid_rows, padded_rows, submit_s)``;
    the uploaded bytes are recorded on the obs plane.
    """
    import jax

    rows = max((len(a) for a in arrays.values()), default=0)
    padded = bucket_rows(rows) if rows else 0
    t0 = time.perf_counter()
    out: Dict[str, "object"] = {}
    nbytes = 0
    for name, arr in arrays.items():
        host = pad_to_bucket(np.asarray(arr), padded)
        out[name] = jax.device_put(host)
        nbytes += host.nbytes
    jaxmon.record_h2d(nbytes)
    return out, rows, padded, time.perf_counter() - t0


def wait_ready(device_arrays: Mapping[str, "object"]) -> float:
    """Block until every array of a staged segment is resident on
    device; returns the seconds spent blocked. This is the data plane's
    ONLY completion wait — called from the ops layer so the pipelined
    dataplane modules stay sync-free (the JAX006 contract)."""
    import jax

    t0 = time.perf_counter()
    for a in device_arrays.values():
        jax.block_until_ready(a)
    return time.perf_counter() - t0
