"""Host-side ratings preprocessing: COO -> bucketed, padded solve plans.

This is the ragged->fixed-shape edge (SURVEY.md hard part #3): events per
user/item are power-law ragged, XLA wants static shapes. Entities are
bucketed by rating count into geometric-ladder segment lengths K
(bucket_lengths); each bucket is processed as [B, K] padded batches with B
chosen to keep B*K work roughly constant, so the whole sweep compiles to a
ladder's worth of kernel shapes consumed by one scan program per side.

Replaces the grouping/shuffle phase of MLlib's block ALS (reference consumer:
examples/scala-parallel-recommendation/custom-prepartor/src/main/scala/
ALSAlgorithm.scala:55 `ALS.train`), and the `((u,i),1).reduceByKey` rating
construction of the similarproduct template
(examples/scala-parallel-similarproduct/multi/src/main/scala/ALSAlgorithm.scala:96-133).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class RatingsCOO:
    """Deduplicated (user, item, rating) triples with dense int32 indices."""
    user_idx: np.ndarray   # [nnz] int32
    item_idx: np.ndarray   # [nnz] int32
    rating: np.ndarray     # [nnz] float32
    n_users: int
    n_items: int

    @property
    def nnz(self) -> int:
        return int(self.user_idx.shape[0])

    def transpose(self) -> "RatingsCOO":
        return RatingsCOO(self.item_idx, self.user_idx, self.rating,
                          self.n_items, self.n_users)


def dedup_ratings(user_idx, item_idx, rating, timestamps=None,
                  policy: str = "latest") -> Tuple[np.ndarray, np.ndarray,
                                                   np.ndarray]:
    """Collapse duplicate (user, item) pairs.

    policy:
      'latest' — keep the rating with the greatest timestamp (the reference
                 recommendation DataSource semantics for re-rated items);
                 requires `timestamps` (falls back to last occurrence).
      'sum'    — sum ratings (the similarproduct view-count semantics,
                 `((u,i),1).reduceByKey(_+_)`).
      'mean'   — average duplicates.
    """
    user_idx = np.asarray(user_idx, dtype=np.int64)
    item_idx = np.asarray(item_idx, dtype=np.int64)
    rating = np.asarray(rating, dtype=np.float32)
    if user_idx.size == 0:
        return (user_idx.astype(np.int32), item_idx.astype(np.int32), rating)
    n_items = int(item_idx.max()) + 1
    pair = user_idx * n_items + item_idx
    if policy == "latest":
        order = (np.argsort(timestamps, kind="stable")
                 if timestamps is not None else np.arange(pair.size))
        pair_o = pair[order]
        # keep the last occurrence in time order
        uniq, last_pos = np.unique(pair_o[::-1], return_index=True)
        keep = order[::-1][last_pos]
        keep.sort()
        return (user_idx[keep].astype(np.int32),
                item_idx[keep].astype(np.int32), rating[keep])
    uniq, inv = np.unique(pair, return_inverse=True)
    sums = np.bincount(inv, weights=rating.astype(np.float64))
    if policy == "mean":
        counts = np.bincount(inv)
        sums = sums / counts
    elif policy != "sum":
        raise ValueError(f"unknown dedup policy {policy!r}")
    return ((uniq // n_items).astype(np.int32),
            (uniq % n_items).astype(np.int32),
            sums.astype(np.float32))


@dataclass(frozen=True)
class SolveBatch:
    """One fixed-shape batch of entities to solve: gather `idx` rows of the
    counterpart factor table, weight by `val`, mask padding."""
    rows: np.ndarray    # [B] int32 — dense indices being solved; padding = -1
    idx: np.ndarray     # [B, K] int32 — counterpart indices; padding = 0
    val: np.ndarray     # [B, K] float32 — ratings; padding = 0
    mask: np.ndarray    # [B, K] float32 — 1 for real entries

    @property
    def shape(self) -> Tuple[int, int]:
        return self.idx.shape


@dataclass(frozen=True)
class SolvePlan:
    """All batches needed to solve one side of the factorization."""
    batches: Sequence[SolveBatch]
    n_entities: int
    nnz: int

    @property
    def kernel_shapes(self):
        return sorted({b.shape for b in self.batches})

    @property
    def padded_work(self) -> int:
        """Total padded gather/Gram positions: real entities x their
        padded segment length K."""
        return sum(int(np.count_nonzero(b.rows >= 0)) * b.shape[1]
                   for b in self.batches)

    @property
    def padding_overhead(self) -> float:
        """padded work / real work — the Gram FLOP inflation from the
        ragged->fixed bucketing (1.0 = no waste)."""
        if self.nnz == 0:
            return 1.0
        return self.padded_work / self.nnz


def bucket_lengths(max_count: int, min_k: int = 8,
                   ratio: float = 1.125) -> np.ndarray:
    """Padded segment lengths: a geometric ladder (ratio ~1.125) aligned
    to the gather buffer's layout granularity — multiples of 8 (the f32
    sublane tile, so a finer K would occupy the same HBM anyway) up to
    128, then coarser powers of two (16/32/64/128) chosen so the rounding
    never dominates the geometric step. The odd multiples of 8 below 128
    (24, 40, 56, ...) are 8- but not 16-aligned: the f32 factor-row
    gather — the dominant HBM term — is exact at them, while the bf16
    compute intermediate may round its sublane dim up to the next 16, in
    which case its cost equals (never exceeds) a 16-aligned ladder's.
    Bounds the
    per-entity Gram/gather padding waste at ~12-33% (12% asymptotic,
    granularity-bound below 32) through the whole mid-range where the
    rating-count mass sits, vs the up-to-2x windows of pow2 buckets
    (rounds 1-3: (8,16],(16,32],(32,64] each cost 2x worst-case, which
    is exactly where ML-20M's 20+-ratings-per-user floor lands).
    ~50 sizes to 20k; every size is a scan group inside
    the ONE _solve_sweep program, so the cost is compile time (amortized
    by the persistent compilation cache), not dispatches."""
    sizes = []
    k = min_k
    while True:
        sizes.append(k)
        if k >= max_count:
            break
        t = k * ratio
        step = (8 if t < 128 else 16 if t < 512 else
                32 if t < 2048 else 64 if t < 8192 else 128)
        k = max(int(np.ceil(t / step) * step), k + step)
    return np.array(sizes, dtype=np.int64)


def build_solve_plan(group_idx: np.ndarray, counter_idx: np.ndarray,
                     values: np.ndarray, n_groups: int,
                     work_budget: int = 1 << 20, min_k: int = 8,
                     batch_multiple: int = 1,
                     bucket_ratio: float = 1.125) -> SolvePlan:
    """Group COO entries by `group_idx`, bucket groups by padded segment
    length K (geometric ladder, bucket_lengths), and emit [B, K] batches
    with B ~= work_budget/K rounded up to `batch_multiple` (the mesh
    data-parallel degree).

    Vectorized host numpy — no per-entity Python loops.
    """
    group_idx = np.asarray(group_idx, dtype=np.int64)
    counter_idx = np.asarray(counter_idx, dtype=np.int32)
    values = np.asarray(values, dtype=np.float32)
    nnz = group_idx.size

    order = np.argsort(group_idx, kind="stable")
    g_sorted = group_idx[order]
    c_sorted = counter_idx[order]
    v_sorted = values[order]
    counts = np.bincount(g_sorted, minlength=n_groups).astype(np.int64)
    starts = np.zeros(n_groups + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])

    present = np.nonzero(counts)[0]
    if present.size == 0:
        return SolvePlan(batches=(), n_entities=n_groups, nnz=0)
    sizes = bucket_lengths(int(counts[present].max()), min_k,
                           ratio=bucket_ratio)
    ks = sizes[np.searchsorted(sizes, counts[present], side="left")]
    # Merge SPARSE buckets upward: a bucket holding a handful of
    # entities still costs a whole scan group in the compiled sweep
    # (XLA program size — the finer ladder's one real cost, measured as
    # minutes of full-scale compile) for almost no work. Entities move
    # to the next ladder size while their cumulative padding stays
    # within `merge_cap` of their ORIGINAL bucket, so the tail giants
    # (one entity per bucket by nature, big nnz) never cascade into a
    # 2x-padded monster bucket.
    min_bucket, merge_cap, work_share = 32, 1.25, 0.002
    ks_orig = ks.copy()
    cnts_present = counts[present]
    for i in range(len(sizes) - 1):
        members = ks == sizes[i]
        n_mem = int(np.count_nonzero(members))
        # merge only buckets that are BOTH sparse and a negligible share
        # of the total work — at small scale every bucket is sparse and
        # merging would buy padding for nothing; at full scale this
        # fires exactly on the long tail of near-singleton buckets
        if (0 < n_mem < min_bucket
                and int(cnts_present[members].sum()) < work_share * nnz):
            movable = members & (sizes[i + 1] <= merge_cap * ks_orig)
            if movable.sum() == n_mem:
                # move only when the WHOLE bucket can go — a partial
                # move keeps the source group alive and buys padding
                # without reducing the compiled program
                ks[movable] = sizes[i + 1]

    batches: List[SolveBatch] = []
    for k in np.unique(ks):
        members = present[ks == k]  # entities padded to this K
        b_full = max(int(work_budget // k), 1)
        b_full = ((b_full + batch_multiple - 1) // batch_multiple
                  ) * batch_multiple
        for lo in range(0, members.size, b_full):
            chunk = members[lo:lo + b_full]
            b = ((chunk.size + batch_multiple - 1) // batch_multiple
                 ) * batch_multiple
            rows = np.full(b, -1, dtype=np.int32)
            rows[:chunk.size] = chunk
            idx = np.zeros((b, int(k)), dtype=np.int32)
            val = np.zeros((b, int(k)), dtype=np.float32)
            mask = np.zeros((b, int(k)), dtype=np.float32)
            # vectorized fill: flat positions row*k + [0..count)
            cnts = counts[chunk]
            row_of = np.repeat(np.arange(chunk.size), cnts)
            # position within each segment
            pos = np.arange(row_of.size) - np.repeat(
                np.concatenate([[0], np.cumsum(cnts)[:-1]]), cnts)
            src = np.repeat(starts[chunk], cnts) + pos
            idx[row_of, pos] = c_sorted[src]
            val[row_of, pos] = v_sorted[src]
            mask[row_of, pos] = 1.0
            batches.append(SolveBatch(rows, idx, val, mask))
    return SolvePlan(batches=tuple(batches), n_entities=n_groups, nnz=nnz)


def plan_for_users(r: RatingsCOO, **kw) -> SolvePlan:
    return build_solve_plan(r.user_idx, r.item_idx, r.rating, r.n_users, **kw)


def plan_for_items(r: RatingsCOO, **kw) -> SolvePlan:
    return build_solve_plan(r.item_idx, r.user_idx, r.rating, r.n_items, **kw)
