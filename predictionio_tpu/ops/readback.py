"""Serve readback plane: packed payloads + overlapped d2h (ISSUE 19).

The live TPU capture said serving lost to the READBACK, not the
compute: ``d2h_floor_ms`` is 74.8 ms — a fixed device→host latency
paid once per serve window while the device idles — and it is
IDENTICAL for 40-byte and 400KB payloads (latency-bound, not
bandwidth-bound). Two conclusions, both implemented here:

* **Fewer walls.** One contiguous on-device payload per window instead
  of two full-width arrays: int32 ids + float16-quantized scores,
  ``k x batch x 6`` bytes (:func:`pack_device`, fused INSIDE the
  jitted serve kernels so the AOT bucket's output aval IS the packed
  array and steady-state packing compiles nothing). Even with packing
  off, the begin/finish closures route both result arrays through ONE
  :func:`begin_fetch` call — one d2h wall per window, never two.
* **Overlapped walls.** :func:`begin_fetch` initiates
  ``copy_to_host_async()`` at DISPATCH time, on the formation thread —
  the transfer rides behind the device compute and behind neighboring
  windows' completions. The finish() closure only *waits* on an
  already-in-flight copy, so with ``PIO_SERVE_INFLIGHT`` >= 3 the K
  in-flight windows' d2h walls overlap instead of serialize (the d2h
  dual of the PR 16 ``DeviceStager`` h2d slots in dataplane/upload.py:
  each in-flight window holds its own device output slot, bounded by
  the executor's inflight semaphore).

This module is the ONE sanctioned serve d2h site (the d2h mirror of
``ops/staging.py`` for h2d): it lives in the ops layer so the
pipelined modules (serving/, tenancy/, dataplane/) stay host-sync-free
(the JAX006 contract), and every byte it moves is attributed —
``jaxmon.record_d2h``, ``pio_serve_d2h_seconds_total{phase}``,
``pio_serve_d2h_bytes_total``, per-tenant bytes via the obs-plane
tenant context, and a module snapshot (:func:`stats_snapshot`) that
bench turns into ``serve_d2h_overlap_frac`` /
``serve_readback_bytes_per_window``.

Env gates:

* ``PIO_SERVE_PACK=on`` (default) — f16-quantized packed payloads.
* ``PIO_SERVE_PACK=exact`` — packed single payload, full f32 scores
  (8 bytes/slot): one wall, bit-exact scores.
* ``PIO_SERVE_PACK=off`` — legacy two-array results (still fetched
  through one overlapped wall).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional, Tuple

import numpy as np

from predictionio_tpu.obs import jaxmon, tenantctx
from predictionio_tpu.obs.metrics import get_registry

# -- pack modes (the AOT bucket dim ``p``) -------------------------------

#: legacy two-array results (scores f32 + ids i32, two avals)
PACK_OFF = 0
#: one uint8 payload per window: i32 ids + f16 scores = 6 bytes/slot
PACK_F16 = 1
#: one uint8 payload per window: i32 ids + f32 scores = 8 bytes/slot
PACK_EXACT = 2

#: bytes per (id, score) slot by pack mode
SLOT_BYTES = {PACK_F16: 6, PACK_EXACT: 8}


def pack_flag() -> int:
    """The pack mode serving currently runs under — read per dispatch
    (cheap) so tests and operators can flip ``PIO_SERVE_PACK`` live.
    The value rides the bucket dims as ``p``, so each mode owns its own
    AOT programs and flipping modes never invalidates warmed buckets of
    the other."""
    v = os.environ.get("PIO_SERVE_PACK", "on").strip().lower()
    if v in ("off", "0", "false", "no"):
        return PACK_OFF
    if v == "exact":
        return PACK_EXACT
    return PACK_F16


# -- device-side pack (called INSIDE jitted serve kernels) ---------------

def pack_device(scores, idx, p: int):
    """Fuse ``(scores [B,K] f32, idx [B,K] i32)`` into one contiguous
    ``[B, K, slot]`` uint8 payload ON DEVICE — ranking happened before
    this point, so ids are byte-identical to the unpacked path; scores
    are f16-quantized under :data:`PACK_F16` (wire format: 4 id bytes
    then 2 or 4 score bytes per slot, device-native little-endian).
    Must be traced inside the serve kernel's jit so the executable
    emits the packed aval directly (one output buffer, one transfer)."""
    import jax.numpy as jnp
    from jax import lax
    ids8 = lax.bitcast_convert_type(idx.astype(jnp.int32), jnp.uint8)
    if p == PACK_EXACT:
        sc8 = lax.bitcast_convert_type(scores.astype(jnp.float32),
                                       jnp.uint8)
    else:
        sc8 = lax.bitcast_convert_type(scores.astype(jnp.float16),
                                       jnp.uint8)
    return jnp.concatenate([ids8, sc8], axis=-1)


def unpack_host(buf: np.ndarray, p: int
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side inverse of :func:`pack_device`: ``[B, K, slot]`` uint8
    → ``(scores f32 [B,K], idx i32 [B,K])``. Pure numpy views + one
    cast — no device interaction (the payload already crossed in
    :func:`begin_fetch`'s single wall). f16 scores upcast to f32 so
    downstream finite-filters and serialization see the usual dtype."""
    b = np.asarray(buf)
    ids = np.ascontiguousarray(b[..., :4]).view(np.int32)[..., 0]
    if p == PACK_EXACT:
        sc = np.ascontiguousarray(b[..., 4:8]).view(np.float32)[..., 0]
    else:
        sc = np.ascontiguousarray(
            b[..., 4:6]).view(np.float16)[..., 0].astype(np.float32)
    return sc, ids


# -- the instrumented overlapped d2h site --------------------------------

class _Stats:
    """Cumulative readback accounting (process-global, lock-guarded).

    ``span_s`` is wall time from copy initiation to fetch completion;
    ``submit_s + wait_s`` is the part of it a thread was actually
    blocked. Their ratio is the overlap fraction: ~0 when completions
    serialize their full readback (the pre-ISSUE-19 behavior), →1 when
    the copy finished behind other windows' work and the completion
    thread only picked up bytes already on the host."""

    def __init__(self):
        self.lock = threading.Lock()
        self.windows = 0
        self.bytes = 0
        self.submit_s = 0.0
        self.wait_s = 0.0
        self.span_s = 0.0


_STATS = _Stats()
_TLS = threading.local()
_metrics_lock = threading.Lock()
_metrics = {}


def _get_metrics():
    with _metrics_lock:
        if not _metrics:
            reg = get_registry()
            _metrics["seconds"] = reg.counter(
                "pio_serve_d2h_seconds_total",
                "Serve readback device->host seconds by phase "
                "(submit = async-copy initiation, wait = blocked "
                "completion wait)", labelnames=("phase",))
            _metrics["bytes"] = reg.counter(
                "pio_serve_d2h_bytes_total",
                "Serve readback bytes fetched device->host")
            _metrics["windows"] = reg.counter(
                "pio_serve_readback_windows_total",
                "Serve windows fetched through the readback plane")
            _metrics["tenant_bytes"] = reg.counter(
                "pio_tenant_serve_d2h_bytes_total",
                "Serve readback bytes by tenant",
                labelnames=("tenant",))
        return _metrics


def thread_wait_s() -> float:
    """Seconds THIS thread has spent blocked inside :func:`begin_fetch`
    waits, cumulative. The pipelined executor samples the delta around
    ``finish()`` to decompose its completion stage into wait-for-copy
    vs post-process without itself touching a device handle (JAX006)."""
    return getattr(_TLS, "wait_s", 0.0)


def thread_d2h_bytes() -> int:
    """Bytes THIS thread has fetched through the readback plane,
    cumulative — same delta-sampling contract as :func:`thread_wait_s`."""
    return getattr(_TLS, "bytes", 0)


def begin_fetch(*arrays, tenant: Optional[str] = None
                ) -> Callable[[], Tuple[np.ndarray, ...]]:
    """Initiate the device→host copy of ``arrays`` NOW (async,
    non-blocking — call this on the dispatch/formation thread right
    after enqueueing the serve kernel) and return a ``wait()`` callable
    that blocks until the bytes are on the host and returns them as
    numpy arrays, attributing seconds/bytes to the obs plane.

    Passing MULTIPLE arrays still costs one d2h wall: every copy is
    in flight before the first wait starts, so the transfers overlap
    each other (this is the packing-off fusion path). The per-window
    device outputs double-buffer naturally — each in-flight window
    owns its own output slot until its ``wait()`` drains it, bounded
    by the executor's ``PIO_SERVE_INFLIGHT`` semaphore."""
    if tenant is None:
        tenant = tenantctx.current_tenant()
    t0 = time.perf_counter()
    for a in arrays:
        start = getattr(a, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except Exception:
                pass  # backend without async d2h: wait() still works
    submit_s = time.perf_counter() - t0

    def wait() -> Tuple[np.ndarray, ...]:
        t1 = time.perf_counter()
        host = tuple(np.asarray(a) for a in arrays)
        t2 = time.perf_counter()
        wait_s = t2 - t1
        nbytes = sum(int(h.nbytes) for h in host)
        _TLS.wait_s = getattr(_TLS, "wait_s", 0.0) + wait_s
        _TLS.bytes = getattr(_TLS, "bytes", 0) + nbytes
        jaxmon.record_d2h(nbytes)
        m = _get_metrics()
        m["seconds"].labels(phase="submit").inc(submit_s)
        m["seconds"].labels(phase="wait").inc(wait_s)
        m["bytes"].inc(nbytes)
        m["windows"].inc()
        if tenant:
            m["tenant_bytes"].labels(tenant=str(tenant)).inc(nbytes)
        with _STATS.lock:
            _STATS.windows += 1
            _STATS.bytes += nbytes
            _STATS.submit_s += submit_s
            _STATS.wait_s += wait_s
            _STATS.span_s += t2 - t0
        return host
    return wait


def begin_fetch_packed(packed, p: int, tenant: Optional[str] = None
                       ) -> Callable[[], Tuple[np.ndarray, np.ndarray]]:
    """:func:`begin_fetch` + :func:`unpack_host` in one closure: the
    shape every packed serve path wants — async copy initiated now,
    ``wait() -> (scores, idx)`` host arrays later."""
    fetch = begin_fetch(packed, tenant=tenant)

    def wait() -> Tuple[np.ndarray, np.ndarray]:
        (buf,) = fetch()
        return unpack_host(buf, p)
    return wait


def stats_snapshot() -> dict:
    """Cumulative readback counters + derived overlap fraction — bench
    diffs two snapshots around its timed phase to report
    ``serve_d2h_overlap_frac`` and ``serve_readback_bytes_per_window``."""
    with _STATS.lock:
        s = {"windows": _STATS.windows, "bytes": _STATS.bytes,
             "submit_s": _STATS.submit_s, "wait_s": _STATS.wait_s,
             "span_s": _STATS.span_s}
    s["overlap_frac"] = overlap_frac(s)
    return s


def overlap_frac(snap: dict, base: Optional[dict] = None) -> float:
    """Fraction of the readback span hidden behind other work:
    ``1 - blocked/span`` over ``snap`` (optionally minus a ``base``
    snapshot). 1.0 for an empty window (nothing exposed, nothing to
    hide — the DeviceStager convention)."""
    keys = ("submit_s", "wait_s", "span_s")
    d = {k: snap[k] - (base[k] if base else 0.0) for k in keys}
    if d["span_s"] <= 0.0:
        return 1.0
    return max(0.0, min(1.0, 1.0 - (d["submit_s"] + d["wait_s"])
                        / d["span_s"]))
