"""Multi-process SPMD query coordination for mesh-sharded models.

The reference's P-serve contract is HTTP-to-distributed-lookup: the
driver's HTTP route evaluates a query against a cluster-resident model and
every executor participates (reference: core/src/main/scala/io/prediction/
workflow/CreateServer.scala:490-641 — the query path over a live
SparkContext; controller/PAlgorithm.scala:44-125 — the distributed-model
predict). The TPU-native equivalent: under multi-controller JAX every
process must enter the SAME XLA program in the SAME order, so the HTTP
frontend (process 0) broadcasts each query payload to all processes
before any device work, and worker processes sit in a loop running the
identical predict pipeline against their shards of the model.

Transport: ``jax.experimental.multihost_utils.broadcast_one_to_all`` over
a fixed-size byte buffer — the broadcast itself is a device collective,
so it doubles as the ordering barrier; a host-side lock on the primary
keeps concurrent HTTP threads from interleaving two queries' collectives.

Contract for engines served this way: ``Serving.supplement`` must be
deterministic given the query (each process re-derives the supplemented
query locally — the same closure-determinism the reference requires of
executor-evaluated serve code), and feedback/plugins should be enabled
only on the primary.
"""

from __future__ import annotations

import json
import logging
import threading
from contextlib import contextmanager
from typing import Callable, Optional

import numpy as np

logger = logging.getLogger(__name__)

_SHUTDOWN = 0xFFFFFFFF


class MeshServingUnavailable(RuntimeError):
    """The mesh coordinator cannot serve: a broadcast collective failed to
    complete (a worker process is dead or wedged) and the coordinator is
    poisoned. Maps to HTTP 503 — the operator must redeploy the mesh, the
    same recovery the reference's MasterActor expects after an executor
    loss (CreateServer.scala:277-400 bind-retry/undeploy role)."""

    http_status = 503


class MeshQueryCoordinator:
    """Serializes and broadcasts query payloads so every JAX process runs
    the same SPMD predict program in the same order.

    Primary (process 0) wraps each query's device work in
    ``serialized(payload)``; workers run ``worker_loop(handler)`` and the
    handler re-executes the same pipeline. Payloads are JSON objects
    (a dict for single queries, a list for micro-batched windows).
    """

    def __init__(self, max_bytes: int = 1 << 16,
                 broadcast_timeout_s: float = 30.0):
        import jax
        self.max_bytes = max_bytes
        self.broadcast_timeout_s = broadcast_timeout_s
        self.n_processes = jax.process_count()
        self.is_primary = jax.process_index() == 0
        self._lock = threading.Lock()
        self._down = False
        # poisoned = a broadcast never completed (dead/wedged worker):
        # every subsequent query fails fast with 503 instead of queueing
        # behind a collective that will never finish
        self._poisoned = False

    @property
    def multi_process(self) -> bool:
        return self.n_processes > 1

    def health(self) -> dict:
        """Operator-visible coordinator state, surfaced in /stats.json,
        /metrics, the engine status page, and `pio servers` (round-4
        verdict stretch: the poisoned state was visible only as 503s).
        poisoned = a broadcast never completed (dead/wedged worker);
        every query answers 503 until the mesh is redeployed."""
        return {"processes": self.n_processes,
                "poisoned": self._poisoned,
                "shutdown": self._down}

    @classmethod
    def create_if_distributed(cls, max_bytes: int = 1 << 16,
                              broadcast_timeout_s: float = 30.0
                              ) -> Optional["MeshQueryCoordinator"]:
        """A coordinator when running under a multi-process mesh, else
        None (single-process serving needs no broadcast)."""
        try:
            import jax
            if jax.process_count() > 1:
                return cls(max_bytes=max_bytes,
                           broadcast_timeout_s=broadcast_timeout_s)
        except Exception:  # jax not initialized — plain local serving
            pass
        return None

    # -- wire format --------------------------------------------------------
    def _encode(self, obj) -> np.ndarray:
        data = json.dumps(obj).encode("utf-8")
        if len(data) > self.max_bytes - 4:
            raise ValueError(
                f"query payload {len(data)}B exceeds the mesh broadcast "
                f"buffer ({self.max_bytes - 4}B); raise max_bytes")
        buf = np.zeros(self.max_bytes, np.uint8)
        buf[:4] = np.frombuffer(
            np.uint32(len(data)).tobytes(), np.uint8)
        buf[4:4 + len(data)] = np.frombuffer(data, np.uint8)
        return buf

    @staticmethod
    def _decode(buf: np.ndarray):
        n = int(np.frombuffer(buf[:4].tobytes(), np.uint32)[0])
        if n == _SHUTDOWN:
            return None
        return json.loads(buf[4:4 + n].tobytes().decode("utf-8"))

    def _bcast(self, buf: np.ndarray) -> np.ndarray:
        from jax.experimental import multihost_utils
        out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
        # the broadcast is a psum, and some backends promote its u8
        # operand (CPU gloo returns int32): values are intact but
        # _decode slices BYTES, so restore the wire dtype — without
        # this the worker json-parses NUL-interleaved text, dies, and
        # the primary's next collective hangs into the watchdog
        if out.dtype != np.uint8:
            out = out.astype(np.uint8)
        return out

    def _bcast_watched(self, buf: np.ndarray) -> np.ndarray:
        """Primary-side broadcast under a watchdog. The collective blocks
        forever if a participant process is gone, so it runs in a daemon
        thread with a deadline; on timeout the coordinator is POISONED —
        the hung thread is abandoned (it can never be cancelled), no
        further broadcasts are attempted, and every queued/future query
        raises MeshServingUnavailable (503) instead of waiting on a
        collective with a missing participant."""
        done = threading.Event()
        result: list = []

        def run():
            try:
                result.append(self._bcast(buf))
            except BaseException as e:  # runtime teardown raises SystemExit
                result.append(e)
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True,
                             name="mesh-bcast-watchdog")
        t.start()
        if not done.wait(self.broadcast_timeout_s):
            self._poisoned = True
            logger.critical(
                "mesh broadcast did not complete within %.1fs — a worker "
                "process is dead or wedged; coordinator poisoned, all "
                "further mesh queries answer 503 until redeploy",
                self.broadcast_timeout_s)
            raise MeshServingUnavailable(
                f"mesh broadcast timed out after "
                f"{self.broadcast_timeout_s:.1f}s (worker dead?); "
                f"redeploy the mesh")
        out = result[0]
        if isinstance(out, BaseException):
            self._poisoned = True
            logger.critical("mesh broadcast failed (%s: %s) — "
                            "coordinator poisoned", type(out).__name__, out)
            raise MeshServingUnavailable(
                f"mesh broadcast failed: {out}") from out
        return out

    # -- primary side -------------------------------------------------------
    @contextmanager
    def serialized(self, payload):
        """Primary: broadcast `payload` then hold the SPMD slot while the
        caller runs the device work (collective order across processes
        equals broadcast order). Worker side: a plain pass-through —
        ordering is the sequential worker loop."""
        if not self.multi_process or not self.is_primary:
            yield
            return
        if self._poisoned:
            raise MeshServingUnavailable(
                "mesh coordinator is poisoned (earlier broadcast never "
                "completed); redeploy the mesh")
        with self._lock:
            if self._down:
                raise RuntimeError("mesh coordinator is shut down")
            if self._poisoned:  # poisoned while we queued on the lock
                raise MeshServingUnavailable(
                    "mesh coordinator is poisoned (earlier broadcast "
                    "never completed); redeploy the mesh")
            self._bcast_watched(self._encode(payload))
            yield

    def shutdown(self):
        """Primary: release every worker loop."""
        if not (self.multi_process and self.is_primary) or self._down:
            self._down = True
            return
        with self._lock:
            if self._down:          # lost the race to another stop()
                return
            self._down = True
            if self._poisoned:      # a release bcast would hang too
                logger.warning("mesh coordinator poisoned: skipping "
                               "worker-release broadcast")
                return
            buf = np.zeros(self.max_bytes, np.uint8)
            buf[:4] = np.frombuffer(
                np.uint32(_SHUTDOWN).tobytes(), np.uint8)
            try:
                self._bcast_watched(buf)
            except Exception as e:  # peers already gone
                logger.warning("mesh coordinator shutdown bcast: %s", e)

    # -- worker side --------------------------------------------------------
    def worker_loop(self, handler: Callable[[object], object]):
        """Non-primary processes: block on the next broadcast, run the
        same pipeline, repeat until the primary shuts down. `handler`
        receives the decoded payload (dict = one query, list = one
        micro-batched window) and must execute the identical device
        program the primary runs."""
        assert not self.is_primary, "worker_loop is for process_index > 0"
        zeros = np.zeros(self.max_bytes, np.uint8)
        while True:
            obj = self._decode(self._bcast(zeros))
            if obj is None:
                logger.info("mesh worker %d: shutdown",
                            __import__("jax").process_index())
                return
            try:
                handler(obj)
            except Exception as e:
                # Two failure classes, different policies. Host-level
                # exceptions (KeyError/ValueError in supplement/predict)
                # are deterministic under this module's contract: the
                # primary raised the SAME error at the SAME point (its
                # HTTP layer answers 500 and keeps serving), both sides
                # skipped the same collectives, the mesh is in sync —
                # continue, mirroring the primary. Device/XLA runtime
                # errors are the worker-only class (per-host OOM, device
                # fault): the worker may have diverged mid-collective,
                # and looping would hide a wedged mesh — crash loudly so
                # a supervisor can redeploy.
                mod = type(e).__module__ or ""
                if ("Xla" in type(e).__name__ or "jaxlib" in mod
                        or mod.startswith("jax")):
                    logger.critical(
                        "mesh worker: device-level failure (%s: %s) — "
                        "possible mid-collective divergence, exiting",
                        type(e).__name__, e)
                    raise
                logger.error(
                    "mesh worker: query handler raised %s: %s "
                    "(continuing — under the determinism contract the "
                    "primary answers 500 for the same query)",
                    type(e).__name__, e)
