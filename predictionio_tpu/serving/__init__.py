"""Engine query serving (L4 deploy side)."""

from predictionio_tpu.serving.server import EngineServer, ServerConfig

__all__ = ["EngineServer", "ServerConfig"]
