"""Engine-server plugin interface.

Rebuilds the reference's ``EngineServerPlugin``
(reference: core/src/main/scala/io/prediction/workflow/EngineServerPlugin.scala:21-40
and EngineServerPluginContext ServiceLoader discovery): plugins either
transform outgoing prediction JSON (outputblocker) or observe it
(outputsniffer). Discovery is by explicit registration or entry-point-style
dotted names in PIO_ENGINE_SERVER_PLUGINS."""

from __future__ import annotations

import abc
import importlib
import logging
import os
from typing import Dict, List

logger = logging.getLogger(__name__)

OUTPUT_BLOCKER = "outputblocker"
OUTPUT_SNIFFER = "outputsniffer"


class EngineServerPlugin(abc.ABC):
    plugin_name: str = "plugin"
    plugin_description: str = ""
    output_type: str = OUTPUT_SNIFFER

    def start(self, context: "EngineServerPluginContext") -> None:
        pass

    @abc.abstractmethod
    def process(self, engine_instance, query: dict, prediction: dict,
                context: "EngineServerPluginContext") -> dict:
        """outputblocker: return (possibly modified) prediction JSON;
        outputsniffer: return value ignored."""

    def handle_rest(self, arguments: List[str]) -> dict:
        return {"message": "The plugin does not support REST."}


class EngineServerPluginContext:
    def __init__(self):
        self.plugins: Dict[str, Dict[str, EngineServerPlugin]] = {
            OUTPUT_BLOCKER: {}, OUTPUT_SNIFFER: {}}

    def register(self, plugin: EngineServerPlugin):
        self.plugins[plugin.output_type][plugin.plugin_name] = plugin

    @staticmethod
    def load_from_env() -> "EngineServerPluginContext":
        """PIO_ENGINE_SERVER_PLUGINS=pkg.mod.Class,pkg2.mod.Other"""
        ctx = EngineServerPluginContext()
        spec = os.environ.get("PIO_ENGINE_SERVER_PLUGINS", "")
        for dotted in filter(None, (s.strip() for s in spec.split(","))):
            try:
                module_name, _, attr = dotted.rpartition(".")
                cls = getattr(importlib.import_module(module_name), attr)
                ctx.register(cls())
            except Exception as e:
                logger.error("Cannot load plugin %s: %s", dotted, e)
        return ctx

    def apply_output(self, engine_instance, query: dict,
                     prediction: dict) -> dict:
        for plugin in self.plugins[OUTPUT_SNIFFER].values():
            try:
                plugin.process(engine_instance, query, prediction, self)
            except Exception as e:
                logger.error("outputsniffer %s failed: %s",
                             plugin.plugin_name, e)
        out = prediction
        for plugin in self.plugins[OUTPUT_BLOCKER].values():
            out = plugin.process(engine_instance, query, out, self)
        return out

    def to_dict(self) -> dict:
        return {
            "plugins": {
                kind: {name: {"name": p.plugin_name,
                              "description": p.plugin_description,
                              "class": type(p).__name__}
                       for name, p in plugins.items()}
                for kind, plugins in self.plugins.items()}}
