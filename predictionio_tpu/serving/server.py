"""The engine server: deployed query HTTP service.

Rebuilds the reference's ``CreateServer``
(reference: core/src/main/scala/io/prediction/workflow/CreateServer.scala:
ServerConfig :80-98, model restore + prepareDeploy :206-265, ServerActor
routes `/`, `/queries.json`, `/reload`, `/stop`, `/plugins.json` :461-708,
query path :490-641, feedback loop :526-596, serving counters :418-420).

TPU notes: models restored from the model store are re-uploaded to device
HBM lazily by each algorithm's first predict; the query path is host ->
jitted device scoring -> host JSON, with business-rule event reads kept off
the device path (the templates handle that). requestCount / avgServingSec /
lastServingSec counters match the reference status page.
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time
import urllib.request

import numpy as np
from dataclasses import dataclass
from typing import List, Optional

from predictionio_tpu.core.engine import Engine, EngineParams
from predictionio_tpu.data.event import format_event_time, utcnow
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.models import get_engine_factory
from predictionio_tpu.obs import (FLIGHT, MetricsRegistry, SLOEngine,
                                  TRACER, default_engine_specs, fleet,
                                  flight_response, get_incidents,
                                  get_registry, health_response,
                                  ingress_trace_kwargs, jaxmon,
                                  slow_response, trace_context_headers,
                                  traces_response)
from predictionio_tpu.obs.slowlog import (capture_slow_query,
                                          slow_threshold_s)
from predictionio_tpu.serving.plugins import EngineServerPluginContext
from predictionio_tpu.utils.http import (HttpServer, Request, Response,
                                         Router)

logger = logging.getLogger(__name__)


@dataclass
class ServerConfig:
    """(CreateServer.scala:80-98)"""
    ip: str = "0.0.0.0"
    port: int = 8000
    engine_instance_id: Optional[str] = None
    engine_id: Optional[str] = None
    engine_version: Optional[str] = None
    engine_variant: str = "engine.json"
    batch: str = ""
    accesskey: str = ""
    event_server_ip: str = "0.0.0.0"
    event_server_port: int = 7070
    feedback: bool = False
    # >1 coalesces concurrent queries into one batched device call
    # (beyond-parity). On by default so a plain `pio deploy` gets the same
    # concurrency mitigation the benchmarks measure. Coalescing is
    # drain-first and self-regulating (serving/batcher.py); the window
    # is held only while more submitted-but-unanswered queries exist
    # than the batch holds, so idle and closed-loop-serial traffic pay
    # nothing and max_wait_ms is just the stall bound on a counted
    # straggler between its submit and its enqueue — not a per-query
    # tax, and not a knob that needs tuning per link anymore.
    micro_batch: int = 16
    micro_batch_wait_ms: float = 5.0
    # optional cap on how long the oldest query may sit in the
    # coalescing stage (ms), for tail-latency-sensitive deployments
    micro_batch_latency_budget_ms: Optional[float] = None
    # pipelined serving executor (ISSUE 14): device batches allowed in
    # flight — batch N's device compute overlaps batch N+1's formation
    # and batch N-1's readback/serialization. None reads
    # PIO_SERVE_INFLIGHT (default 2); 1 restores the synchronous loop.
    # Forced to 1 under a multi-process mesh (collective ordering).
    serve_inflight: Optional[int] = None
    # adaptive batch sizing (ISSUE 14): scale the coalescing hold with
    # the pio_device_occupancy EWMA + queue depth instead of the fixed
    # wait-window, snapping targets to the warmed pow2 AOT buckets
    adaptive_batching: bool = True
    # touched-row-invalidated top-k result cache (ISSUE 14;
    # serving/result_cache.py). PIO_SERVE_CACHE=off also disables.
    result_cache: bool = True
    result_cache_max_entries: int = 8192
    result_cache_max_bytes: int = 64 << 20
    # multi-process mesh serving: per-query broadcast buffer size; raise
    # it when large micro-batched windows of filter-heavy queries exceed
    # the default 64 KiB (every broadcast ships the full buffer, so keep
    # it as small as the workload allows)
    mesh_broadcast_bytes: int = 1 << 16
    # watchdog deadline for the primary's per-query broadcast collective:
    # if a worker process dies, the collective never completes — after
    # this many seconds the coordinator poisons itself and answers 503
    # (serving/mesh_serving.py MeshServingUnavailable) instead of
    # queueing every subsequent query forever
    mesh_broadcast_timeout_s: float = 30.0
    # guarded deploys (ISSUE 5, guard/canary.py): when canary_fraction
    # > 0, swap_models stages the new version as a CANDIDATE serving
    # only that traffic share (responses tagged X-PIO-Canary); a
    # watchdog compares error-rate / NaN-score / latency against the
    # incumbent and either promotes (after a clean canary_window_s) or
    # rolls back to the incumbent automatically. 0 keeps the PR 1
    # immediate-swap behavior.
    canary_fraction: float = 0.0
    canary_window_s: float = 30.0
    canary_min_requests: int = 20
    canary_max_error_ratio: float = 2.0
    canary_max_latency_ratio: float = 3.0
    canary_nan_tolerance: int = 0


class EngineServer:
    def __init__(self, config: ServerConfig,
                 engine: Optional[Engine] = None,
                 engine_params: Optional[EngineParams] = None,
                 plugin_context: Optional[EngineServerPluginContext] = None,
                 mesh_coordinator=None,
                 tenant: Optional[str] = None,
                 shared_result_cache=None):
        self.config = config
        # multi-tenant serving (ISSUE 15): when this server is one slot
        # of a tenancy.ServingHost, `tenant` names it — every device
        # upload the query/warm paths trigger runs under a
        # device_cache.tenant_scope so the HBM budget manager can
        # account and evict this tenant's tables independently, and the
        # (host-shared) result cache is namespaced per tenant.
        self.tenant = str(tenant) if tenant is not None else None
        if self.tenant is not None:
            # bounded metric-label cardinality: only registered
            # tenants get a named ``tenant`` label value (ISSUE 17)
            from predictionio_tpu.obs.tenantctx import register_tenant
            register_tenant(self.tenant)
        self._lock = threading.RLock()
        # multi-process mesh serving: under a >1-process JAX mesh every
        # process must run each query's SPMD program, so the primary
        # broadcasts payloads and workers mirror the pipeline
        # (serving/mesh_serving.py; CreateServer.scala:490-641 role)
        if mesh_coordinator is None:
            from predictionio_tpu.serving.mesh_serving import \
                MeshQueryCoordinator
            mesh_coordinator = MeshQueryCoordinator.create_if_distributed(
                max_bytes=config.mesh_broadcast_bytes,
                broadcast_timeout_s=config.mesh_broadcast_timeout_s)
        self.coordinator = mesh_coordinator
        self.engine = engine
        self.engine_params = engine_params
        self.engine_instance = None
        self.algorithms = []
        self.models = []
        self.serving = None
        self.plugin_context = (plugin_context or
                               EngineServerPluginContext.load_from_env())
        # serving counters (CreateServer.scala:418-420), plus a predict-time
        # split so operators can tell device/score time from HTTP+serve
        # overhead (beyond-parity observability)
        self.request_count = 0
        self.serving_seconds = 0.0
        self.last_serving_sec = 0.0
        self.predict_seconds = 0.0
        # per-request serving-time ring for tail percentiles (p50/p95/p99
        # in /stats.json); 4096 samples bounds memory and keeps the
        # percentiles a rolling view of recent traffic
        self._lat_ring = collections.deque(maxlen=4096)
        # online-update counters (ISSUE 1 hot-swap observability): every
        # model replacement after the initial load counts as a swap —
        # /reload instance swaps and in-process fold-in swaps alike
        self.swap_count = 0
        self.fold_in_count = 0
        self.fold_in_events = 0
        self.model_version: Optional[str] = None
        # graceful degradation (ISSUE 3): when a fold-in publish/hot-swap
        # fails the server keeps answering from the stale-but-valid
        # model and advertises its age via the X-PIO-Model-Staleness-Ms
        # response header until a swap lands again
        self.publish_degraded = False
        self.publish_failures = 0
        self._last_swap_wall = time.time()
        self.start_time = utcnow()
        self.server: Optional[HttpServer] = None
        # ISSUE 2: this server's metrics registry, chained onto the
        # process-wide one (JAX telemetry, fold/train instruments ride
        # along on /metrics). Per-server counters keep the server as
        # their single source of truth and are sampled via func
        # collectors at scrape time; latency distributions are native
        # registry histograms.
        jaxmon.install()
        self.metrics = MetricsRegistry(parent=get_registry())
        self._h_query = self.metrics.histogram(
            "pio_engine_query_seconds",
            "Per-query serving latency (batched queries observe the "
            "window's wall time each)")
        # diagnostics plane (ISSUE 6): per-executable compile/HBM
        # attribution, flight-recorder metric context from this
        # server's families, burn-rate SLOs at GET /health.json, and
        # an incident-bundle provider exposing serving + lineage state
        from predictionio_tpu.obs import costmon
        costmon.install()
        FLIGHT.add_source(self.metrics)
        # a tenant slot evaluates per-tenant spec thresholds
        # (PIO_SLO_*__<TENANT> overrides) and reads only its own
        # tenant's children out of tenant-labeled process families
        self.slo = SLOEngine(default_engine_specs(self.tenant),
                             registries=[self.metrics],
                             tenant=self.tenant)
        # last-seen status per SLO name: the ok->breached transition
        # detector behind the ISSUE 11 auto-capture in _health
        self._slo_status: dict = {}
        get_incidents().register_provider(
            "engine_server" if self.tenant is None
            else f"engine_server.{self.tenant}", self._incident_state)
        # guarded deploys (ISSUE 5): canary controller + rollback
        # anchors. last_good_version tracks the newest version this
        # server trusts (the loaded instance, then every promotion);
        # on_canary_decision lets the attached scheduler pin the
        # registry and escalate on rollback.
        from predictionio_tpu.guard.canary import (CanaryConfig,
                                                   CanaryController)
        self.canary = CanaryController(CanaryConfig(
            fraction=config.canary_fraction,
            window_s=config.canary_window_s,
            min_requests=config.canary_min_requests,
            max_error_ratio=config.canary_max_error_ratio,
            max_latency_ratio=config.canary_max_latency_ratio,
            nan_tolerance=config.canary_nan_tolerance),
            registry=self.metrics)
        self.last_good_version: Optional[str] = None
        self.on_canary_decision = None
        # compile plane (ISSUE 9): swap-to-first-query measurement.
        # _swap_marker = (version, t0, candidate_only) armed by every
        # model change (load/swap/canary stage/promote); the first query
        # completion that matches closes it into
        # last_swap_to_first_query_ms + a flight record — the end-to-end
        # number the AOT warm path exists to shrink.
        self._swap_marker = None
        self.last_swap_to_first_query_ms: Optional[float] = None
        self.last_aot_warm: Optional[dict] = None
        # fleet member record id (ISSUE 13), set by start()'s on_bound
        # hook under _lock (stop() may run on a /stop route thread)
        self._fleet_id: Optional[str] = None
        self._register_metrics()
        # pipelined executor + result cache (ISSUE 14): single-process
        # servers only. Under a multi-process mesh every query is a
        # collective whose enqueue/readback ordering must stay strictly
        # serialized across processes — and a cache hit on the primary
        # alone would (a) skip the collective the workers are waiting
        # to mirror and (b) keep answering 200 for hot queries after a
        # worker death, masking the coordinator's loud-503 poisoned
        # contract (ISSUE 3).
        single_process = (self.coordinator is None
                          or not self.coordinator.multi_process)
        from predictionio_tpu.serving import result_cache as RC
        self.result_cache = None
        if config.result_cache and single_process \
                and RC.cache_enabled():
            if shared_result_cache is not None and self.tenant is not None:
                # one host-wide budget, tenant-namespaced keys: two
                # tenants' byte-identical queries can never alias
                self.result_cache = RC.TenantResultCache(
                    shared_result_cache, self.tenant)
            else:
                self.result_cache = RC.ResultCache(
                    max_entries=config.result_cache_max_entries,
                    max_bytes=config.result_cache_max_bytes,
                    metrics=self.metrics)
        self.batcher = None
        if config.micro_batch > 1:
            from predictionio_tpu.serving.batcher import MicroBatcher
            self.batcher = MicroBatcher(
                self.handle_query_batch, max_batch=config.micro_batch,
                max_wait_ms=config.micro_batch_wait_ms,
                latency_budget_ms=config.micro_batch_latency_budget_ms,
                metrics=self.metrics, tenant=self.tenant,
                process_batch_begin=(self.handle_query_batch_begin
                                     if single_process else None),
                inflight=(config.serve_inflight
                          if single_process else 1),
                adaptive=config.adaptive_batching)
        self.router = self._build_router()

    def _register_metrics(self):
        """Mount every serving counter on the registry. The func
        collectors sample the live attributes under no extra locks —
        scrape-time reads of GIL-atomic ints/floats."""
        m = self.metrics
        m.counter_func("pio_engine_requests_total", "Queries served",
                       lambda: self.request_count)
        m.counter_func("pio_engine_serving_seconds_total",
                       "Cumulative serve wall time",
                       lambda: self.serving_seconds)
        m.counter_func("pio_engine_predict_seconds_total",
                       "Cumulative device/predict time",
                       lambda: self.predict_seconds)
        m.counter_func("pio_engine_model_swaps_total",
                       "Hot model swaps since start (reloads + fold-ins)",
                       lambda: self.swap_count)
        m.counter_func("pio_engine_fold_ins_total",
                       "Online fold-in swaps since start",
                       lambda: self.fold_in_count)
        m.counter_func("pio_engine_fold_in_events_total",
                       "Events absorbed by online fold-ins",
                       lambda: self.fold_in_events)
        m.summary_func("pio_engine_serving_seconds",
                       "Recent serving-time quantiles (rolling ring)",
                       self._quantile_samples)
        m.gauge_func("pio_engine_model_stale",
                     "1 while serving a stale model because a fold-in "
                     "publish/hot-swap failed",
                     lambda: int(self.publish_degraded))
        m.gauge_func("pio_engine_model_staleness_seconds",
                     "Age of the serving model (since last load/swap)",
                     lambda: self.model_staleness_s())
        m.counter_func("pio_engine_publish_failures_total",
                       "Fold-in publish/hot-swap failures reported by "
                       "the scheduler",
                       lambda: self.publish_failures)
        m.gauge_func("pio_guard_canary_state",
                     "1 while a canary candidate version serves a "
                     "fraction of this server's traffic",
                     lambda: int(self.canary.active))
        m.gauge_func("pio_engine_swap_to_first_query_ms",
                     "Wall ms from the latest model change (load, "
                     "hot-swap, canary stage/promote) to its first "
                     "served query — compile-free when the AOT warm "
                     "path did its job",
                     lambda: self.last_swap_to_first_query_ms or 0.0)
        if self.coordinator is not None:
            m.gauge_func("pio_engine_mesh_processes",
                         "Processes in the serving mesh",
                         lambda: self.coordinator.health()["processes"])
            m.gauge_func("pio_engine_mesh_poisoned",
                         "1 when a mesh broadcast failed and every query "
                         "answers 503 until redeploy",
                         lambda: int(
                             self.coordinator.health()["poisoned"]))

    def _incident_state(self) -> dict:
        """Serving + model-lineage state frozen into incident bundles
        (obs/incidents.py). Lock-free attribute reads — an incident
        capture must never contend with the query path."""
        inst = self.engine_instance
        return {
            "modelVersion": self.model_version,
            "lastGoodVersion": self.last_good_version,
            "engineInstance": getattr(inst, "id", None),
            "lineage": getattr(inst, "batch", None),
            "requestCount": self.request_count,
            "modelSwaps": self.swap_count,
            "foldIns": self.fold_in_count,
            "publishDegraded": self.publish_degraded,
            "publishFailures": self.publish_failures,
            "modelStalenessSec": self.model_staleness_s(),
            "canary": self.canary.stats(),
        }

    def _model_sharding(self) -> list:
        """Per-algorithm factor-table layout for /stats.json (ISSUE
        12): operators reading the over-budget runbook confirm from
        here that a deployment actually serves sharded tables — and
        what one shard costs a device."""
        from predictionio_tpu.parallel.sharded_table import is_sharded
        out = []
        for m in list(self.models):
            als = getattr(m, "als", None) or m
            t = getattr(als, "item_factors", None)
            if is_sharded(t):
                out.append({"layout": "model", "shards": t.n_shards,
                            "rows": t.n_rows,
                            "perShardBytes": t.per_shard_nbytes,
                            "resident": t._dev is not None})
            else:
                out.append({"layout": "replicated"})
        return out

    def _quantile_samples(self):
        with self._lock:
            pct = self._ring_percentiles()
        if pct is None:
            return None
        return [({"quantile": q}, float(v))
                for q, v in zip(("0.5", "0.95", "0.99"), pct)]

    # -- model loading (createServerActorWithEngine, :206-265) -------------
    def load_engine_instance(self):
        instances = Storage.get_meta_data_engine_instances()
        cfg = self.config
        if cfg.engine_instance_id:
            instance = instances.get(cfg.engine_instance_id)
            if instance is None:
                raise ValueError(
                    f"Invalid engine instance id {cfg.engine_instance_id}")
        else:
            instance = instances.get_latest_completed(
                cfg.engine_id or "default", cfg.engine_version or "0",
                cfg.engine_variant)
            if instance is None:
                raise ValueError(
                    f"No valid engine instance found for engine "
                    f"{cfg.engine_id} {cfg.engine_version} "
                    f"{cfg.engine_variant}. Try running `pio train` first.")
        return instance

    def load(self):
        """Restore models and build the serving pipeline (the deploy path)."""
        with self._lock:
            instance = self.load_engine_instance()
            if self.engine is None:
                factory = get_engine_factory(instance.engine_factory)
                self.engine = factory.apply()
            if self.engine_params is None:
                variant = {
                    "datasource": json.loads(
                        instance.data_source_params or "{}"),
                    "preparator": json.loads(
                        instance.preparator_params or "{}"),
                    "algorithms": json.loads(
                        instance.algorithms_params or "[]"),
                    "serving": json.loads(instance.serving_params or "{}"),
                }
                self.engine_params = self.engine.json_to_engine_params(
                    variant)
            model = Storage.get_model_data_models().get(instance.id)
            if model is None:
                raise ValueError(
                    f"No model found for engine instance {instance.id}")
            persisted = self.engine.deserialize_models(model.models)
            result = self.engine.prepare_deploy(
                self.engine_params, persisted, instance.id)
            was_loaded = bool(self.algorithms)
            self.engine_instance = instance
            self.algorithms = result.algorithms
            self.models = result.models
            self.serving = self.engine.make_serving(self.engine_params)
            self.model_version = instance.id
            # an operator-initiated (re)load is a trusted deploy: it is
            # the rollback anchor, and it supersedes any undecided
            # canary (whose candidate referenced the old pipeline)
            self.last_good_version = instance.id
            self.canary.abandon("full (re)load of instance "
                                + instance.id)
            self._last_swap_wall = time.time()
            self.publish_degraded = False
            if was_loaded:
                self.swap_count += 1  # /reload hot-swap, not first load
            logger.info("Engine instance %s loaded (%d algorithm(s))",
                        instance.id, len(self.algorithms))
        # a full (re)load rebuilds vocabularies/models wholesale — no
        # touched-row lineage, so every cached ranking is suspect
        if self.result_cache is not None:
            self.result_cache.invalidate_all("reload")
        # compile plane (ISSUE 9): AOT-compile the serving executables
        # at deploy time — outside the serving lock (an in-flight query
        # during /reload keeps answering from the jit path meanwhile)
        self._warm_aot(self.models, instance.id)
        self._arm_swap_marker(instance.id, models_token=self.models)
        FLIGHT.record("hot_swap" if was_loaded else "model_load",
                      model_version=instance.id, source="load")
        return self

    def _tenant_cm(self):
        """Attribution scope for device uploads on this server's paths
        (ISSUE 15): a nullcontext for single-tenant deployments."""
        if self.tenant is None:
            import contextlib
            return contextlib.nullcontext()
        from predictionio_tpu.utils import device_cache
        return device_cache.tenant_scope(self.tenant)

    # -- compile plane (ISSUE 9) --------------------------------------------
    def _warm_aot(self, models, version: Optional[str]):
        """AOT-compile the serving executables for ``models`` BEFORE
        they take a request (the caller — scheduler publish thread,
        canary stage, deploy load — pays the compile, never a query).
        Fail-soft: a warm failure leaves the jit fallback path serving
        correctly."""
        try:
            from predictionio_tpu.compile.aot import warm_models
            with self._tenant_cm():
                summary = warm_models(
                    self.algorithms, models,
                    batch_hint=max(self.config.micro_batch, 1))
            self.last_aot_warm = dict(summary, version=version)
            if summary.get("compiled"):
                FLIGHT.record("aot_warm", model_version=version,
                              **{k: summary[k] for k in
                                 ("compiled", "skipped", "wallS")
                                 if k in summary})
        except Exception:
            logger.warning("AOT warm failed; serving falls back to "
                           "jit dispatch", exc_info=True)

    def _arm_swap_marker(self, version: Optional[str],
                         candidate_only: bool = False,
                         models_token=None):
        """``models_token`` is the exact model-list object installed by
        the change: only a query that SERVED it may close the marker (a
        query already in flight against the old models at swap time
        would otherwise bank a fake ~0 ms first-query wall). Canary
        stages pass no token — the CANDIDATE arm check is the gate."""
        with self._lock:
            self._swap_marker = (version, time.perf_counter(),
                                 candidate_only, models_token)

    def _close_swap_marker(self, arm: str, models_used=None):
        """First matching query after a model change: bank the
        swap-to-first-query wall. Candidate-only markers (canary stage)
        wait for the first CANDIDATE-served query — the one that would
        pay any un-warmed compile."""
        marker = self._swap_marker
        if marker is None:
            return
        version, t0, candidate_only, token = marker
        from predictionio_tpu.guard.canary import CANDIDATE
        if candidate_only and arm != CANDIDATE:
            return
        if token is not None and models_used is not token:
            return  # an in-flight query against the pre-swap models
        with self._lock:
            if self._swap_marker is not marker:
                return
            self._swap_marker = None
            ms = (time.perf_counter() - t0) * 1000.0
            self.last_swap_to_first_query_ms = ms
        FLIGHT.record("first_query_after_swap", model_version=version,
                      swapToFirstQueryMs=round(ms, 3),
                      canary=candidate_only)

    def swap_models(self, models, version: Optional[str] = None,
                    fold_in_events: int = 0,
                    touched_entities: Optional[dict] = None):
        """Atomic in-process hot-swap (the fold-in publish path): replace
        the whole model list under the serving lock so no query ever sees
        a mixed-version set. The query paths snapshot (algorithms, models,
        serving) under the same lock, and fold-in produces NEW model
        objects rather than mutating deployed ones — both halves of the
        no-torn-read guarantee.

        ``touched_entities`` ({"user": ids, "item": ids}, ISSUE 14): the
        exact rows this publish re-solved — the result cache drops ONLY
        their entries, so untouched hot users keep their cached rankings
        across the swap. None (an unattributed model change) clears the
        whole cache.

        Compile plane (ISSUE 9): the incoming models' serving
        executables are AOT-warmed HERE, on the publishing thread,
        before the swap/stage — so the first query against the new
        version (including a guarded rollback's return to the
        incumbent, whose executables are already resident) runs zero
        XLA compiles."""
        models = list(models)
        if len(models) != len(self.algorithms):
            raise ValueError(
                f"swap_models got {len(models)} models for "
                f"{len(self.algorithms)} algorithms")
        self._warm_aot(models, version)
        # guarded deploys (ISSUE 5): with canarying on, the new version
        # becomes a CANDIDATE serving canary_fraction of traffic; the
        # watchdog promotes or rolls back — the incumbent keeps
        # answering the rest and stays fully live either way. Not under
        # a multi-process mesh: per-request model choice on the primary
        # only would run mismatched SPMD programs across processes
        # (the same reason /reload is rejected there).
        single_process = (self.coordinator is None
                          or not self.coordinator.multi_process)
        if single_process and self.canary.stage(models, version,
                                                int(fold_in_events)):
            # the candidate is warm BEFORE its first routed request:
            # measure stage -> first candidate-served query
            self._arm_swap_marker(version, candidate_only=True)
            FLIGHT.record("canary_staged", model_version=version,
                          fraction=self.canary.config.fraction,
                          foldInEvents=int(fold_in_events))
            return
        with self._lock:
            self.models = models
            self.swap_count += 1
            self.fold_in_count += 1
            self.fold_in_events += int(fold_in_events)
            if version is not None:
                self.model_version = version
            # a landed swap ends any stale-model degradation window
            self._last_swap_wall = time.time()
            self.publish_degraded = False
        if self.result_cache is not None:
            from predictionio_tpu.serving.result_cache import entity_tags
            if touched_entities is not None:
                # fold-tick lineage: drop exactly the touched entities'
                # entries; untouched cached rankings survive the swap
                self.result_cache.invalidate_entities(
                    entity_tags(touched_entities), reason="fold_swap")
            else:
                self.result_cache.invalidate_all("swap")
        self._arm_swap_marker(version, models_token=models)
        FLIGHT.record("hot_swap", model_version=version,
                      source="fold_publish",
                      foldInEvents=int(fold_in_events))
        logger.info("Hot-swapped models (swap #%d, version %s)",
                    self.swap_count, version or "<in-process>")

    # -- graceful degradation (ISSUE 3) -------------------------------------
    def note_publish_failure(self):
        """The scheduler reports a failed fold-in publish/hot-swap: keep
        serving the stale-but-valid model, but say so — queries gain the
        X-PIO-Model-Staleness-Ms header and /metrics flips
        pio_engine_model_stale until a swap lands."""
        with self._lock:
            self.publish_degraded = True
            self.publish_failures += 1

    def model_staleness_s(self) -> float:
        return max(time.time() - self._last_swap_wall, 0.0)

    # -- canary plumbing (ISSUE 5) ------------------------------------------
    def _canary_route(self):
        """(models_override, version, arm) for this request; the plain
        (None, None, incumbent) when canarying is off or idle — the
        default query path pays one config read."""
        from predictionio_tpu.guard.canary import CANDIDATE, INCUMBENT
        if not self.canary.enabled:
            return None, None, INCUMBENT
        routed = self.canary.route()
        if routed is None:
            return None, None, INCUMBENT
        models, version = routed
        return models, version, CANDIDATE

    def _canary_observe(self, arm, pred_dicts=None, error: bool = False,
                        latency_s: Optional[float] = None, n: int = 1):
        """Record per-arm outcomes and run the watchdog decision."""
        if not self.canary.enabled:
            return
        from predictionio_tpu.guard.canary import count_nonfinite
        nonfinite = 0
        if pred_dicts:
            nonfinite = sum(count_nonfinite(d) for d in pred_dicts)
        self.canary.record(arm, error=error, nonfinite=nonfinite,
                           latency_s=latency_s, n=n)
        self._apply_canary_decision()

    def _apply_canary_decision(self):
        decision = self.canary.take_decision()
        if decision is None:
            return
        if decision["decision"] == "promote":
            with self._lock:
                self.models = decision["models"]
                self.swap_count += 1
                self.fold_in_count += 1
                self.fold_in_events += decision["foldInEvents"]
                if decision["candidateVersion"]:
                    self.model_version = decision["candidateVersion"]
                self.last_good_version = self.model_version
                self._last_swap_wall = time.time()
                self.publish_degraded = False
            if self.result_cache is not None:
                # the staged candidate's touched-row lineage is gone by
                # promote time; a full clear is the safe contract (a
                # ROLLBACK keeps the incumbent — entries stay valid)
                self.result_cache.invalidate_all("canary_promote")
            # the promoted candidate's executables are already resident
            # (warmed at stage): promote -> first query is compile-free
            self._arm_swap_marker(decision["candidateVersion"],
                                  models_token=decision["models"])
            FLIGHT.record("hot_swap",
                          model_version=decision["candidateVersion"],
                          source="canary_promote")
            logger.info("Hot-swapped models after clean canary "
                        "(swap #%d, version %s)", self.swap_count,
                        decision["candidateVersion"] or "<in-process>")
        hook = self.on_canary_decision
        if hook is not None:
            try:
                hook({k: v for k, v in decision.items()
                      if k != "models"})
            except Exception:
                logger.exception("on_canary_decision hook failed")
        elif decision["candidateVersion"] \
                and getattr(self.engine_instance, "engine_id", None):
            # standalone deploy (no attached scheduler to delegate to):
            # make the verdict durable directly — pin a promotion as
            # last-known-good, demote a rolled-back version so the next
            # /reload or restart cannot resolve it
            try:
                from predictionio_tpu.online.registry import \
                    ModelVersionRegistry
                inst = self.engine_instance
                if decision["decision"] == "promote":
                    ModelVersionRegistry().pin_last_good(
                        inst.engine_id, inst.engine_version,
                        inst.engine_variant,
                        decision["candidateVersion"])
                else:
                    ModelVersionRegistry().demote_version(
                        decision["candidateVersion"])
            except Exception:
                logger.exception("durable canary verdict failed")

    # -- query path (ServerActor.myRoute /queries.json, :490-641) ----------
    def handle_query(self, query_dict: dict) -> dict:
        t0 = time.perf_counter()
        with self._lock:
            algorithms = self.algorithms
            models = self.models
            serving = self.serving
        canary_models, canary_version, arm = self._canary_route()
        if canary_models is not None:
            models = canary_models
        if not algorithms:
            raise RuntimeError("no engine loaded")
        # decode via the first algorithm's query class (JsonExtractor :499)
        qc = algorithms[0].query_class
        query = qc.from_dict(query_dict) if qc is not None else query_dict
        try:
            with self._tenant_cm(), self._spmd_guard(query_dict):
                with TRACER.span("supplement"):
                    supplemented = serving.supplement(query)
                tp = time.perf_counter()
                with TRACER.span("predict", algorithms=len(algorithms)):
                    predictions = [algo.predict(model, supplemented)
                                   for algo, model in zip(algorithms,
                                                          models)]
                predict_dt = time.perf_counter() - tp
            with TRACER.span("post_process"):
                prediction = serving.serve(query, predictions)
                pred_dict = (prediction.to_dict()
                             if hasattr(prediction, "to_dict")
                             else prediction)
                if not isinstance(pred_dict, dict):
                    pred_dict = {"result": pred_dict}
        except Exception:
            self._canary_observe(arm, error=True,
                                 latency_s=time.perf_counter() - t0)
            raise
        if self.config.feedback:
            pr_id = query_dict.get("prId") or self.engine_instance.id
            pred_dict = dict(pred_dict, prId=pr_id)
            self._send_feedback(query_dict, pred_dict, pr_id)
        pred_dict = self.plugin_context.apply_output(
            self.engine_instance, query_dict, pred_dict)
        dt = time.perf_counter() - t0
        with self._lock:
            self.request_count += 1
            self.serving_seconds += dt
            self.last_serving_sec = dt
            self.predict_seconds += predict_dt
            self._lat_ring.append(dt)
        self._h_query.observe(dt)
        self._close_swap_marker(arm, models_used=models)
        self._canary_observe(arm, pred_dicts=(pred_dict,), latency_s=dt)
        if canary_models is not None:
            # response tagging: the HTTP layer turns this into the
            # X-PIO-Canary header so clients/tests can tell which arm
            # answered
            pred_dict = dict(pred_dict,
                             _pioCanary=canary_version or "candidate")
        return pred_dict

    def _spmd_guard(self, payload):
        """Broadcast `payload` to mesh workers and hold the SPMD slot for
        this query's device work; a no-op for single-process serving and
        on the worker side (whose ordering is its sequential loop)."""
        if self.coordinator is None:
            import contextlib
            return contextlib.nullcontext()
        return self.coordinator.serialized(payload)

    def serve_mesh_worker(self):
        """Run this process as a mesh serve worker: mirror the primary's
        predict pipeline for every broadcast query — the executor side of
        the reference's distributed-model serve (CreateServer.scala:
        490-641; PAlgorithm.predictBase on cluster-resident models)."""
        if self.coordinator is None or self.coordinator.is_primary:
            raise RuntimeError(
                "serve_mesh_worker requires a multi-process mesh and "
                "process_index > 0")
        # workers mirror only the device work: per-query side effects
        # (feedback events, output plugins) belong to the primary alone,
        # else every query's feedback would be posted N times
        if self.config.feedback:
            import dataclasses
            self.config = dataclasses.replace(self.config, feedback=False)
        self.plugin_context = EngineServerPluginContext()

        def handler(obj):
            if isinstance(obj, list):
                self.handle_query_batch(obj)
            else:
                self.handle_query(obj)

        logger.info("mesh serve worker ready (process %d)",
                    __import__("jax").process_index())
        self.coordinator.worker_loop(handler)

    def handle_query_batch(self, query_dicts: List[dict]) -> List[dict]:
        """Batched query path: one Algorithm.batch_predict device call for
        all queries in the window (serving/batcher.py). Canary routing is
        per WINDOW — a coalesced batch runs against ONE model set, so the
        traffic fraction is realized across windows."""
        return self.handle_query_batch_begin(query_dicts)()

    def handle_query_batch_begin(self, query_dicts: List[dict]):
        """Pipelined batch path, stage 1 (ISSUE 14): snapshot the model
        set, decode + supplement, and ENQUEUE the device call (JAX async
        dispatch — the call returns the moment the work is queued on the
        device stream). Returns ``finish() -> List[dict]`` — stage 2:
        the deferred device->host readback, post-process and per-query
        result dicts, safe to run on the batcher's completion thread
        while the next window forms and dispatches.

        Version-mixing safety with K windows in flight: everything a
        window touches — algorithms, models, serving — is snapshotted
        here, once, under the serving lock; ``finish`` closes over the
        snapshot, so a hot-swap/rollback landing mid-flight never mixes
        versions inside a window (fold-in publishes new model OBJECTS,
        the deployed ones are immutable)."""
        import sys
        t0 = time.perf_counter()
        with self._lock:
            algorithms = self.algorithms
            models = self.models
            serving = self.serving
        canary_models, canary_version, arm = self._canary_route()
        if canary_models is not None:
            models = canary_models
        if not algorithms:
            raise RuntimeError("no engine loaded")
        qc = algorithms[0].query_class
        queries = [qc.from_dict(d) if qc is not None else d
                   for d in query_dicts]
        # the SPMD guard is entered here and exited after the readback:
        # with pipelining off (mesh / direct calls) finish() runs
        # immediately, preserving the old guard extent; the pipelined
        # single-process path gets a nullcontext anyway
        guard_holder = [self._spmd_guard(query_dicts)]
        guard_holder[0].__enter__()

        def _exit_guard(exc_info=(None, None, None)):
            g = guard_holder and guard_holder.pop()
            if g:
                g.__exit__(*exc_info)

        try:
            with self._tenant_cm():
                with TRACER.span("supplement"):
                    indexed = [(i, serving.supplement(q))
                               for i, q in enumerate(queries)]
                tp = time.perf_counter()
                with TRACER.span("predict", batch=len(queries),
                                 algorithms=len(algorithms)):
                    fetchers = []
                    for algo, model in zip(algorithms, models):
                        begin = getattr(algo, "batch_predict_begin",
                                        None)
                        if begin is not None:
                            fetchers.append(begin(model, indexed))
                        else:
                            # no async split for this algorithm: run
                            # the full (sync) batch predict in this
                            # stage — correct, just without overlap
                            res = algo.batch_predict(model, indexed)
                            fetchers.append(lambda res=res: res)
                dispatch_dt = time.perf_counter() - tp
        except BaseException as e:
            _exit_guard(sys.exc_info())
            if isinstance(e, Exception):
                self._canary_observe(arm, error=True,
                                     latency_s=time.perf_counter() - t0,
                                     n=len(queries))
            raise

        def finish() -> List[dict]:
            try:
                from predictionio_tpu.ops import readback as _rb
                tr = time.perf_counter()
                rb_w0, rb_b0 = _rb.thread_wait_s(), _rb.thread_d2h_bytes()
                with TRACER.span("readback") as rb_span:
                    # the window's d2h copy went in flight at dispatch
                    # (ops/readback, ISSUE 19) — this is the wait on
                    # that copy + host unpack, the pipeline's ONE
                    # inherent sync (results must reach the host to
                    # serialize); costmon's 1-in-N sampled sync inside
                    # the dispatch stays the only other deliberate one
                    per_algo = [dict(f()) for f in fetchers]
                    if rb_span is not None:
                        rb_span.attrs["d2hWaitMs"] = round(
                            (_rb.thread_wait_s() - rb_w0) * 1000.0, 3)
                        rb_span.attrs["d2hBytes"] = (
                            _rb.thread_d2h_bytes() - rb_b0)
                readback_dt = time.perf_counter() - tr
            except BaseException as e:
                _exit_guard(sys.exc_info())
                if isinstance(e, Exception):
                    self._canary_observe(
                        arm, error=True,
                        latency_s=time.perf_counter() - t0,
                        n=len(queries))
                raise
            _exit_guard()
            try:
                out = []
                with TRACER.span("post_process"):
                    for i, (q, d) in enumerate(zip(queries,
                                                   query_dicts)):
                        prediction = serving.serve(
                            q, [pa[i] for pa in per_algo])
                        pred_dict = (prediction.to_dict()
                                     if hasattr(prediction, "to_dict")
                                     else prediction)
                        if not isinstance(pred_dict, dict):
                            pred_dict = {"result": pred_dict}
                        if self.config.feedback:
                            pr_id = (d.get("prId")
                                     or self.engine_instance.id)
                            pred_dict = dict(pred_dict, prId=pr_id)
                            self._send_feedback(d, pred_dict, pr_id)
                        out.append(self.plugin_context.apply_output(
                            self.engine_instance, d, pred_dict))
            except Exception:
                self._canary_observe(arm, error=True,
                                     latency_s=time.perf_counter() - t0,
                                     n=len(queries))
                raise
            dt = time.perf_counter() - t0
            with self._lock:
                self.request_count += len(queries)
                self.serving_seconds += dt
                self.last_serving_sec = dt / max(len(queries), 1)
                self.predict_seconds += dispatch_dt + readback_dt
                # every query in the window experienced the window's
                # wall time inside the server: one ring sample each
                self._lat_ring.extend([dt] * len(queries))
            for _ in queries:
                self._h_query.observe(dt)
            self._close_swap_marker(arm, models_used=models)
            self._canary_observe(arm, pred_dicts=out, latency_s=dt,
                                 n=len(queries))
            if canary_models is not None:
                return [dict(d, _pioCanary=canary_version or "candidate")
                        for d in out]
            return out
        return finish

    # -- feedback loop (:526-596) ------------------------------------------
    def _send_feedback(self, query: dict, prediction: dict, pr_id: str):
        event = {
            "event": "predict", "entityType": "pio_pr", "entityId": pr_id,
            "properties": {"query": query, "prediction": prediction},
            "eventTime": format_event_time(utcnow()),
        }
        url = (f"http://{self.config.event_server_ip}:"
               f"{self.config.event_server_port}/events.json"
               f"?accessKey={self.config.accesskey}")
        # capture the query's trace context NOW (ISSUE 13): the POST
        # runs on a fresh thread whose contextvars are empty, and the
        # event server adopting this id is what ties the feedback
        # event's ingest to the query that produced it across processes
        headers = {"Content-Type": "application/json",
                   **trace_context_headers()}

        def _post():
            try:
                req = urllib.request.Request(
                    url, data=json.dumps(event).encode(),
                    headers=headers, method="POST")
                urllib.request.urlopen(req, timeout=5).read()
            except Exception as e:
                logger.error("feedback event POST failed: %s", e)

        threading.Thread(target=_post, daemon=True).start()

    # -- routes -------------------------------------------------------------
    def _ring_percentiles(self):
        """(p50, p95, p99) of recent serving seconds, or None when no
        traffic yet. Callers must hold self._lock."""
        if not self._lat_ring:
            return None
        return np.percentile(list(self._lat_ring), (50, 95, 99))

    def _status_page(self, req: Request) -> Response:
        with self._lock:
            avg = (self.serving_seconds / self.request_count
                   if self.request_count else 0.0)
            inst = self.engine_instance
            pct = self._ring_percentiles()
            tail = ""
            if pct is not None:
                p50, p95, p99 = pct
                tail = (f"<tr><td>p50 / p95 / p99 serving time</td>"
                        f"<td>{p50:.6f} / {p95:.6f} / {p99:.6f} s"
                        f"</td></tr>")
            if self.coordinator is not None:
                h = self.coordinator.health()
                state = ("POISONED — redeploy the mesh" if h["poisoned"]
                         else "healthy")
                tail += (f"<tr><td>Mesh coordinator "
                         f"({h['processes']} processes)</td>"
                         f"<td>{state}</td></tr>")
        html = f"""<html><head><title>Engine Server at
{self.config.ip}:{self.config.port}</title></head><body>
<h1>Engine Server</h1>
<table border=1>
<tr><td>Started</td><td>{self.start_time.isoformat()}</td></tr>
<tr><td>Engine instance</td><td>{inst.id if inst else '-'}</td></tr>
<tr><td>Engine factory</td><td>{inst.engine_factory if inst else '-'}</td></tr>
<tr><td>Request count</td><td>{self.request_count}</td></tr>
<tr><td>Average serving time</td><td>{avg:.6f} s</td></tr>
<tr><td>Last serving time</td><td>{self.last_serving_sec:.6f} s</td></tr>
{tail}</table></body></html>"""
        return Response(200, html, content_type="text/html; charset=UTF-8")

    @staticmethod
    def _request_deadline_s(req: Request) -> Optional[float]:
        """Deadline budget propagated from HTTP ingress (ISSUE 3):
        ``X-PIO-Deadline-Ms`` header or ``deadlineMs`` query param —
        how long the CLIENT will still care about the answer. Fed to
        the batcher's admission control so saturated queues shed
        out-of-deadline work with 503 + Retry-After."""
        raw = (req.headers.get("X-PIO-Deadline-Ms")
               or req.params.get("deadlineMs"))
        if not raw:
            return None
        try:
            ms = float(raw)
        except ValueError:
            raise ValueError(f"bad deadline {raw!r}: want milliseconds")
        if ms <= 0:
            raise ValueError("deadline must be positive milliseconds")
        return ms / 1000.0

    def _degraded_headers(self) -> Optional[dict]:
        """The stale-model advisory header while a fold-in publish
        failure leaves this server behind the event stream."""
        if not self.publish_degraded:
            return None
        return {"X-PIO-Model-Staleness-Ms":
                str(int(self.model_staleness_s() * 1000))}

    def _cache_usable(self) -> bool:
        """The result cache serves/stores only when a response is a
        pure function of (query, deployed models): no canary split in
        progress (two model sets answer concurrently), no feedback
        loop (each query must land its predict event), no output
        plugins (sniffers must see every prediction)."""
        if self.result_cache is None:
            return False
        if self.canary.active:
            return False
        if self.config.feedback:
            return False
        p = self.plugin_context.plugins
        return not any(p.get(k) for k in p)

    @staticmethod
    def _result_item_ids(out) -> tuple:
        """Item ids a response ranks (strict-mode invalidation join) —
        ALL of them: a cap would silently exempt deep rankings from
        the PIO_SERVE_CACHE_STRICT drop-if-contains-touched-item
        contract (num is client-bounded, so this stays small)."""
        try:
            return tuple(str(s["item"])
                         for s in out.get("itemScores", ()))
        except Exception:
            return ()

    def _serve_cache_hit(self, body: bytes, t_q0: float) -> Response:
        """Account + answer one result-cache hit (no trace is minted:
        an empty span tree is not worth a double-digit-percent tax on
        the measured hit path; hits stay fully counted in the request
        metrics and latency histogram)."""
        dt = time.perf_counter() - t_q0
        with self._lock:
            self.request_count += 1
            self.serving_seconds += dt
            self.last_serving_sec = dt
            self._lat_ring.append(dt)
        self._h_query.observe(dt)
        return Response(200, body, headers=self._degraded_headers())

    def _queries(self, req: Request) -> Response:
        t_q0 = time.perf_counter()
        # result cache (ISSUE 14): a hit returns the stored serialized
        # bytes — no queue, no batch, no device, no re-serialization
        # (byte-identical across hot-swaps that did not touch this
        # query's entities). The exact-bytes alias answers a repeat
        # client BEFORE the JSON body is even parsed.
        from predictionio_tpu.serving import result_cache as RC
        key = generation = None
        cacheable = self._cache_usable()
        if cacheable:
            body = self.result_cache.get_raw(req.body)
            if body is not None:
                return self._serve_cache_hit(body, t_q0)
        d = req.json()
        if not isinstance(d, dict):
            raise ValueError("query must be a JSON object")
        if cacheable:
            key = RC.query_key(d)
            body = self.result_cache.get(key)
            if body is not None:
                return self._serve_cache_hit(body, t_q0)
            # store-time freshness fence: any invalidation landing
            # while this query computes refuses the store (the result
            # may reflect the pre-swap models)
            generation = self.result_cache.generation
        deadline_s = self._request_deadline_s(req)
        # ingress trace: minted per query — or ADOPTED from an inbound
        # X-PIO-Trace-Id (ISSUE 13), so a traced upstream caller's id
        # spans this process's serve waterfall too. In batched mode the
        # device work happens under the batcher thread's own
        # batch_predict trace; submit() records the two-way link so
        # /traces.json ties a query to the coalesced window that
        # answered it.
        with TRACER.trace("query",
                          **ingress_trace_kwargs(req.headers)) as qt:
            if self.batcher is not None:
                out = self.batcher.submit(d, deadline_s=deadline_s)
            else:
                out = self.handle_query(d)
            total_s = time.perf_counter() - t_q0
            headers = self._degraded_headers()
            if isinstance(out, dict) and "_pioCanary" in out:
                # the canary tag rides the result dict out of the (
                # possibly batched) predict path; surface it as the
                # X-PIO-Canary response header instead of body noise
                out = dict(out)
                version = out.pop("_pioCanary")
                headers = dict(headers or {})
                headers["X-PIO-Canary"] = str(version)
                cacheable = False   # a canary arm answered after all
            body = None
            if cacheable and key is not None and isinstance(out, dict):
                # serialize ONCE: the same bytes answer this request
                # and every future hit (the serialize stage is paid
                # exactly once per distinct query per model version)
                try:
                    body = json.dumps(out).encode("utf-8")
                except (TypeError, ValueError):
                    body = None
                if body is not None:
                    self.result_cache.put(
                        key, body, RC.query_entities(d),
                        result_items=self._result_item_ids(out),
                        generation=generation, raw=req.body)
            if total_s >= slow_threshold_s():
                # slow-query forensics (ISSUE 11): this request already
                # blew the SLO latency bound — capture its stage
                # waterfall (all capture work is off the fast path by
                # construction)
                self._capture_slow(qt, d, out, total_s)
            return Response(200, body if body is not None else out,
                            headers=headers)

    def _capture_slow(self, qt, query_dict: dict, out, total_s: float):
        """Build + record the slow request's waterfall; never raises
        into the response path."""
        try:
            # the serialize stage IS a second json.dumps of the
            # response: tens of µs on a request that already took
            # >=250 ms (<0.05%), paid only on the slow path — and when
            # the payload is big enough for this to matter, a
            # serialize-dominated tail is exactly the diagnosis the
            # stage exists to surface
            t0 = time.perf_counter()
            try:
                json.dumps(out, default=str)
            except Exception:
                pass
            serialize_s = time.perf_counter() - t0
            # the batcher's submit() linked the coalesced window's
            # batch_predict trace onto this query trace
            batch_tid = next(iter(qt.links), None)
            capture_slow_query(qt, total_s, query=query_dict,
                               model_version=self.model_version,
                               serialize_s=serialize_s,
                               batch_trace_id=batch_tid,
                               tenant=self.tenant)
        except Exception:
            logger.debug("slow-query capture failed", exc_info=True)

    def _slow(self, req: Request) -> Response:
        """GET /slow.json — recent slow-query stage waterfalls
        (?n=; obs/slowlog.py). Each entry's traceId resolves via
        /traces.json?trace_id= to the full span tree."""
        return Response(200, slow_response(req.params))

    def _reload(self, req: Request) -> Response:
        """Hot-swap to the latest COMPLETED instance (:337-358). When
        the POST carries an inbound trace id (a cross-process
        scheduler's publish hop, ISSUE 13) the reload runs under it, so
        this process's hot_swap flight record and load spans join the
        fold tick's fleet-stitched story."""
        kw = ingress_trace_kwargs(req.headers)
        if kw:
            with TRACER.trace("reload", **kw):
                return self._reload_inner(req)
        return self._reload_inner(req)

    def _reload_inner(self, req: Request) -> Response:
        if self.coordinator is not None and self.coordinator.multi_process:
            # reload is per-process: swapping models on the primary only
            # would serve mismatched shards (wrong scores or a collective
            # shape hang). Redeploy the whole mesh instead.
            return Response(400, {
                "message": "reload is not supported under a multi-process "
                           "mesh; redeploy all processes"})
        cfg = self.config
        if cfg.engine_instance_id is None and self.engine_instance:
            cfg.engine_id = self.engine_instance.engine_id
            cfg.engine_version = self.engine_instance.engine_version
            cfg.engine_variant = self.engine_instance.engine_variant
        self.engine_params = None  # re-derive from the new instance
        self.load()
        return Response(200, {"message": "Reloaded"})

    def _stop(self, req: Request) -> Response:
        threading.Thread(target=self.stop, daemon=True).start()
        return Response(200, {"message": "Shutting down."})

    def _plugins(self, req: Request) -> Response:
        return Response(200, self.plugin_context.to_dict())

    def _stats(self, req: Request) -> Response:
        """JSON serving counters with the predict/total latency split: how
        much of the serving time is the algorithm's device scoring vs
        serve/HTTP overhead."""
        if self.canary.enabled:
            # idle-traffic watchdog kick: a stats poll can land the
            # promote/rollback decision when no query has since
            self._apply_canary_decision()
        with self._lock:
            n = self.request_count
            out = {
                "requestCount": n,
                "avgServingSec": self.serving_seconds / n if n else 0.0,
                "lastServingSec": self.last_serving_sec,
                "avgPredictSec": self.predict_seconds / n if n else 0.0,
                "microBatch": self.config.micro_batch,
                "startTime": self.start_time.isoformat(),
                # online-update observability (ISSUE 1): how many times
                # the serving models were hot-swapped, how many fold-ins
                # landed, and which version answers queries right now
                "modelSwaps": self.swap_count,
                "foldIns": self.fold_in_count,
                "foldInEvents": self.fold_in_events,
                "modelVersion": self.model_version,
                # graceful-degradation state (ISSUE 3): is this server
                # knowingly serving a stale model, and how stale
                "publishDegraded": self.publish_degraded,
                "publishFailures": self.publish_failures,
                "modelStalenessSec": self.model_staleness_s(),
                # guarded deploys (ISSUE 5): canary arm state and the
                # in-memory rollback anchor
                "canary": self.canary.stats(),
                "lastGoodVersion": self.last_good_version,
                # compile plane (ISSUE 9): how fast the last model
                # change reached its first served query, and the last
                # deploy-time warm summary
                "swapToFirstQueryMs": self.last_swap_to_first_query_ms,
                "aotWarm": self.last_aot_warm,
                # sharded online plane (ISSUE 12): per-algorithm factor
                # table layout (+ per-shard HBM cost when sharded)
                "modelSharding": self._model_sharding(),
            }
            if self.tenant is not None:
                out["tenant"] = self.tenant
            pct = self._ring_percentiles()
            if pct is not None:
                out.update({"p50ServingSec": float(pct[0]),
                            "p95ServingSec": float(pct[1]),
                            "p99ServingSec": float(pct[2])})
            # registry-derived distributions (ISSUE 2): bucketed
            # percentiles for the query path, and batch-wait when the
            # micro-batcher is on — same instruments /metrics exposes
            out["queryLatency"] = self._h_query.snapshot()
            if self.batcher is not None and self.batcher.wait_hist \
                    is not None:
                out["batchWait"] = self.batcher.wait_hist.snapshot()
            if self.batcher is not None:
                # realized coalescing (avg/max batch size) — the datum
                # for tuning micro_batch_wait_ms on a given link
                out.update(self.batcher.stats())
            if self.result_cache is not None:
                # result cache (ISSUE 14): hit rate + residency next
                # to the coalescing numbers they offload
                out["resultCache"] = self.result_cache.stats()
            if self.coordinator is not None:
                out["meshCoordinator"] = self.coordinator.health()
        # AOT registry + persistent-cache state (ISSUE 9 satellite):
        # executables resident, buckets compiled, dispatch hit/miss and
        # persistent-cache counters since start — outside the serving
        # lock (snapshot takes the registry's own lock; cache status
        # does a small dir listing)
        try:
            from predictionio_tpu.compile.aot import get_aot
            from predictionio_tpu.compile.cache import cache_status
            out["aot"] = get_aot().snapshot()
            out["xlaCache"] = cache_status()
        except Exception:
            logger.debug("aot stats unavailable", exc_info=True)
        # runtime attribution (ISSUE 11): estimated device seconds per
        # executable + occupancy — where the accelerator's time goes
        try:
            from predictionio_tpu.obs import costmon
            out["deviceTime"] = costmon.device_snapshot()
        except Exception:
            logger.debug("device time stats unavailable",
                         exc_info=True)
        return Response(200, out)

    def _profile(self, req: Request) -> Response:
        """``/profile.json`` — profiling surface (obs/profiler.py,
        ISSUE 11): POST ``{"action": "start"|"stop"}`` toggles the
        jax.profiler device trace with the ISSUE 2 idempotent
        semantics (state machine now lives in obs/profiler so the
        event server shares it); ``action=report`` (GET or POST)
        returns the always-on sampling profiler's folded-stack
        report."""
        from predictionio_tpu.obs import profiler
        status, body = profiler.profile_response_from_request(req)
        return Response(status, body)

    def _metrics(self, req: Request) -> Response:
        """Prometheus text exposition, rendered solely by the shared
        metrics registry (ISSUE 2): this server's families (counters,
        quantile summary, query/batch-wait histograms, batcher and mesh
        collectors) plus the process-wide ones (JAX runtime, fold/train
        instruments) through the parent chain. ``?exemplars=1`` (or an
        OpenMetrics Accept header) switches to the exemplar-bearing
        OpenMetrics exposition (ISSUE 11) — the default body stays
        classic-parser safe."""
        from predictionio_tpu.utils.prometheus import (
            CONTENT_TYPE, OPENMETRICS_CONTENT_TYPE, wants_exemplars)
        om = wants_exemplars(req)
        return Response(
            200, self.metrics.render(exemplars=om),
            content_type=OPENMETRICS_CONTENT_TYPE if om
            else CONTENT_TYPE)

    def _traces(self, req: Request) -> Response:
        """GET /traces.json — recent span trees from the process-wide
        tracer (?n=, ?kind=, ?trace_id=, ?sort=slowest)."""
        return Response(200, traces_response(req.params))

    def _flight(self, req: Request) -> Response:
        """GET /flight.json — recent lifecycle wide events from the
        process flight recorder (?n=, ?kind=, ?trace_id=)."""
        return Response(200, flight_response(req.params))

    def _health(self, req: Request) -> Response:
        """GET /health.json — SLO verdicts with fast/slow burn rates
        (ISSUE 6): serve p99, fold-tick duration, model staleness and
        the guarded-deploys event budget. A latency SLO transitioning
        into ``breached`` auto-captures an incident bundle (ISSUE 11):
        the slow_queries + profiler providers put the top waterfalls
        and the sampling profiler's stacks into it, so the p99
        postmortem starts with evidence, not with reproduction."""
        out = health_response(self.slo, extra={
            "modelVersion": self.model_version,
            "publishDegraded": self.publish_degraded})
        try:
            self._note_slo_breaches(out)
        except Exception:
            logger.debug("slo breach capture failed", exc_info=True)
        return Response(200, out)

    def _note_slo_breaches(self, health: dict):
        """Fire one incident capture per ok->breached transition of a
        latency SLO (the per-kind cooldown in IncidentManager bounds a
        flapping SLO). State is per-server, in-memory — a restart
        re-captures, which is the right bias for forensics."""
        for s in health.get("slo", ()):
            name, status = s.get("name"), s.get("status")
            if name is None:
                continue
            prev = self._slo_status.get(name)
            self._slo_status[name] = status
            if status == "breached" and prev != "breached" \
                    and s.get("kind") == "latency":
                # tenant scope (None = no-op): a slot's breach record
                # and bundle name the tenant, and the bundle's
                # forensics keep to that tenant's slice (ISSUE 17)
                from predictionio_tpu.obs.tenantctx import tenant_scope
                with tenant_scope(self.tenant):
                    FLIGHT.record("slo_breach", slo=name,
                                  burnFast=s.get("burnFast"),
                                  burnSlow=s.get("burnSlow"))
                    get_incidents().capture(
                        "slo_breach",
                        f"latency SLO {name} breached "
                        f"(burn fast/slow = {s.get('burnFast')}/"
                        f"{s.get('burnSlow')})",
                        context={"slo": s},
                        tenant=self.tenant)

    # -- fleet federation (ISSUE 13) ----------------------------------------
    def _fleet_status(self, req: Request) -> Response:
        """GET /fleet/status.json — member registry with liveness."""
        return Response(200, fleet.fleet_status_response(req.params))

    def _fleet_health(self, req: Request) -> Response:
        """GET /fleet/health.json — worst-of SLO rollup across live
        members."""
        return Response(200, fleet.fleet_health_response(req.params))

    def _fleet_metrics(self, req: Request) -> Response:
        """GET /fleet/metrics — every live member's scrape merged with
        {role,pid} labels (obs/fleet.py)."""
        from predictionio_tpu.utils.prometheus import CONTENT_TYPE
        return Response(200, fleet.fleet_metrics_response(req.params),
                        content_type=CONTENT_TYPE)

    def _fleet_traces(self, req: Request) -> Response:
        """GET /fleet/traces.json?trace_id= — one trace stitched
        fleet-wide into a cross-process waterfall."""
        return Response(200, fleet.fleet_traces_response(req.params))

    def _incidents_list(self, req: Request) -> Response:
        """GET /incidents.json — bundle index (`pio incidents list
        --url`)."""
        from predictionio_tpu.obs.incidents import incidents_response
        return Response(200, incidents_response(req.params))

    def _incident_show(self, req: Request) -> Response:
        from predictionio_tpu.obs.incidents import incident_response
        status, body = incident_response(req.path_args[0])
        return Response(status, body)

    def _build_router(self) -> Router:
        r = Router()
        r.add("GET", "/", self._status_page)
        r.add("POST", "/queries.json", self._queries)
        r.add("GET", "/reload", self._reload)
        r.add("POST", "/reload", self._reload)
        r.add("POST", "/stop", self._stop)
        r.add("GET", "/stop", self._stop)
        r.add("GET", "/plugins.json", self._plugins)
        r.add("GET", "/stats.json", self._stats)
        r.add("GET", "/metrics", self._metrics)
        r.add("GET", "/traces.json", self._traces)
        r.add("GET", "/flight.json", self._flight)
        r.add("GET", "/health.json", self._health)
        r.add("GET", "/fleet/status.json", self._fleet_status)
        r.add("GET", "/fleet/health.json", self._fleet_health)
        r.add("GET", "/fleet/metrics", self._fleet_metrics)
        r.add("GET", "/fleet/traces.json", self._fleet_traces)
        r.add("GET", "/incidents.json", self._incidents_list)
        r.add("GET", "/incidents/<id>.json", self._incident_show)
        r.add("GET", "/slow.json", self._slow)
        r.add("POST", "/profile.json", self._profile)
        r.add("GET", "/profile.json", self._profile)
        return r

    # -- lifecycle ----------------------------------------------------------
    def start(self, background: bool = True) -> "EngineServer":
        # always-on sampling profiler (ISSUE 11; PIO_PROFILER=off to
        # disable): server processes sample from first request on, so
        # a p99 postmortem never starts with "restart with profiling"
        from predictionio_tpu.obs import profiler
        profiler.ensure_started()
        srv = HttpServer(self.router, self.config.ip, self.config.port)
        self.server = srv

        def _bound(s):
            # post-bind / pre-serve: publish the resolved port (fleet
            # member record, ISSUE 13) before a foreground
            # serve_forever blocks
            self.config.port = s.port
            fid = fleet.register_member(
                "engine_server", port=s.port, host=self.config.ip)
            with self._lock:
                self._fleet_id = fid
            logger.info("Engine server started on %s:%d",
                        self.config.ip, s.port)

        srv.on_bound = _bound
        srv.start(background=background)
        return self

    def stop(self):
        # order matters for a clean drain: stop ACCEPTING first (the
        # HTTP listener), then the batcher (which fails any still-queued
        # waiters so their request threads return 500 instead of
        # blocking forever), then release the mesh workers. self.server
        # is nulled LAST — deploy's foreground loop watches it, and
        # signaling "stopped" before the worker-release broadcast lets
        # the primary's interpreter exit mid-collective and strand the
        # workers (observed as a poisoned release bcast in the 2-proc
        # test)
        # /stop runs this on a spawned thread while start()'s on_bound
        # hook writes _fleet_id from the serving thread: swap it out
        # under the serving lock, deregister (file IO) outside it
        with self._lock:
            fleet_id = self._fleet_id
            self._fleet_id = None
        fleet.deregister_member(fleet_id)
        if self.server:
            self.server.stop()
        if self.batcher is not None:
            self.batcher.stop()
        if self.coordinator is not None:
            self.coordinator.shutdown()
        self.server = None
