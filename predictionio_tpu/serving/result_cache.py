"""Touched-row-invalidated top-k result cache (ISSUE 14 tentpole c).

Serving traffic is zipfian: a small set of hot users asks for the same
ranking over and over, and between fold ticks the answer is a pure
function of (query, deployed model). This cache stores the FINAL
serialized response bytes per canonical query, so a hit skips the
whole pipeline — queue, batch formation, supplement, device dispatch,
readback, post-process AND serialization — and returns bytes the HTTP
layer writes straight to the socket.

Invalidation contract (the part that makes this safe under online
updates): fold-tick publishes know exactly which user/item rows they
re-solved (EntityDelta -> touched entity ids; sharded publishes patch
the same rows through ShardedTable.with_rows), so a hot-swap from a
fold tick drops ONLY the entries registered under a touched entity —
cached rankings for untouched users survive the swap byte-identical.
Any model change whose touched set is unknown (full /reload, canary
stage/promote/rollback, an operator swap without lineage) clears the
whole cache. Within a fold tick the untouched users' factor rows are
bit-identical by construction (touched-row solves never move other
rows), so a surviving entry equals a recompute against its own row;
item-row movement can perturb an untouched user's ranking by at most
the touched rows' score deltas — the documented staleness trade, on
by default and bounded by the fold cadence. ``PIO_SERVE_CACHE=off``
(or ``ServerConfig.result_cache=False``) disables;
``PIO_SERVE_CACHE_STRICT=1`` additionally drops every entry whose
CACHED RESULT contains a touched item id (exact-result freshness at
the cost of broader invalidation).

Budget: hard entry and byte caps, LRU eviction, O(1) per lookup.
Telemetry: ``pio_serve_cache_{hits,misses,invalidations}_total``,
entry/byte gauges, eviction counter.
"""

from __future__ import annotations

import collections
import json
import os
import threading
from typing import Dict, Iterable, List, Optional, Tuple

#: query-dict fields that name cacheable entities, and the tag prefix
#: their values register under (the invalidation join key)
_ENTITY_FIELDS = (("user", "user"), ("item", "item"), ("items", "item"))

#: namespace separator for tenant-prefixed keys/tags (ISSUE 15
#: satellite): a control character no JSON-canonical query key or
#: entity id produced by ``query_key``/``entity_tags`` can contain, so
#: a namespaced key can never collide with (or alias) an unnamespaced
#: one
NS_SEP = "\x1f"


def cache_enabled() -> bool:
    return os.environ.get("PIO_SERVE_CACHE", "").lower() not in (
        "off", "0", "false", "no")


def strict_items() -> bool:
    """Strict mode: entries whose cached result CONTAINS a touched
    item are dropped too (exact freshness; broader invalidation)."""
    return os.environ.get("PIO_SERVE_CACHE_STRICT", "").lower() in (
        "1", "on", "true", "yes")


def query_key(query_dict: dict) -> Optional[str]:
    """Canonical cache key for one query body; None = uncacheable
    (non-JSON-canonical content)."""
    try:
        return json.dumps(query_dict, sort_keys=True,
                          separators=(",", ":"))
    except (TypeError, ValueError):
        return None


def query_entities(query_dict: dict) -> Tuple[str, ...]:
    """The entity tags a query's cached result registers under —
    exactly the ids a fold tick names when it touches the entity."""
    tags: List[str] = []
    for field, prefix in _ENTITY_FIELDS:
        v = query_dict.get(field)
        if v is None:
            continue
        if isinstance(v, (list, tuple)):
            tags.extend(f"{prefix}:{x}" for x in v)
        else:
            tags.append(f"{prefix}:{v}")
    return tuple(tags)


def entity_tags(touched: Dict[str, Iterable]) -> List[str]:
    """{"user": ids, "item": ids} -> flat tag list."""
    out: List[str] = []
    for kind, ids in (touched or {}).items():
        out.extend(f"{kind}:{i}" for i in ids)
    return out


class _Entry:
    __slots__ = ("body", "entities", "result_items", "nbytes", "raw")

    def __init__(self, body: bytes, entities: Tuple[str, ...],
                 result_items: Tuple[str, ...],
                 raw: Optional[bytes] = None):
        self.body = body
        self.entities = entities
        self.result_items = result_items
        self.nbytes = len(body)
        # the exact request bytes that produced this entry (one per
        # entry): a repeat client resends byte-identical bodies, so
        # the hot-path lookup can skip JSON parse + canonicalization
        self.raw = raw


class ResultCache:
    """Thread-safe LRU of serialized response bytes, indexed by entity
    tag for O(touched) fold-swap invalidation."""

    def __init__(self, max_entries: int = 8192,
                 max_bytes: int = 64 << 20, metrics=None):
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, _Entry]" = \
            collections.OrderedDict()
        # exact request bytes -> canonical key (one alias per entry):
        # the zero-parse hot-path lookup for repeat clients
        self._raw_alias: Dict[bytes, str] = {}
        # entity tag -> keys whose cached entry registered it
        self._by_entity: Dict[str, set] = {}
        self._bytes = 0
        #: bumped by every invalidation — the store-time freshness
        #: fence: a caller snapshots it before computing and passes it
        #: to put(); a mismatch (a swap landed mid-compute) refuses the
        #: store, so a result reflecting pre-swap models can never be
        #: cached after its invalidation already ran
        self.generation = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # per-reason invalidation counts (fold_swap / full / budget ...)
        self.invalidations: Dict[str, int] = {}
        if metrics is not None:
            metrics.counter_func(
                "pio_serve_cache_hits_total",
                "Queries answered from the serving result cache "
                "(skipping batch formation, dispatch and serialization)",
                lambda: self.hits)
            metrics.counter_func(
                "pio_serve_cache_misses_total",
                "Cacheable queries that missed the result cache",
                lambda: self.misses)
            metrics.counter_func(
                "pio_serve_cache_invalidations_total",
                "Cache entries dropped by invalidation, by reason "
                "(fold_swap = touched-entity drop, full = whole-cache "
                "clear on an unattributed model change)",
                lambda: [({"reason": r}, n) for r, n in
                         sorted(self.invalidations.items())]
                or [(None, 0)])
            metrics.counter_func(
                "pio_serve_cache_evictions_total",
                "Entries evicted by the entry/byte budget (LRU)",
                lambda: self.evictions)
            metrics.gauge_func(
                "pio_serve_cache_entries",
                "Entries resident in the serving result cache",
                lambda: len(self._entries))
            metrics.gauge_func(
                "pio_serve_cache_bytes",
                "Serialized bytes resident in the serving result cache",
                lambda: self._bytes)

    # -- lookup/store -------------------------------------------------------
    def get(self, key: Optional[str]) -> Optional[bytes]:
        if key is None:
            return None
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return e.body

    def get_raw(self, raw: bytes) -> Optional[bytes]:
        """Exact-request-bytes lookup — the zero-parse hot path: a
        repeat client resends byte-identical bodies, so a hit here
        costs two dict probes and NO JSON parse/canonicalization.
        None on miss (the caller falls back to the canonical key and
        counts the miss there — a raw miss is not a cache miss)."""
        with self._lock:
            key = self._raw_alias.get(raw)
            if key is None:
                return None
            e = self._entries.get(key)
            if e is None:
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return e.body

    def put(self, key: Optional[str], body: bytes,
            entities: Tuple[str, ...],
            result_items: Tuple[str, ...] = (),
            generation: Optional[int] = None,
            raw: Optional[bytes] = None) -> bool:
        """Store one serialized response under its entity tags.
        ``result_items``: the item ids the response ranks — consulted
        only in strict mode. ``generation``: the caller's pre-compute
        snapshot of :attr:`generation`; a mismatch refuses the store.
        ``raw``: the exact request bytes, registered as the zero-parse
        alias for :meth:`get_raw`. Oversized bodies are refused (one
        giant response must not evict the whole hot set)."""
        if key is None or len(body) > self.max_bytes // 4:
            return False
        with self._lock:
            if generation is not None and generation != self.generation:
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._unindex(key, old)
                self._bytes -= old.nbytes
            e = _Entry(bytes(body), entities, tuple(result_items),
                       raw=bytes(raw) if raw is not None else None)
            self._entries[key] = e
            self._bytes += e.nbytes
            if e.raw is not None:
                self._raw_alias[e.raw] = key
            for tag in entities:
                self._by_entity.setdefault(tag, set()).add(key)
            while (len(self._entries) > self.max_entries
                   or self._bytes > self.max_bytes):
                k, victim = self._entries.popitem(last=False)
                self._unindex(k, victim)
                self._bytes -= victim.nbytes
                self.evictions += 1
        return True

    def _unindex(self, key: str, e: _Entry):
        if e.raw is not None and self._raw_alias.get(e.raw) == key:
            self._raw_alias.pop(e.raw, None)
        for tag in e.entities:
            keys = self._by_entity.get(tag)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    self._by_entity.pop(tag, None)

    # -- invalidation -------------------------------------------------------
    def invalidate_entities(self, tags: Iterable[str],
                            reason: str = "fold_swap") -> int:
        """Drop exactly the entries registered under any touched tag
        (plus, in strict mode, entries whose cached result contains a
        touched item id). O(touched + dropped), never a full scan —
        untouched entries are not even visited.

        Tags may carry a tenant namespace prefix (``<ns>\\x1f<tag>``,
        :class:`TenantResultCache`): the strict-mode result-item join
        then only considers entries in the SAME namespace — tenant A's
        fold tick touching item i must never drop tenant B's cached
        rankings of a same-named item."""
        tags = list(tags)
        strict = strict_items()
        # touched item ids per namespace ("" = unnamespaced keys)
        touched_by_ns: Dict[str, set] = {}
        if strict:
            for t in tags:
                ns, sep, rest = t.rpartition(NS_SEP)
                if rest.startswith("item:"):
                    touched_by_ns.setdefault(
                        ns + sep, set()).add(rest.split(":", 1)[1])
        with self._lock:
            self.generation += 1
            doomed = set()
            for tag in tags:
                doomed |= self._by_entity.get(tag, set())
            if touched_by_ns:
                for k, e in self._entries.items():
                    for nsp, items in touched_by_ns.items():
                        if nsp:
                            if not k.startswith(nsp):
                                continue
                        elif NS_SEP in k:
                            continue
                        if items.intersection(e.result_items):
                            doomed.add(k)
                            break
            for k in doomed:
                e = self._entries.pop(k, None)
                if e is None:
                    continue
                self._unindex(k, e)
                self._bytes -= e.nbytes
            if doomed:
                self.invalidations[reason] = \
                    self.invalidations.get(reason, 0) + len(doomed)
            return len(doomed)

    def invalidate_prefix(self, prefix: str, reason: str = "full") -> int:
        """Drop every entry whose key starts with ``prefix`` — the
        tenant-scoped analog of :meth:`invalidate_all` on a shared
        cache (one tenant's /reload must not clear its neighbors' hot
        sets). O(entries) like invalidate_all, paid only on
        unattributed model changes."""
        with self._lock:
            self.generation += 1
            doomed = [k for k in self._entries if k.startswith(prefix)]
            for k in doomed:
                e = self._entries.pop(k, None)
                if e is None:
                    continue
                self._unindex(k, e)
                self._bytes -= e.nbytes
            if doomed:
                self.invalidations[reason] = \
                    self.invalidations.get(reason, 0) + len(doomed)
            return len(doomed)

    def invalidate_all(self, reason: str = "full") -> int:
        with self._lock:
            self.generation += 1
            n = len(self._entries)
            self._entries.clear()
            self._raw_alias.clear()
            self._by_entity.clear()
            self._bytes = 0
            if n:
                self.invalidations[reason] = \
                    self.invalidations.get(reason, 0) + n
            return n

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "maxEntries": self.max_entries,
                "maxBytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hitRate": (self.hits / total if total else None),
                "evictions": self.evictions,
                "invalidations": dict(self.invalidations),
            }


class TenantResultCache:
    """Tenant-namespaced view over a shared :class:`ResultCache`
    (ISSUE 15 satellite bugfix). The underlying cache keyed entries on
    request bytes / canonical query JSON / entity ids ONLY — correct
    for one engine per process, but the moment a serving host packs
    many engines, two tenants sending byte-identical queries (every
    template shares the ``{"user": ..., "num": ...}`` wire shape) would
    collide: tenant B could be answered with tenant A's cached ranking.
    Every canonical key, exact-request-bytes alias and entity tag is
    prefixed here with the tenant id + ``NS_SEP``, so cross-tenant hits
    are structurally impossible while all tenants still share ONE
    entry/byte budget and LRU order (a hot tenant can use the whole
    pool when its neighbors are idle)."""

    def __init__(self, inner: ResultCache, tenant: str):
        tenant = str(tenant)
        if NS_SEP in tenant:
            raise ValueError("tenant id must not contain NS_SEP")
        self.inner = inner
        self.tenant = tenant
        self._kp = tenant + NS_SEP
        self._rp = self._kp.encode("utf-8")
        # per-NAMESPACE store-time freshness fence: only THIS tenant's
        # invalidations bump it. Proxying the shared inner counter
        # would let tenant A's fold cadence refuse tenant B's
        # concurrent stores (nothing in B's namespace changed) —
        # cross-tenant hit-rate interference the isolation contract
        # forbids. Int read/write under the GIL; the worst race is one
        # refused store, the safe direction.
        self._generation = 0

    @property
    def generation(self) -> int:
        return self._generation

    def get(self, key: Optional[str]) -> Optional[bytes]:
        return self.inner.get(None if key is None else self._kp + key)

    def get_raw(self, raw: bytes) -> Optional[bytes]:
        return self.inner.get_raw(self._rp + raw)

    def put(self, key: Optional[str], body: bytes,
            entities: Tuple[str, ...],
            result_items: Tuple[str, ...] = (),
            generation: Optional[int] = None,
            raw: Optional[bytes] = None) -> bool:
        # the fence is enforced HERE against the per-tenant counter;
        # the inner cache's (cross-tenant) generation is bypassed
        if generation is not None and generation != self._generation:
            return False
        return self.inner.put(
            None if key is None else self._kp + key, body,
            tuple(self._kp + t for t in entities),
            result_items=result_items, generation=None,
            raw=None if raw is None else self._rp + raw)

    def invalidate_entities(self, tags: Iterable[str],
                            reason: str = "fold_swap") -> int:
        self._generation += 1
        return self.inner.invalidate_entities(
            [self._kp + t for t in tags], reason=reason)

    def invalidate_all(self, reason: str = "full") -> int:
        # tenant-scoped: this tenant's /reload or canary event clears
        # ONLY its namespace; the neighbors' hot sets survive
        self._generation += 1
        return self.inner.invalidate_prefix(self._kp, reason=reason)

    def stats(self) -> dict:
        return dict(self.inner.stats(), tenant=self.tenant, shared=True)
