"""Micro-batching for the query path (beyond-parity).

The reference serves queries one at a time per request thread
(CreateServer.scala:515 "TODO: Parallelize"). On a TPU the per-call
dispatch + device->host fetch dominates single-query latency, so under
concurrent load the server can coalesce queries that arrive within a short
window into ONE batched device call (Algorithm.batch_predict) and fan the
results back out — the standard accelerator-serving pattern.

Opt-in via ServerConfig.micro_batch > 1. Coalescing is DRAIN-FIRST:
each dispatch takes everything that queued while the previous batch was
on the device — under load the queue grows, so batches grow, which is
the self-regulating part that delivers the throughput. On top of that,
the door is held open (up to `max_wait_ms`) only while MORE queries are
known to be in flight (submitted, unanswered, not yet dispatched, not
in this batch) than the batch holds: that covers the instants between a
submit's counter increment and its queue put, and nothing else — a
query still being HTTP-parsed is invisible to the server and no window
can wait for it honestly. A lone closed-loop client (serial requests)
always sees `batch == undispatched` and dispatches immediately with no
window cost; so does an idle server. Two earlier designs were rejected
by measurement: an unconditional window (rounds 2-3) charged every
serial query the full window, and an EMA-of-arrival-gaps gate charged
them the same way because one closed-loop client's gaps equal the
service time — dense by any rate heuristic. `latency_budget_ms`, when
set, caps how long the OLDEST query may sit in the coalescing stage
(the knob for tail-latency-sensitive deployments; it bounds queueing
delay, not device time).

Pipelined executor (ISSUE 14): with ``process_batch_begin`` provided
and ``inflight`` > 1 (PIO_SERVE_INFLIGHT, default 2), the serve path
runs as a two-stage pipeline exploiting JAX async dispatch — the
FORMATION thread forms batch N+1 and enqueues its device call while
batch N's compute is still on the device, and a dedicated COMPLETION
thread performs batch N's deferred device->host readback,
post-processing and waiter wakeup. A bounded semaphore caps the
windows between dispatch and completion at ``inflight`` (backpressure:
formation blocks when the device/completion side lags). Host-side
stages (formation, supplement, serialization) thereby overlap device
compute; the costmon 1-in-N sampled sync inside the dispatch stays the
only deliberate sync besides the completion readback itself.

Adaptive batch sizing (ISSUE 14): instead of the fixed wait-window
alone, each window derives a pow2-snapped target batch size from the
known demand (queue depth + undispatched count) and scales its hold
with the ``pio_device_occupancy`` EWMA — a busy device earns fuller
windows (fewer, larger dispatches), an idle one dispatches at the
first pow2 boundary covering demand. Targets never exceed
``max_batch`` and snap to the same pow2 buckets the AOT warm ladder
compiled, so adaptation can never mint a program or trigger a compile.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from typing import Any, Callable, Optional

from predictionio_tpu.obs.slo import lock_probe, timed_acquire

logger = logging.getLogger(__name__)


def _inflight_default() -> int:
    """Dispatched-not-completed window cap. Since the readback plane
    (ISSUE 19) every in-flight window's d2h copy is already in flight
    at dispatch, so this is also the TRANSFER-depth knob: K windows'
    d2h walls overlap instead of serialize, and values > 2 genuinely
    deepen the pipeline on a real accelerator (bench sweeps it as
    ``serve_inflight_sweep``)."""
    try:
        return max(1, int(os.environ.get("PIO_SERVE_INFLIGHT", 2)))
    except (TypeError, ValueError):
        return 2


def _adapt_occ_default() -> float:
    """Occupancy above which the adaptive sizer doubles its target
    toward the next pow2 bucket (the device is the bottleneck: fuller
    windows cut per-dispatch overhead)."""
    try:
        return float(os.environ.get("PIO_SERVE_ADAPT_OCC", 0.4))
    except (TypeError, ValueError):
        return 0.4


class ShedError(RuntimeError):
    """Load shed: the queue's wait bound exceeds the request's deadline,
    so the server answers 503 + Retry-After NOW instead of burning a
    thread on an answer the client will have abandoned (ISSUE 3
    graceful degradation). ``retry_after_s`` is the server's own wait
    bound — the honest earliest time a retry could be served."""

    http_status = 503

    def __init__(self, wait_bound_s: float, deadline_s: float):
        super().__init__(
            f"overloaded: queue wait bound {wait_bound_s * 1000:.0f}ms "
            f"exceeds request deadline {deadline_s * 1000:.0f}ms")
        self.retry_after_s = wait_bound_s


class ShutdownError(RuntimeError):
    """The micro-batcher is stopping; queued requests fail explicitly
    instead of hanging their futures."""

    http_status = 503

    def __init__(self, message: str = "server shutting down"):
        super().__init__(message)


class _Pending:
    __slots__ = ("query", "event", "result", "error", "t_enqueue",
                 "trace_id", "batch_trace_id")

    def __init__(self, query):
        self.query = query
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.t_enqueue = time.perf_counter()
        # ingress trace of the submitting request thread; the dispatch
        # loop links it to the batch_predict trace (and back)
        self.trace_id: Optional[str] = None
        self.batch_trace_id: Optional[str] = None


class _InFlight:
    """One dispatched-not-completed window riding the completion
    queue: its members, the deferred finish() closure, the (open)
    batch_predict trace, and the dispatch timestamps."""

    __slots__ = ("batch", "finish", "trace", "t_dispatch", "t_ready")

    def __init__(self, batch, finish, trace, t_dispatch):
        self.batch = batch
        self.finish = finish
        self.trace = trace
        self.t_dispatch = t_dispatch
        self.t_ready = time.perf_counter()


class MicroBatcher:
    def __init__(self, process_batch, max_batch: int = 32,
                 max_wait_ms: float = 5.0,
                 latency_budget_ms: Optional[float] = None,
                 metrics=None,
                 process_batch_begin: Optional[Callable] = None,
                 inflight: Optional[int] = None,
                 adaptive: bool = True,
                 tenant: Optional[str] = None):
        """process_batch: fn(List[query]) -> List[result].
        ``process_batch_begin``: fn(List[query]) -> finish() -> results
        — the two-stage split enabling the pipelined executor; with it
        and ``inflight`` > 1 the batcher overlaps device compute with
        formation/completion (see module docstring). `metrics`: an
        obs.MetricsRegistry to mount the coalescing telemetry on — the
        counters below stay the single source of truth (stats() reads
        them directly) and the registry samples them at scrape time;
        the batch-wait distribution is a native histogram."""
        # device dispatch runs on the formation/completion threads,
        # not the request thread — so tenant attribution (ISSUE 17
        # costmon device-time booking, flight/trace stamps) must be
        # entered HERE, once per thread, not per request
        self.tenant = str(tenant) if tenant is not None else None
        self.process_batch = process_batch
        self.process_batch_begin = process_batch_begin
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.latency_budget_s = (latency_budget_ms / 1000.0
                                 if latency_budget_ms is not None else None)
        self.inflight = max(1, int(inflight) if inflight is not None
                            else _inflight_default())
        self.pipelined = (process_batch_begin is not None
                          and self.inflight > 1)
        self.adaptive = bool(adaptive)
        self._adapt_occ = _adapt_occ_default()
        # realized coalescing telemetry (read via /stats.json): whether
        # concurrent load actually forms full batches is THE datum for
        # tuning micro_batch_wait_ms on a given link
        self.n_batches = 0
        self.n_queries = 0
        self.max_batch_seen = 0
        # batches dispatched without ever blocking on the window —
        # includes idle/serial traffic AND fully-drained batches under
        # saturated load; (batches - immediateBatches) is the number of
        # dispatches that actually waited for a straggler
        self.n_immediate = 0
        # WHY each dispatch closed its batch — the attribution data for a
        # realized avg batch below micro_batch under concurrent load
        # (e.g. the pinned serve_avg_batch_size=8.0 at micro_batch=16):
        #   exitFullBatch   — hit max_batch (device-bound; raising
        #                     micro_batch could coalesce more)
        #   exitDrainGate   — queue empty and undispatched <= batch: the
        #                     CLIENT POOL was the limit (every submitted-
        #                     undispatched query is already in this batch
        #                     — with N closed-loop clients the steady-
        #                     state batch is at most N whatever the
        #                     window)
        #   exitWindow      — the hold expired waiting on a counted
        #                     straggler (max_wait_ms / latency budget
        #                     bound; raising the window could help)
        #   exitAdaptive    — the pow2-snapped adaptive target was
        #                     reached (ISSUE 14): demand covered, no
        #                     point holding for stragglers past the
        #                     bucket boundary the padding pays anyway
        self.n_exit_full = 0
        self.n_exit_drain_gate = 0
        self.n_exit_window = 0
        self.n_exit_adaptive = 0
        # sum of inflight observed at dispatch: avg inflight is the
        # effective concurrent-client count the batcher actually saw
        self.inflight_at_dispatch_sum = 0
        # queries submitted and not yet answered — feeds the queue wait
        # bound and stats
        self._inflight = 0
        # queries submitted and not yet taken into a dispatched batch —
        # the adaptive window's signal: hold only while the batch is
        # smaller than this. Distinct from _inflight since pipelining
        # (ISSUE 14): members of an earlier window awaiting completion
        # are in flight but NOT coming to this window — gating on them
        # would hold every window open for stragglers that can never
        # arrive.
        self._undispatched = 0
        # windows dispatched to the device and not yet completed
        self._inflight_batches = 0
        self._flight_lock = threading.Lock()
        # deadline shedding (ISSUE 3): EWMA of per-batch service time
        # feeds the queue wait bound; requests whose deadline the bound
        # already exceeds are refused at admission with 503+Retry-After
        self._service_ewma_s = 0.0
        self.n_shed = 0
        self.n_shutdown_failed = 0
        # formation blocked on the in-flight cap (ISSUE 14): the
        # backpressure signal — the device/completion side is the
        # bottleneck, not batch formation
        self.n_pipeline_stalls = 0
        self._q: "queue.Queue[_Pending]" = queue.Queue()
        self._stop = threading.Event()
        # contention probe (ISSUE 6): request threads' wait on the
        # admission lock, as pio_lock_wait_seconds{lock=batcher_inflight}
        self._lock_wait = lock_probe("batcher_inflight")
        self.wait_hist = None
        self.stage_hist = None
        if metrics is not None:
            self.wait_hist = metrics.histogram(
                "pio_engine_batch_wait_seconds",
                "Per-query time in the coalescing stage "
                "(enqueue -> dispatch)")
            # pipeline-stage decomposition (ISSUE 14): where a window's
            # wall goes — formation, device dispatch (enqueue), the
            # sit in the completion queue, and readback+post-process
            self.stage_hist = metrics.histogram(
                "pio_serve_stage_seconds",
                "Per-window wall time by pipeline stage (formation = "
                "first dequeue -> dispatch, dispatch = async enqueue, "
                "completion_wait = dispatched -> completion thread "
                "pickup, readback = blocked on the in-flight d2h copy "
                "(ops/readback wait), completion = post-process + "
                "waiter wakeup minus the readback wait)",
                labelnames=("stage",))
            # children resolved eagerly (the ISSUE 6 self-metrics
            # precedent): a quiet server scrapes zeroed stage series,
            # not an empty family
            for st in ("formation", "dispatch", "completion_wait",
                       "readback", "completion"):
                self.stage_hist.labels(stage=st)
            metrics.counter_func(
                "pio_engine_batches_total", "Micro-batch dispatches",
                lambda: self.n_batches)
            metrics.counter_func(
                "pio_engine_batched_queries_total",
                "Queries through the micro-batcher",
                lambda: self.n_queries)
            metrics.counter_func(
                "pio_engine_immediate_batches_total",
                "Dispatches that never blocked on the window",
                lambda: self.n_immediate)
            metrics.gauge_func(
                "pio_engine_max_batch_size", "Largest coalesced batch",
                lambda: self.max_batch_seen)
            metrics.counter_func(
                "pio_engine_batch_exits_total",
                "Why each dispatch closed its batch (attributes a "
                "sub-micro_batch realized batch size: drain_gate = "
                "client pool was the limit, window = straggler hold "
                "expired, full = max_batch hit, adaptive = pow2 "
                "demand target reached)",
                lambda: [({"reason": "full"}, self.n_exit_full),
                         ({"reason": "drain_gate"},
                          self.n_exit_drain_gate),
                         ({"reason": "window"}, self.n_exit_window),
                         ({"reason": "adaptive"},
                          self.n_exit_adaptive)])
            metrics.gauge_func(
                "pio_engine_avg_inflight_at_dispatch",
                "Mean submitted-unanswered queries at dispatch (the "
                "effective concurrent-client count)",
                lambda: round(self.inflight_at_dispatch_sum
                              / self.n_batches, 3)
                if self.n_batches else 0.0)
            metrics.counter_func(
                "pio_engine_shed_total",
                "Queries refused at admission because the queue wait "
                "bound exceeded their deadline (503 + Retry-After)",
                lambda: self.n_shed)
            metrics.gauge_func(
                "pio_engine_queue_wait_bound_seconds",
                "Current admission-time wait bound (queue depth x EWMA "
                "batch service time + window)",
                lambda: self.queue_wait_bound_s())
            metrics.gauge_func(
                "pio_serve_inflight_batches",
                "Windows dispatched to the device and not yet "
                "completed (bounded by PIO_SERVE_INFLIGHT)",
                lambda: self._inflight_batches)
            metrics.counter_func(
                "pio_serve_pipeline_stalls_total",
                "Formation blocked on the in-flight window cap "
                "(backpressure: device/completion is the bottleneck)",
                lambda: self.n_pipeline_stalls)
        # pipelined executor threads (ISSUE 14): formation forms +
        # dispatches; completion reads back + wakes waiters. The
        # semaphore caps dispatched-not-completed windows.
        self._inflight_sem = threading.Semaphore(self.inflight)
        self._completions: "queue.Queue[Optional[_InFlight]]" = \
            queue.Queue()
        self._completion_thread = None
        if self.pipelined:
            self._completion_thread = threading.Thread(
                target=self._completion_loop, daemon=True,
                name="pio-serve-completion")
            self._completion_thread.start()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stats(self) -> dict:
        # the counters are updated together by the dispatch thread just
        # before each process_batch call; snapshotting queries BEFORE
        # batches keeps the derived average internally consistent
        # (avg <= max_batch) even when a batch lands mid-read
        nq = self.n_queries
        nb = self.n_batches
        mx = self.max_batch_seen
        return {"batches": nb, "batchedQueries": nq,
                "avgBatchSize": (nq / nb if nb else 0.0),
                "maxBatchSize": mx,
                "immediateBatches": self.n_immediate,
                "exitFullBatch": self.n_exit_full,
                "exitDrainGate": self.n_exit_drain_gate,
                "exitWindow": self.n_exit_window,
                "exitAdaptive": self.n_exit_adaptive,
                "shedQueries": self.n_shed,
                "queueWaitBoundSec": self.queue_wait_bound_s(),
                "pipelined": self.pipelined,
                "inflightWindows": self.inflight,
                "inflightBatches": self._inflight_batches,
                "pipelineStalls": self.n_pipeline_stalls,
                "avgInflightAtDispatch": (
                    self.inflight_at_dispatch_sum / nb if nb else 0.0)}

    def queue_wait_bound_s(self) -> float:
        """Upper bound on how long a query enqueued NOW waits before its
        batch dispatches: the window(s) currently on the device plus
        every queued batch ahead of it costs one EWMA service time
        each, plus one coalescing window. An idle batcher returns 0 —
        the drain gate dispatches a lone query immediately, so nothing
        with a deadline is ever shed at zero load. This is the
        admission-control signal AND the Retry-After value on sheds —
        the server's honest estimate, not a constant. With pipelining
        the in-flight windows overlap, so this stays an upper bound."""
        depth = self._q.qsize()
        if self.pipelined:
            busy = self._inflight_batches
        else:
            # inflight > queued means a dispatched batch occupies the
            # device
            busy = 1 if self._inflight > depth else 0
        batches_ahead = (depth + self.max_batch - 1) // self.max_batch \
            + busy
        if batches_ahead == 0:
            return 0.0
        ewma = self._service_ewma_s
        if self.pipelined:
            # the EWMA measures dispatch -> completion, which at
            # steady saturation already INCLUDES the wait behind the
            # other in-flight windows (~inflight x device time);
            # charging every window ahead the full EWMA would
            # double-count the overlap and shed ~2x too eagerly
            ewma /= max(self.inflight, 1)
        return batches_ahead * ewma + self.max_wait_s

    def submit(self, query, deadline_s: Optional[float] = None) -> Any:
        """Blocking: enqueue and wait for the batched result.

        ``deadline_s``: the request's remaining deadline budget
        (propagated from HTTP ingress). When the queue's wait bound
        already exceeds it the query is shed at admission with
        ``ShedError`` (503 + Retry-After) — wasted-work protection
        under saturation while in-deadline queries still answer."""
        from predictionio_tpu.obs import TRACER
        if deadline_s is not None:
            bound = self.queue_wait_bound_s()
            if bound > deadline_s:
                self.n_shed += 1
                from predictionio_tpu.obs.flight import FLIGHT
                FLIGHT.record("shed", coalesce_s=1.0,
                              waitBoundS=round(bound, 4),
                              deadlineS=round(deadline_s, 4))
                raise ShedError(bound, deadline_s)
        p = _Pending(query)
        p.trace_id = TRACER.current_trace_id()
        with timed_acquire(self._flight_lock, self._lock_wait):
            # check-and-enqueue is atomic with stop()'s set-and-sweep
            # (both under _flight_lock), so no submitter can slip a
            # pending item in after the shutdown sweep ran
            if self._stop.is_set():
                raise ShutdownError("micro-batcher is shut down")
            self._inflight += 1
            self._undispatched += 1
            self._q.put(p)
        with TRACER.span("batch_wait"):
            p.event.wait()
        if p.batch_trace_id is not None:
            # tie this query's ingress trace to the coalesced window
            # that answered it (the dispatch loop recorded the reverse
            # link before waking us)
            cur = TRACER.current_trace()
            if cur is not None:
                cur.link(p.batch_trace_id)
        if p.error is not None:
            raise p.error
        return p.result

    # -- adaptive sizing (ISSUE 14) -----------------------------------------
    def _occupancy(self) -> float:
        try:
            from predictionio_tpu.obs import costmon
            return costmon.occupancy()
        except Exception:
            return 0.0

    def _target_batch(self) -> int:
        """The pow2-snapped batch target for this window: cover the
        known demand (undispatched + queued), and when the device
        occupancy EWMA says the device is busy, aim one bucket higher
        (fuller windows cut per-dispatch overhead exactly when
        dispatches are the contended resource). Always a pow2 <=
        max_batch — the same buckets the AOT warm ladder compiled, so
        adaptation can never trigger a compile."""
        from predictionio_tpu.compile.buckets import bucket_batch
        demand = min(max(self._undispatched, self._q.qsize() + 1),
                     self.max_batch)
        if self._occupancy() >= self._adapt_occ:
            demand = min(demand * 2, self.max_batch)
        return min(bucket_batch(max(demand, 1)), self.max_batch)

    def _window_deadline(self, t_first: float, first: _Pending) -> float:
        """The straggler-hold deadline for one window. Adaptive mode
        scales the base window with device pressure: an idle device
        holds briefly (latency matters, batches add little), a busy or
        backlogged one may hold the full window (throughput matters).
        The latency budget still caps the oldest query's stage time."""
        window_s = self.max_wait_s
        if self.adaptive:
            depth = self._q.qsize()
            scale = min(1.0, 0.25 + self._occupancy()
                        + depth / max(self.max_batch, 1))
            window_s = self.max_wait_s * scale
        deadline = t_first + window_s
        if self.latency_budget_s is not None:
            # cap the oldest query's time in the coalescing stage
            deadline = min(deadline,
                           first.t_enqueue + self.latency_budget_s)
        return deadline

    # -- formation loop ------------------------------------------------------
    def _enter_tenant(self):
        """Pin this thread's context to the batcher's tenant. The
        formation/completion threads serve exactly one tenant for
        their whole lifetime, so a one-shot contextvar set (no scope
        exit) is correct and free on the per-batch path."""
        if self.tenant is not None:
            from predictionio_tpu.obs.tenantctx import _tenant_var
            _tenant_var.set(self.tenant)

    def _loop(self):
        self._enter_tenant()
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            t_first = time.perf_counter()   # batch-formation stage t0
            batch = [first]
            # Drain-first batching: take the backlog that accumulated
            # while the previous batch was on the device (the
            # self-regulating coalescing), then hold the door open ONLY
            # while more queries are known in flight (submitted,
            # unanswered, not yet dispatched, not in this batch) —
            # i.e. between their counter increment and queue put,
            # microseconds away. When batch == undispatched nobody else
            # is known to be coming: a closed-loop serial client, or an
            # idle server, dispatches with zero window cost. The
            # (adaptive) window bounds the hold in case a counted
            # straggler stalls before reaching the queue; the adaptive
            # target dispatches at a pow2 boundary once demand is
            # covered.
            held = False
            exit_reason = "full"   # loop falls through => max_batch hit
            deadline = self._window_deadline(t_first, first)
            target = self._target_batch() if self.adaptive \
                else self.max_batch
            while len(batch) < self.max_batch:
                try:
                    batch.append(self._q.get_nowait())
                    continue
                except queue.Empty:
                    pass
                if self._undispatched <= len(batch):
                    exit_reason = "drain_gate"
                    break          # nobody else known in flight
                if self.adaptive and len(batch) >= target:
                    exit_reason = "adaptive"
                    break          # demand target (pow2) covered
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    exit_reason = "window"
                    break
                held = True
                try:
                    batch.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    exit_reason = "window"
                    break
            self.n_batches += 1
            self.n_queries += len(batch)
            self.max_batch_seen = max(self.max_batch_seen, len(batch))
            self.inflight_at_dispatch_sum += self._inflight
            if exit_reason == "full":
                self.n_exit_full += 1
            elif exit_reason == "drain_gate":
                self.n_exit_drain_gate += 1
            elif exit_reason == "adaptive":
                self.n_exit_adaptive += 1
            else:
                self.n_exit_window += 1
            if not held:
                self.n_immediate += 1
            with self._flight_lock:
                # members of this batch are no longer awaiting dispatch
                # (they await COMPLETION — _inflight still counts them)
                self._undispatched -= len(batch)
            if self._stop.is_set():
                # stop landed while this batch was collecting: fail its
                # members explicitly rather than racing a device call
                # against interpreter teardown
                with self._flight_lock:
                    self._inflight -= len(batch)
                for p in batch:
                    self.n_shutdown_failed += 1
                    p.error = ShutdownError()
                    p.event.set()
                continue
            t_dispatch = time.perf_counter()
            if self.wait_hist is not None:
                for p in batch:
                    self.wait_hist.observe(t_dispatch - p.t_enqueue)
            if self.stage_hist is not None:
                self.stage_hist.labels(stage="formation").observe(
                    t_dispatch - t_first)
            if self.pipelined:
                self._dispatch_pipelined(batch, t_first, t_dispatch)
                continue
            try:
                results = self._run_batch(
                    batch, formation_s=t_dispatch - t_first)
                if len(results) != len(batch):
                    raise RuntimeError(
                        f"batch handler returned {len(results)} results "
                        f"for {len(batch)} queries")
                with self._flight_lock:
                    self._inflight -= len(batch)
                for p, r in zip(batch, results):
                    p.result = r
                    p.event.set()
            except BaseException as e:  # propagate to every waiter
                with self._flight_lock:
                    self._inflight -= len(batch)
                for p in batch:
                    p.error = e
                    p.event.set()
            # EWMA of batch service time: the queue wait bound's basis.
            # Updated on the dispatch thread only; alpha 0.2 smooths
            # device-warmup spikes without lagging a real slowdown.
            self._note_service_time(time.perf_counter() - t_dispatch)

    def _note_service_time(self, dt: float):
        self._service_ewma_s = (dt if self._service_ewma_s == 0.0
                                else 0.8 * self._service_ewma_s
                                + 0.2 * dt)

    def _fail_batch(self, batch, err: BaseException):
        with self._flight_lock:
            self._inflight -= len(batch)
        for p in batch:
            p.error = err
            p.event.set()

    # -- pipelined dispatch/completion (ISSUE 14) ----------------------------
    def _dispatch_pipelined(self, batch, t_first: float,
                            t_dispatch: float):
        """Stage 1 tail: enqueue the window's device call via
        ``process_batch_begin`` and hand the deferred finish() to the
        completion thread. Blocks on the in-flight semaphore first —
        at most ``inflight`` windows sit between dispatch and
        completion (backpressure onto formation, and transitively onto
        the admission queue + shed bound)."""
        from predictionio_tpu.obs import TRACER
        if not self._inflight_sem.acquire(blocking=False):
            # the device/completion side is the bottleneck right now:
            # count the stall once, then wait (poll so stop() can't be
            # held hostage by a wedged completion)
            self.n_pipeline_stalls += 1
            while not self._inflight_sem.acquire(timeout=0.1):
                if self._stop.is_set():
                    self.n_shutdown_failed += len(batch)
                    self._fail_batch(batch, ShutdownError())
                    return
        member_traces = [p.trace_id for p in batch if p.trace_id]
        bt = None
        if member_traces:
            bt = TRACER.begin_trace(
                "batch_predict", batch=len(batch),
                formationMs=round((t_dispatch - t_first) * 1000.0, 3),
                pipelined=True)
            for tid in member_traces:
                bt.link(tid)
            for p in batch:
                p.batch_trace_id = bt.trace_id
        try:
            queries = [p.query for p in batch]
            if bt is not None:
                with TRACER.resume(bt):
                    finish = self.process_batch_begin(queries)
            else:
                finish = self.process_batch_begin(queries)
        except BaseException as e:
            self._inflight_sem.release()
            if bt is not None:
                # commit the failed window's trace so ?trace_id=
                # resolves it from the members' links
                with self._note_exc(bt):
                    pass
            self._fail_batch(batch, e)
            self._note_service_time(time.perf_counter() - t_dispatch)
            return
        if self.stage_hist is not None:
            self.stage_hist.labels(stage="dispatch").observe(
                time.perf_counter() - t_dispatch)
        with self._flight_lock:
            self._inflight_batches += 1
        self._completions.put(_InFlight(batch, finish, bt, t_dispatch))

    def _note_exc(self, bt):
        """Commit an open batch trace from an error path."""
        from predictionio_tpu.obs import TRACER
        return TRACER.resume(bt, commit=True)

    def _completion_loop(self):
        self._enter_tenant()
        while True:
            item = self._completions.get()
            if item is None:        # stop() sentinel
                break
            self._finish_one(item)

    def _finish_one(self, item: _InFlight):
        """Stage 2: the deferred readback + post-process for one
        window, result fan-out, in-flight bookkeeping. Runs on the
        dedicated completion thread — overlapping the formation
        thread's next window and the device's current one."""
        from predictionio_tpu.obs import TRACER
        from predictionio_tpu.ops import readback as _readback
        batch, finish, bt = item.batch, item.finish, item.trace
        t_c0 = time.perf_counter()
        wait_s = t_c0 - item.t_ready
        # readback decomposition (ISSUE 19): finish() internally waits
        # on the window's already-in-flight d2h copy through the
        # ops/readback plane; sampling this thread's cumulative wait
        # around the call splits completion into wait-for-copy vs
        # post-process without this module touching a device handle
        # (the JAX006 contract)
        rb0 = _readback.thread_wait_s()
        try:
            if bt is not None:
                bt.root.attrs["completionWaitMs"] = round(
                    wait_s * 1000.0, 3)
                with TRACER.resume(bt, commit=True):
                    results = finish()
            else:
                results = finish()
            if len(results) != len(batch):
                raise RuntimeError(
                    f"batch handler returned {len(results)} results "
                    f"for {len(batch)} queries")
        except BaseException as e:
            self._inflight_sem.release()
            with self._flight_lock:
                self._inflight_batches -= 1
            self._fail_batch(batch, e)
            self._note_service_time(time.perf_counter()
                                    - item.t_dispatch)
            return
        self._inflight_sem.release()
        with self._flight_lock:
            self._inflight -= len(batch)
            self._inflight_batches -= 1
        for p, r in zip(batch, results):
            p.result = r
            p.event.set()
        if self.stage_hist is not None:
            rb_s = max(0.0, _readback.thread_wait_s() - rb0)
            total_s = time.perf_counter() - t_c0
            self.stage_hist.labels(stage="completion_wait").observe(
                wait_s)
            self.stage_hist.labels(stage="readback").observe(rb_s)
            self.stage_hist.labels(stage="completion").observe(
                max(0.0, total_s - rb_s))
        self._note_service_time(time.perf_counter() - item.t_dispatch)

    def _run_batch(self, batch, formation_s: float = 0.0):
        """One synchronous dispatch (non-pipelined mode). When any
        member carries an ingress trace, the device call runs under its
        own batch_predict trace linked both ways — the dispatch thread
        has no request context, so the link set is how /traces.json
        ties a query to its window. ``formation_s`` (first dequeue ->
        dispatch) rides the trace as the slow-query waterfall's
        batch_formation stage."""
        member_traces = [p.trace_id for p in batch if p.trace_id]
        if not member_traces:
            return self.process_batch([p.query for p in batch])
        from predictionio_tpu.obs import TRACER
        with TRACER.trace("batch_predict", batch=len(batch),
                          formationMs=round(formation_s * 1000.0, 3)
                          ) as bt:
            for tid in member_traces:
                bt.link(tid)
            for p in batch:
                p.batch_trace_id = bt.trace_id
            return self.process_batch([p.query for p in batch])

    def stop(self, join_timeout_s: float = 10.0):
        """Drain-on-stop: the dispatch thread is given time to finish
        the batch on the device (pipelined mode: the completion thread
        finishes every already-dispatched window — its device work is
        enqueued, the readback completes it), then every request still
        queued (or collected but not yet dispatched) fails with an
        explicit "server shutting down" 503 — no future ever hangs.
        Atomic with submit()'s check-and-enqueue via _flight_lock, so
        nothing can enqueue after the sweep."""
        self._stop.set()
        self._thread.join(timeout=join_timeout_s)
        if self._thread.is_alive():
            logger.warning(
                "micro-batcher dispatch thread still busy after %.1fs; "
                "sweeping the queue anyway", join_timeout_s)
        if self._completion_thread is not None:
            # sentinel AFTER the formation thread stopped enqueuing:
            # every already-dispatched window completes first, in order
            self._completions.put(None)
            self._completion_thread.join(timeout=join_timeout_s)
            if self._completion_thread.is_alive():
                logger.warning(
                    "completion thread still busy after %.1fs; failing "
                    "its undelivered windows", join_timeout_s)
            # a wedged (or sentinel-raced) completion queue: fail any
            # window still undelivered so no waiter hangs forever
            while True:
                try:
                    item = self._completions.get_nowait()
                except queue.Empty:
                    break
                if item is None:
                    continue
                with self._flight_lock:
                    self._inflight_batches -= 1
                self.n_shutdown_failed += len(item.batch)
                self._fail_batch(item.batch, ShutdownError())
        with self._flight_lock:
            while True:
                try:
                    p = self._q.get_nowait()
                except queue.Empty:
                    break
                self._inflight -= 1
                self._undispatched -= 1
                self.n_shutdown_failed += 1
                p.error = ShutdownError()
                p.event.set()
