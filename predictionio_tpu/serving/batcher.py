"""Micro-batching for the query path (beyond-parity).

The reference serves queries one at a time per request thread
(CreateServer.scala:515 "TODO: Parallelize"). On a TPU the per-call
dispatch + device->host fetch dominates single-query latency, so under
concurrent load the server can coalesce queries that arrive within a short
window into ONE batched device call (Algorithm.batch_predict) and fan the
results back out — the standard accelerator-serving pattern.

Opt-in via ServerConfig.micro_batch > 1. Every dispatch holds the door
open for up to `max_wait_ms` (default 2 ms) so requests still mid-flight
through HTTP parsing join the current batch — an isolated query
therefore pays up to max_wait extra latency (microscopic next to one
device round trip), and concurrent load coalesces into full batches
instead of fragments.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Any, Optional

logger = logging.getLogger(__name__)


class _Pending:
    __slots__ = ("query", "event", "result", "error")

    def __init__(self, query):
        self.query = query
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class MicroBatcher:
    def __init__(self, process_batch, max_batch: int = 32,
                 max_wait_ms: float = 2.0):
        """process_batch: fn(List[query]) -> List[result]."""
        self.process_batch = process_batch
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        # realized coalescing telemetry (read via /stats.json): whether
        # concurrent load actually forms full batches is THE datum for
        # tuning micro_batch_wait_ms on a given link
        self.n_batches = 0
        self.n_queries = 0
        self.max_batch_seen = 0
        self._q: "queue.Queue[_Pending]" = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stats(self) -> dict:
        # the counters are updated together by the dispatch thread just
        # before each process_batch call; snapshotting queries BEFORE
        # batches keeps the derived average internally consistent
        # (avg <= max_batch) even when a batch lands mid-read
        nq = self.n_queries
        nb = self.n_batches
        mx = self.max_batch_seen
        return {"batches": nb, "batchedQueries": nq,
                "avgBatchSize": (nq / nb if nb else 0.0),
                "maxBatchSize": mx}

    def submit(self, query) -> Any:
        """Blocking: enqueue and wait for the batched result."""
        p = _Pending(query)
        self._q.put(p)
        p.event.wait()
        if p.error is not None:
            raise p.error
        return p.result

    def _loop(self):
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            # adaptive batching: drain the backlog that accumulated while
            # the previous batch was on the device, then hold the door
            # open for at most max_wait so requests mid-flight through
            # HTTP parsing (threads arrive staggered under the GIL) join
            # this batch instead of forming a tiny next one. The window
            # is a few ms — noise next to one device round trip — and it
            # is what turns 16 concurrent clients into batches of ~16
            # rather than ~4.
            import time
            deadline = time.perf_counter() + self.max_wait_s
            while len(batch) < self.max_batch:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    try:
                        batch.append(self._q.get(timeout=remaining))
                    except queue.Empty:
                        break
            self.n_batches += 1
            self.n_queries += len(batch)
            self.max_batch_seen = max(self.max_batch_seen, len(batch))
            try:
                results = self.process_batch([p.query for p in batch])
                if len(results) != len(batch):
                    raise RuntimeError(
                        f"batch handler returned {len(results)} results "
                        f"for {len(batch)} queries")
                for p, r in zip(batch, results):
                    p.result = r
                    p.event.set()
            except BaseException as e:  # propagate to every waiter
                for p in batch:
                    p.error = e
                    p.event.set()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2)
